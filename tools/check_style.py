#!/usr/bin/env python
"""In-tree style checker — the role of the reference's gst-indent /
pre-commit hooks (tools/development/, SURVEY.md §2.5), self-contained so it
runs with no network or extra deps.

Rules for tracked .py files (and the C++ under native/):
- no tabs, no trailing whitespace, LF line endings, final newline
- max line length 100 (the repo style; docstring URLs exempt)
- no merge-conflict markers

Usage: python tools/check_style.py [paths...]   (default: repo tree)
Exit 0 clean, 1 with findings listed one per line.
"""

from __future__ import annotations

import os
import re
import sys

MAX_LEN = 100
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "golden", "build",
              "dist", ".eggs"}
_EXTS = (".py", ".cpp", ".cc", ".h", ".hpp", ".proto", ".toml")
_CONFLICT = re.compile(r"^(<{7}|={7}|>{7})( |$)")
_GENERATED = ("_pb2.py", "_pb2_grpc.py")
_URL = re.compile(r"https?://\S+")


def check_file(path: str) -> list:
    problems = []
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as exc:
        return [f"{path}: unreadable: {exc}"]
    if b"\r\n" in blob:
        problems.append(f"{path}: CRLF line endings")
    if blob and not blob.endswith(b"\n"):
        problems.append(f"{path}: missing final newline")
    text = blob.decode("utf-8", errors="replace")
    for i, line in enumerate(text.split("\n"), 1):
        if "\t" in line:
            problems.append(f"{path}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if len(line) > MAX_LEN and not _URL.search(line):
            problems.append(f"{path}:{i}: line longer than {MAX_LEN} "
                            f"({len(line)})")
        if _CONFLICT.match(line):
            problems.append(f"{path}:{i}: merge conflict marker")
    return problems


def iter_files(roots):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(_EXTS) and not fn.endswith(_GENERATED):
                    yield os.path.join(dirpath, fn)


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:]) or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    problems = []
    for path in iter_files(args):
        problems.extend(check_file(path))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} style problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
