#!/usr/bin/env python
"""In-tree style checker — the role of the reference's gst-indent /
pre-commit hooks (tools/development/, SURVEY.md §2.5), self-contained so it
runs with no network or extra deps.

Rules for tracked .py files (and the C++ under native/):
- no tabs, no trailing whitespace, LF line endings, final newline
- max line length 100 (the repo style; docstring URLs exempt)
- no merge-conflict markers
- `nns-lint --self-check` passes: every registered builtin element's
  PROPERTIES schema covers the properties its code reads (whole-tree
  runs only — explicit path args stay stdlib-fast; --no-self-check
  forces it off entirely)
- `nns-san --race nnstreamer_tpu/` is clean: the package source obeys
  its own concurrency idioms (same whole-tree-only gating)
- `nns-xray --self-check` passes (chain diagnostics W120-W125 wired
  emitters<->catalog<->docs both ways) and every pipeline string in
  examples/ and docs/ xrays clean of the chain diagnostics (same
  whole-tree-only gating)
- `nns-kscope --self-check` wiring passes (kernel diagnostics
  W127-W129 wired emitters<->catalog<->docs, pallas registry complete
  against the package and dispatch.KNOWN_OPS; the interpret-mode
  parity sweep stays in the test suite, not here)

Usage: python tools/check_style.py [paths...]   (default: repo tree)
Exit 0 clean, 1 with findings listed one per line.
"""

from __future__ import annotations

import os
import re
import sys

MAX_LEN = 100
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "golden", "build",
              "dist", ".eggs"}
_EXTS = (".py", ".cpp", ".cc", ".h", ".hpp", ".proto", ".toml")
_CONFLICT = re.compile(r"^(<{7}|={7}|>{7})( |$)")
_GENERATED = ("_pb2.py", "_pb2_grpc.py")
_URL = re.compile(r"https?://\S+")


def check_file(path: str) -> list:
    problems = []
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as exc:
        return [f"{path}: unreadable: {exc}"]
    if b"\r\n" in blob:
        problems.append(f"{path}: CRLF line endings")
    if blob and not blob.endswith(b"\n"):
        problems.append(f"{path}: missing final newline")
    text = blob.decode("utf-8", errors="replace")
    for i, line in enumerate(text.split("\n"), 1):
        if "\t" in line:
            problems.append(f"{path}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if len(line) > MAX_LEN and not _URL.search(line):
            problems.append(f"{path}:{i}: line longer than {MAX_LEN} "
                            f"({len(line)})")
        if _CONFLICT.match(line):
            problems.append(f"{path}:{i}: merge conflict marker")
    return problems


def iter_files(roots):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(_EXTS) and not fn.endswith(_GENERATED):
                    yield os.path.join(dirpath, fn)


def run_self_check() -> list:
    """Run nns-lint --self-check in-process: schema gaps are style
    problems (an element property without a PROPERTIES entry is invisible
    to gst-inspect-style tooling and to the static analyzer)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.selfcheck import self_check
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-lint --self-check could not run: {exc}"]
    return [f"self-check: {p}" for p in self_check()]


def run_obs_self_check() -> list:
    """Run the nns-obs metric-catalog self-check in-process: a metric
    emitted but uncataloged (or cataloged but undocumented) is invisible
    to dashboards and to docs/observability.md readers."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.selfcheck import obs_self_check
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"obs self-check could not run: {exc}"]
    return [f"obs: {p}" for p in obs_self_check()]


def run_race_lint_gate() -> list:
    """Run nns-san --race over the package in-process: a concurrency-
    idiom violation (unlocked shared counter, silent service-loop
    swallow, broken _Chan pairing, ...) is a style problem from now on."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.racecheck import run_race_lint
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-san --race could not run: {exc}"]
    report = run_race_lint([os.path.join(repo, "nnstreamer_tpu")])
    return [f"race: {d}" for d in report.diagnostics]


def run_xray_self_check() -> list:
    """Run nns-xray --self-check in-process: a chain diagnostic
    (NNS-W120..W125) missing from the catalog, without an emitter, or
    undocumented in docs/chain-analysis.md + docs/linting.md is a style
    problem — as is a doc mentioning a code that doesn't exist."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.selfcheck import xray_self_check
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-xray --self-check could not run: {exc}"]
    return [f"xray: {p}" for p in xray_self_check()]


def run_kscope_self_check() -> list:
    """Run nns-kscope's wiring self-check in-process: a kernel
    diagnostic (NNS-W127..W129) missing from the catalog, without an
    emitter, or undocumented in docs/kernel-analysis.md +
    docs/linting.md is a style problem — as is a public ops/pallas
    kernel without a registered KernelSpec, or a dispatch op outside
    the registry's coverage."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.selfcheck import kscope_self_check
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-kscope --self-check could not run: {exc}"]
    return [f"kscope: {p}" for p in kscope_self_check()]


def run_disagg_self_check() -> list:
    """Run nns-disagg's wiring self-check in-process: the disagg lint
    code (NNS-W130) missing from the catalog, without an emitter, or
    undocumented in docs/linting.md + docs/llm-serving.md is a style
    problem — as is either disagg metric missing from METRIC_CATALOG
    or without a live emitter."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.selfcheck import disagg_self_check
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-disagg --self-check could not run: {exc}"]
    return [f"disagg: {p}" for p in disagg_self_check()]


def documented_pipeline_strings() -> list:
    """(source, description) for every pipeline launch string embedded
    in examples/*.py and docs/*.md — double-quoted launch strings plus
    paragraph-joined blocks, validated by the real tokenizer (the same
    heuristic as the tests' lint-clean sweep)."""
    import ast

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from nnstreamer_tpu.pipeline.parse import ParseError, scan_description

    def pipelineish(text):
        if " ! " not in text:
            return False
        try:
            items = scan_description(text)
        except (ParseError, ValueError):
            return False
        n_elems = sum(1 for it in items if it[0] in ("element", "caps"))
        return n_elems >= 2 and any(it[0] == "bang" for it in items)

    def candidates(text):
        seen = set()
        flat = " ".join(ln.strip().rstrip("\\").strip()
                        for ln in text.splitlines())
        for m in re.finditer(r'"([^"]+ ! [^"]+)"', flat):
            cand = m.group(1).strip()
            if cand not in seen and pipelineish(cand):
                seen.add(cand)
                yield cand
        for para in re.split(r"\n\s*\n", text):
            joined = " ".join(ln.strip().rstrip("\\").strip()
                              for ln in para.strip().splitlines())
            joined = joined.strip().strip('"').replace('\\"', '"')
            if joined not in seen and pipelineish(joined):
                seen.add(joined)
                yield joined

    found = []
    ex_dir = os.path.join(repo, "examples")
    for fn in sorted(os.listdir(ex_dir)):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(ex_dir, fn)) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for cand in candidates(node.value):
                    found.append((fn, cand))
    doc_dir = os.path.join(repo, "docs")
    for fn in sorted(os.listdir(doc_dir)):
        if not fn.endswith(".md"):
            continue
        with open(os.path.join(doc_dir, fn)) as f:
            for cand in candidates(f.read()):
                found.append((fn, cand))
    return found


def run_xray_docs_gate() -> list:
    """Every pipeline a doc or example shows must xray CLEAN of the
    chain diagnostics: a documented launch string firing W120-W125
    is either a bad example or a false positive — both are gate
    failures (acceptance: zero false chain findings on shipped
    snippets). Unanalyzable pipelines degrade to notes and pass."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from nnstreamer_tpu.analysis.xray import xray
    except Exception as exc:  # pragma: no cover - broken tree
        return [f"nns-xray docs gate could not run: {exc}"]
    chain_codes = {f"NNS-W12{i}" for i in range(6)}
    problems = []
    for src, desc in documented_pipeline_strings():
        result = xray(desc)
        for d in result.diagnostics:
            if d.code in chain_codes:
                problems.append(
                    f"xray-docs: {src}: {desc[:60]!r}: {d.code} "
                    f"[{d.element}]"
                )
    return problems


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    no_self_check = "--no-self-check" in args
    args = [a for a in args if a != "--no-self-check"]
    # explicit path args = quick per-file run: stay stdlib-only; the
    # package-importing self-check rides the whole-tree (gate) run
    whole_tree = not args
    args = args or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    problems = []
    for path in iter_files(args):
        problems.extend(check_file(path))
    if whole_tree and not no_self_check:
        problems.extend(run_self_check())
        problems.extend(run_obs_self_check())
        problems.extend(run_race_lint_gate())
        problems.extend(run_xray_self_check())
        problems.extend(run_kscope_self_check())
        problems.extend(run_disagg_self_check())
        problems.extend(run_xray_docs_gate())
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} style problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
