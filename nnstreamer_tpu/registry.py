"""Subplugin registry: name → implementation per subplugin kind.

Reference: gst/nnstreamer/nnstreamer_subplugin.{c,h} — a per-type name→vtable
registry (register_subplugin :80 / get_subplugin :61) with lazy dlopen of
``libnnstreamer_{filter,decoder,converter}_NAME.so`` from configured search
paths (nnstreamer_subplugin.c:138-166).

TPU-native equivalents of the lazy-load paths, tried in order on a miss:
1. built-in modules (imported on demand from ``nnstreamer_tpu.backends`` /
   ``.decoders`` / ``.converters`` / ``.elements``),
2. Python entry points (group ``nnstreamer_tpu.<kind>``),
3. ``*.py`` files named ``nns_<kind>_<name>.py`` on the config search paths,
   executed and expected to call :func:`register`.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from nnstreamer_tpu.config import conf
from nnstreamer_tpu.log import get_logger

_log = get_logger("registry")

# Subplugin kinds (reference enum nnstreamer_subplugin.h:40-50)
KIND_FILTER = "filter"
KIND_DECODER = "decoder"
KIND_CONVERTER = "converter"
KIND_ELEMENT = "element"
KINDS = (KIND_FILTER, KIND_DECODER, KIND_CONVERTER, KIND_ELEMENT)

# Built-in lazy import table: kind → module that registers its members on
# import. Split per kind so importing the filter registry does not pull in
# decoder deps, mirroring one-.so-per-subplugin in the reference.
_BUILTIN_MODULES: Dict[str, List[str]] = {
    KIND_FILTER: ["nnstreamer_tpu.backends"],
    KIND_DECODER: ["nnstreamer_tpu.decoders"],
    KIND_CONVERTER: ["nnstreamer_tpu.converters"],
    KIND_ELEMENT: ["nnstreamer_tpu.elements"],
}

_lock = threading.RLock()
_registry: Dict[str, Dict[str, Any]] = {k: {} for k in KINDS}
_builtins_loaded: Dict[str, bool] = {k: False for k in KINDS}


def register(kind: str, name: str, impl: Any, *, replace: bool = False) -> Any:
    """register_subplugin analogue. Returns impl so it works as a decorator
    helper. Double registration is an error unless replace=True."""
    if kind not in KINDS:
        raise ValueError(f"unknown subplugin kind {kind!r}")
    name = name.lower()
    with _lock:
        if name in _registry[kind] and not replace:
            existing = _registry[kind][name]
            if existing is impl:
                return impl
            raise ValueError(f"{kind} subplugin {name!r} already registered")
        _registry[kind][name] = impl
    return impl


def unregister(kind: str, name: str) -> bool:
    with _lock:
        return _registry[kind].pop(name.lower(), None) is not None


def _load_builtins(kind: str) -> None:
    if _builtins_loaded[kind]:
        return
    _builtins_loaded[kind] = True
    for mod in _BUILTIN_MODULES.get(kind, []):
        try:
            importlib.import_module(mod)
        except ImportError as exc:  # pragma: no cover - missing optional dep
            _log.warning("builtin subplugin module %s failed to import: %s", mod, exc)


def _load_entry_points(kind: str, name: str) -> bool:
    try:
        from importlib.metadata import entry_points

        eps = entry_points(group=f"nnstreamer_tpu.{kind}")
    except Exception:  # pragma: no cover
        return False
    for ep in eps:
        if ep.name.lower() == name:
            impl = ep.load()
            register(kind, name, impl, replace=True)
            return True
    return False


def _load_from_search_paths(kind: str, name: str) -> bool:
    """Reference nnsconf_get_fullpath + dlopen, for python plugin files."""
    fname = f"nns_{kind}_{name}.py"
    for path in conf().plugin_paths(kind):
        full = os.path.join(path, fname)
        if os.path.isfile(full):
            spec = importlib.util.spec_from_file_location(
                f"nns_tpu_plugin_{kind}_{name}", full
            )
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)  # plugin calls register() on import
            return name in _registry[kind]
    return False


def _resolve(kind: str, name: str) -> Any:
    """Lazy-loading lookup, ignoring the element restriction whitelist."""
    with _lock:
        if name not in _registry[kind]:
            _load_builtins(kind)
        if name not in _registry[kind]:
            if not _load_entry_points(kind, name):
                _load_from_search_paths(kind, name)
        if name not in _registry[kind]:
            raise KeyError(
                f"no {kind} subplugin named {name!r}; known: {sorted(_registry[kind])}"
            )
        return _registry[kind][name]


def exists(kind: str, name: str, *, builtin_only: bool = False) -> bool:
    """True if the subplugin resolves (restriction whitelist NOT applied) —
    the static analyzer's resource checks use this.

    builtin_only=True probes builtins/already-registered names WITHOUT
    entry-point or search-path plugin loading — the only safe probe for a
    name the restriction whitelist blocks (loading would execute code
    the whitelist exists to keep out)."""
    name = name.lower()
    if builtin_only:
        with _lock:
            _load_builtins(kind)
            return name in _registry[kind]
    try:
        _resolve(kind, name)
        return True
    except KeyError:
        return False


def is_restricted(kind: str, name: str) -> bool:
    """True if [common] restricted_elements is active and blocks `name`
    (regardless of whether the element exists)."""
    if kind != KIND_ELEMENT:
        return False
    allowed = conf().get_list("common", "restricted_elements")
    return bool(allowed) and name.lower() not in [a.lower() for a in allowed]


def get(kind: str, name: str) -> Any:
    """get_subplugin analogue with lazy loading; raises KeyError on miss."""
    name = name.lower()
    if kind == KIND_ELEMENT:
        # product element restriction (reference meson_options.txt:40-41
        # element-restriction whitelist): [common] restricted_elements =
        # comma list; empty = everything allowed
        from nnstreamer_tpu.config import conf

        allowed = conf().get_list("common", "restricted_elements")
        if allowed and name not in [a.lower() for a in allowed]:
            # distinguish "blocked" from "no such element" so the user
            # knows whether fixing the config would help — but probe ONLY
            # builtins/already-registered names: a restricted name must
            # never trigger entry-point or search-path plugin EXECUTION
            with _lock:
                _load_builtins(kind)
                known = name in _registry[kind]
            if not known:
                raise KeyError(
                    f"no element subplugin named {name!r} (note: "
                    f"[common] restricted_elements is active; allowed: "
                    f"{sorted(a.lower() for a in allowed)})"
                )
            raise KeyError(
                f"element {name!r} exists but is restricted by "
                f"configuration ([common] restricted_elements allows: "
                f"{sorted(a.lower() for a in allowed)})"
            )
    return _resolve(kind, name)


def available(kind: str) -> List[str]:
    with _lock:
        _load_builtins(kind)
        return sorted(_registry[kind])


def detect_filter_framework(model_path: str) -> Optional[str]:
    """framework=auto detection from model extension + priority config
    (reference tensor_filter_common.c:1155-1218)."""
    ext = os.path.splitext(model_path)[1].lstrip(".").lower()
    if not ext:
        return None
    for candidate in conf().framework_priority(ext):
        try:
            get(KIND_FILTER, candidate)
            return candidate
        except KeyError:
            continue
    return None


def filter_backend(name: str):
    """Decorator: @filter_backend("jax") on a Backend class."""

    def deco(cls):
        return register(KIND_FILTER, name, cls)

    return deco


def decoder_plugin(name: str):
    def deco(obj):
        return register(KIND_DECODER, name, obj)

    return deco


def converter_plugin(name: str):
    def deco(obj):
        return register(KIND_CONVERTER, name, obj)

    return deco


def element(name: str):
    """Decorator registering a pipeline element class under its factory name
    (the analogue of GST_PLUGIN_DEFINE + element_register,
    registerer/nnstreamer.c:88-121)."""

    def deco(cls):
        register(KIND_ELEMENT, name, cls)
        cls.FACTORY_NAME = name
        return cls

    return deco
