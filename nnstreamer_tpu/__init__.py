"""nnstreamer-tpu: a TPU-native tensor stream pipeline framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of NNStreamer
(reference: Jhuni0123/nnstreamer @ /root/reference): a typed tensor stream
type system (``other/tensors`` with static/flexible/sparse formats), a
pipeline of composable elements (converters, transforms, a pluggable
inference filter, decoders, routing/sync/aggregation/branching combinators),
a single-shot invoke API, and an among-device layer that shards pipelines
across a multi-chip TPU slice over ICI/DCN and serves external clients over
the network.

Design (TPU-first, not a port):

- Tensors are device-resident ``jax.Array``s between stages; host copies only
  at ingress/egress boundaries (unlike the reference's per-frame
  map/alloc/unmap, gst/nnstreamer/tensor_filter/tensor_filter.c:566-826).
- Spec negotiation happens once at pipeline build time (the reference's
  GstCaps negotiation, done per-pad at PAUSED), producing static shapes XLA
  can compile.
- Chains of pure-tensor elements are fused into single jitted XLA programs;
  the executor streams frames through with async dispatch-ahead.
- Multi-chip = jax.sharding.Mesh + jit shardings over ICI, replacing the
  reference's host TCP/MQTT "among-device" layer for intra-slice traffic.
"""

__version__ = "0.2.0"

from nnstreamer_tpu.tensors.spec import (  # noqa: F401
    DType,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
)
from nnstreamer_tpu.tensors.frame import Frame  # noqa: F401

__all__ = [
    "DType",
    "TensorFormat",
    "TensorSpec",
    "TensorsSpec",
    "Frame",
    "__version__",
]
