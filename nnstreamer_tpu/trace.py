"""Tracing / profiling: chrome-trace spans + device profiler integration.

The reference has no in-tree tracer — it leans on GstShark/NNShark/
HawkTracer (tools/tracing/README.md, tools/profiling/README.md; SURVEY.md
§5.1), whose common output is chrome://tracing JSON. This module brings
that capability in-tree:

- ``Tracer``: lock-protected event buffer; ``span()`` context manager and
  ``complete()`` record "X" (complete) events per element/frame,
  ``instant()`` marks points, ``counter()`` tracks gauges (queue depths).
  ``save()`` writes the Chrome Trace Event Format JSON that chrome://tracing
  / Perfetto load directly (the HawkTracer workflow, no external daemon).
- The executor records one span per frame per node when tracing is enabled
  (pipeline/executor.py Node.stat), giving the per-element timeline
  NNShark's per-element CPU/proctime view provides.
- ``device_profile()``: wraps ``jax.profiler.trace`` — the XPlane/TensorBoard
  capture for on-device (TPU) timing, the XLA-world analogue of GstShark's
  proctime tracer.

Enable via ``trace.enable()`` / ``nns-launch --trace out.json``; env knob
``NNS_TRACE`` (path) mirrors the reference's GST_DEBUG_DUMP_DOT_DIR-style
opt-in (nnstreamer_conf env > ini > default priority, SURVEY.md §5.6).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

_lock = threading.Lock()
_tracer: Optional["Tracer"] = None


class Tracer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: List[Dict] = []
        self._t0 = time.perf_counter()
        self._pid = os.getpid()

    # -- recording ---------------------------------------------------------
    def _ts_us(self, t: Optional[float] = None) -> float:
        return ((t if t is not None else time.perf_counter()) - self._t0) * 1e6

    def complete(
        self, name: str, cat: str, t_start: float, dur_s: float, args: Optional[Dict] = None
    ) -> None:
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": self._ts_us(t_start),
            "dur": dur_s * 1e6,
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "element", **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.complete(name, cat, t0, time.perf_counter() - t0, args or None)

    def batch(
        self, name: str, t_start: float, dur_s: float, *, batch: int,
        bucket: int, wait_s: float, **extra
    ) -> None:
        """Batch-assembly span (micro-batching, pipeline/batching.py):
        one "X" event per batched invoke carrying the batch size, the
        padded bucket it dispatched as, the pad waste that padding cost,
        and how long the collector waited for stragglers — the three
        numbers that explain where batched throughput (or latency) went."""
        waste = 100.0 * (bucket - batch) / bucket if bucket else 0.0
        self.complete(
            name, "batch", t_start, dur_s,
            {
                "batch": batch,
                "bucket": bucket,
                "wait_ms": round(wait_s * 1000.0, 3),
                "pad_waste_pct": round(waste, 2),
                **extra,
            },
        )

    def fault(self, name: str, action: str, exc=None, **extra) -> None:
        """Fault-layer event (pipeline/faults.py): one instant marker per
        retry/drop/route/stall so the timeline shows where the error
        policies worked and what they cost."""
        args = {"action": action, **extra}
        if exc is not None:
            args["error"] = type(exc).__name__
        self.instant(name, cat="fault", **args)

    def san(self, name: str, code: str, **extra) -> None:
        """Sanitizer finding (pipeline/sanitize.py): one instant marker
        per NNS-S diagnostic so spec violations, accounting leaks, lock
        cycles and thread leaks land on the same timeline as the frames
        that caused them."""
        self.instant(name, cat="san", code=code, **extra)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        with self._lock:
            self._events.append(
                {
                    "name": name, "cat": cat, "ph": "i", "s": "t",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": threading.get_ident() & 0xFFFF,
                    "args": args or {},
                }
            )

    def counter(self, name: str, **values: float) -> None:
        with self._lock:
            self._events.append(
                {
                    "name": name, "cat": "counter", "ph": "C",
                    "ts": self._ts_us(), "pid": self._pid, "tid": 0,
                    "args": values,
                }
            )

    # -- output ------------------------------------------------------------
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def to_chrome_trace(self) -> Dict:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


def enable() -> Tracer:
    """Install (or return) the global tracer; executor nodes start
    recording as soon as this exists."""
    global _tracer
    with _lock:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer


def disable() -> None:
    global _tracer
    with _lock:
        _tracer = None


def get() -> Optional[Tracer]:
    """Active tracer or None (the hot-path check: one global read)."""
    t = _tracer
    if t is None and os.environ.get("NNS_TRACE"):
        t = enable()
    return t


@contextlib.contextmanager
def device_profile(logdir: str):
    """On-device (TPU/XLA) profile capture → TensorBoard/XProf logdir.
    The XPlane-level complement to the host-side chrome trace."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
