"""Tracing / profiling: chrome-trace spans + device profiler integration.

The reference has no in-tree tracer — it leans on GstShark/NNShark/
HawkTracer (tools/tracing/README.md, tools/profiling/README.md; SURVEY.md
§5.1), whose common output is chrome://tracing JSON. This module brings
that capability in-tree:

- ``Tracer``: lock-protected bounded event buffer; ``span()`` context
  manager and ``complete()`` record "X" (complete) events per element/
  frame, ``instant()`` marks points, ``counter()`` tracks gauges (queue
  depths). ``save()`` atomically writes the Chrome Trace Event Format
  JSON that chrome://tracing / Perfetto load directly (the HawkTracer
  workflow, no external daemon).
- Lanes are labeled: each OS thread gets a stable small tid (first-seen
  order, never truncated-ident collisions) and ``to_chrome_trace()``
  emits chrome ``thread_name``/``process_name`` metadata so Perfetto
  shows element/service-thread names instead of bare numbers.
- The buffer is bounded (``max_events``, drop-oldest): soak runs keep a
  sliding window instead of growing without bound;
  ``dropped_events`` counts what the window lost.
- Distributed correlation: a Tracer carries a process label and a
  wall-clock anchor, and :func:`merge` folds several processes' trace
  docs (client + query server) into ONE timeline, shifting each by its
  anchor so cross-host spans line up. Frame identity rides the
  ``frame_id`` meta the edge layer propagates (edge/serialize.py).
- The executor records one span per frame per node when tracing is
  enabled (pipeline/executor.py Node.stat), giving the per-element
  timeline NNShark's per-element CPU/proctime view provides.
- ``device_profile()``: wraps ``jax.profiler.trace`` — the XPlane/
  TensorBoard capture for on-device (TPU) timing, the XLA-world analogue
  of GstShark's proctime tracer.

Enable via ``trace.enable()`` / ``nns-launch --trace out.json``; env knob
``NNS_TRACE`` (path) mirrors the reference's GST_DEBUG_DUMP_DOT_DIR-style
opt-in (nnstreamer_conf env > ini > default priority, SURVEY.md §5.6).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

_lock = threading.Lock()
_tracer: Optional["Tracer"] = None

# drop-oldest window: ~100 MB of JSON at worst, hours of steady-state
# pipeline spans — a soak run records a sliding window, not a leak
DEFAULT_MAX_EVENTS = 500_000


class Tracer:
    def __init__(
        self,
        process: Optional[str] = None,
        max_events: int = DEFAULT_MAX_EVENTS,
        pid: Optional[int] = None,
    ) -> None:
        self._lock = threading.Lock()
        self._max = max(1, int(max_events))
        self._events: deque = deque(maxlen=self._max)
        self.dropped_events = 0
        self._t0 = time.perf_counter()
        # wall-clock anchor paired with the perf_counter epoch: merge()
        # uses the DIFFERENCE of anchors across processes, so absolute
        # wall accuracy only needs to hold to NTP-ish precision
        self._wall_t0 = time.time()
        self._pid = os.getpid() if pid is None else int(pid)
        self.process = process or f"pid{self._pid}"
        # stable small tids: ident → 1,2,3... in first-seen order. The
        # old `get_ident() & 0xFFFF` truncation collided unrelated
        # threads into one Perfetto lane.
        self._tids: Dict[int, int] = {}
        self._tid_names: Dict[int, str] = {}

    # -- recording ---------------------------------------------------------
    def _ts_us(self, t: Optional[float] = None) -> float:
        return ((t if t is not None else time.perf_counter()) - self._t0) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)  # GIL-atomic fast path
        if tid is None:
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
                    self._tid_names[tid] = threading.current_thread().name
        return tid

    def set_process(self, name: str) -> None:
        """Label this process's lanes (shows as the Perfetto process
        name; merge() keys the combined timeline on it)."""
        self.process = name

    def _append(self, ev: Dict) -> None:
        with self._lock:
            if len(self._events) >= self._max:
                self.dropped_events += 1
            self._events.append(ev)

    def complete(
        self, name: str, cat: str, t_start: float, dur_s: float, args: Optional[Dict] = None
    ) -> None:
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": self._ts_us(t_start),
            "dur": dur_s * 1e6,
            "pid": self._pid,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "element", **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.complete(name, cat, t0, time.perf_counter() - t0, args or None)

    def batch(
        self, name: str, t_start: float, dur_s: float, *, batch: int,
        bucket: int, wait_s: float, **extra
    ) -> None:
        """Batch-assembly span (micro-batching, pipeline/batching.py):
        one "X" event per batched invoke carrying the batch size, the
        padded bucket it dispatched as, the pad waste that padding cost,
        and how long the collector waited for stragglers — the three
        numbers that explain where batched throughput (or latency) went."""
        waste = 100.0 * (bucket - batch) / bucket if bucket else 0.0
        self.complete(
            name, "batch", t_start, dur_s,
            {
                "batch": batch,
                "bucket": bucket,
                "wait_ms": round(wait_s * 1000.0, 3),
                "pad_waste_pct": round(waste, 2),
                **extra,
            },
        )

    def fault(self, name: str, action: str, exc=None, **extra) -> None:
        """Fault-layer event (pipeline/faults.py): one instant marker per
        retry/drop/route/stall so the timeline shows where the error
        policies worked and what they cost."""
        args = {"action": action, **extra}
        if exc is not None:
            args["error"] = type(exc).__name__
        self.instant(name, cat="fault", **args)

    def san(self, name: str, code: str, **extra) -> None:
        """Sanitizer finding (pipeline/sanitize.py): one instant marker
        per NNS-S diagnostic so spec violations, accounting leaks, lock
        cycles and thread leaks land on the same timeline as the frames
        that caused them."""
        self.instant(name, cat="san", code=code, **extra)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        self._append(
            {
                "name": name, "cat": cat, "ph": "i", "s": "t",
                "ts": self._ts_us(), "pid": self._pid,
                "tid": self._tid(),
                "args": args or {},
            }
        )

    def counter(self, name: str, **values: float) -> None:
        self._append(
            {
                "name": name, "cat": "counter", "ph": "C",
                "ts": self._ts_us(), "pid": self._pid, "tid": 0,
                "args": values,
            }
        )

    # -- output ------------------------------------------------------------
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def _metadata_events(self) -> List[Dict]:
        """Chrome "M" metadata: process_name + one thread_name per lane,
        synthesized at export (not stored) so the recording buffer holds
        only real events and events() stays metadata-free."""
        meta = [{
            "name": "process_name", "ph": "M", "ts": 0, "pid": self._pid,
            "tid": 0, "args": {"name": self.process},
        }]
        with self._lock:
            names = dict(self._tid_names)
        for tid, tname in sorted(names.items()):
            meta.append({
                "name": "thread_name", "ph": "M", "ts": 0,
                "pid": self._pid, "tid": tid, "args": {"name": tname},
            })
        return meta

    def to_chrome_trace(self) -> Dict:
        return {
            "traceEvents": self._metadata_events() + self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "process": self.process,
                "pid": self._pid,
                "wall_t0_s": self._wall_t0,
                "dropped_events": self.dropped_events,
            },
        }

    def save(self, path: str) -> None:
        """Atomic write (tmp + rename): a crash mid-dump — or a reader
        polling the file during a soak run — never sees a torn JSON."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped_events = 0


def merge(docs: Sequence[Dict]) -> Dict:
    """Fold several processes' chrome-trace docs into ONE timeline.

    Each doc carries its wall-clock anchor (``otherData.wall_t0_s``);
    events shift by the anchor delta against the earliest doc, so a
    client span and the server span it caused line up on one axis
    (client + tensor_query server traces merge into the end-to-end
    view examples/query_offload.py needed). Docs without an anchor
    merge unshifted. Colliding pids (containers, pid reuse) are
    remapped so lanes never interleave across processes.
    """
    anchors = [
        (d.get("otherData") or {}).get("wall_t0_s") for d in docs
    ]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0
    events: List[Dict] = []
    processes = []
    assigned_pids: set = set()
    for doc, anchor in zip(docs, anchors):
        shift_us = ((anchor - base) * 1e6) if anchor is not None else 0.0
        other = doc.get("otherData") or {}
        if other.get("process"):
            processes.append(other["process"])
        doc_pids = {
            e.get("pid") for e in doc.get("traceEvents", [])
            if e.get("pid") is not None
        }
        remap = {}
        for pid in sorted(doc_pids, key=str):
            new = pid
            while new in assigned_pids:
                new = (new if isinstance(new, int) else 0) + 100_000
            remap[pid] = new
            assigned_pids.add(new)
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            if ev.get("pid") in remap:
                ev["pid"] = remap[ev["pid"]]
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts", 0), e.get("ph") != "M"))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"merged_processes": processes},
    }


def enable() -> Tracer:
    """Install (or return) the global tracer; executor nodes start
    recording as soon as this exists."""
    global _tracer
    with _lock:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer


def disable() -> None:
    global _tracer
    with _lock:
        _tracer = None


_env_checked = False


def get() -> Optional[Tracer]:
    """Active tracer or None (the hot-path check: one global read).
    The ``NNS_TRACE`` env opt-in is resolved on the FIRST miss only —
    this runs per frame per node at multi-kfps, and an environ lookup
    each call is a measurable slice of the executor's frame budget."""
    t = _tracer
    if t is None:
        global _env_checked
        if not _env_checked:
            _env_checked = True
            if os.environ.get("NNS_TRACE"):
                t = enable()
    return t


@contextlib.contextmanager
def device_profile(logdir: str):
    """On-device (TPU/XLA) profile capture → TensorBoard/XProf logdir.
    The XPlane-level complement to the host-side chrome trace."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
