"""Layered configuration: env vars > ini file > hardcoded defaults.

Reference: gst/nnstreamer/nnstreamer_conf.{c,h} — priority "env-var >
/etc/nnstreamer.ini > hardcoded" (nnstreamer_conf.h:26-29), controlling
subplugin search paths, framework auto-detect priority per model extension,
and per-backend bool/string knobs (template nnstreamer.ini.in).

Env mapping: section ``filter`` key ``framework_priority`` is overridden by
``NNS_TPU_FILTER_FRAMEWORK_PRIORITY``. The ini path itself comes from
``NNS_TPU_CONF`` (default ``~/.config/nnstreamer_tpu.ini``, then
``/etc/nnstreamer_tpu.ini``). ``enable_envvar`` (default on) can disable the
env layer, mirroring the reference's meson option (meson_options.txt:36).
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

_DEFAULTS: Dict[str, Dict[str, str]] = {
    "common": {
        "enable_envvar": "true",
        # comma list of allowed elements; empty = all (reference
        # element-restriction product whitelist, meson_options.txt:40-41)
        "restricted_elements": "",
    },
    "filter": {
        # search paths for out-of-tree backend plugins (python files defining
        # register()); colon separated
        "plugin_paths": "",
        # model-extension → backend auto-detection priority
        # (reference nnstreamer.ini.in:14-17 framework_priority_*)
        "framework_priority_stablehlo": "jax",
        "framework_priority_mlir": "jax",
        "framework_priority_pkl": "jax",
        "framework_priority_msgpack": "jax",
        "framework_priority_py": "custom",
        "framework_priority_tflite": "tflite,jax",
        # .pt/.pth = TorchScript (torch.jit.load); .pt2 (torch.export
        # archives) is NOT mapped — the torch backend can't load it
        "framework_priority_pt": "torch",
        "framework_priority_pth": "torch",
    },
    "decoder": {"plugin_paths": ""},
    "converter": {"plugin_paths": ""},
    "jax": {
        # default compute dtype for fused segments on TPU
        "compute_dtype": "bfloat16",
        # on-disk XLA executable cache (SURVEY.md §5.4 checkpoint/resume
        # analogue): ON by default — first model open compiles, every
        # later process reloads in ms. Set empty to disable.
        "persistent_cache": "~/.cache/nnstreamer_tpu/xla",
    },
    "edge": {
        "default_port": "3000",  # reference edge_common.h:36-37
        "timeout_sec": "10",  # reference tensor_query_common.h:28
    },
    "plane": {
        # serving-plane defaults (serving_plane/plane.py,
        # docs/serving-plane.md); per-filter plane-* properties
        # override. Env: NNS_TPU_PLANE_MAX_BATCH etc.
        "max_batch": "8",
        "timeout_ms": "1.0",
        # single | shard (data-parallel mesh) | replicas (K failover
        # copies, parallel/replicas.py semantics)
        "mode": "single",
        # devices backing the plane: mesh size (shard) / replica count
        "devices": "1",
        # replica health (mode=replicas): consecutive device faults
        # that bench a replica, and probe cadence for re-admission
        "unhealthy_after": "3",
        "probe_every": "64",
        # a submit with no service inside this window fails typed
        # (service thread dead / program wedged), never hangs a node
        "submit_timeout_s": "30",
        # Hermes placement bound for place_pipeline (placement.py):
        # bytes per device, K/M/G suffixes accepted; empty = the
        # planner requires an explicit bound argument
        "memory_per_device": "",
    },
    "llm": {
        # continuous-batching LLM serving defaults
        # (tensor_llm_serversink props override; docs/llm-serving.md).
        # kv_layout: slot (one contiguous worst-case cache per slot) |
        # paged (block arena + per-request block tables with prefix
        # sharing, chunked prefill and preemption-by-eviction)
        "kv_layout": "slot",
        # paged decode formulation: auto (= block) | block (attend the
        # arena directly through the block tables, in-place token
        # writes — the default) | gather (materialize the contiguous
        # view per step: the debug/parity oracle, pays a transient HBM
        # doubling — nns-lint NNS-W117 flags it against memory_bound)
        "kv_attn": "auto",
        # tokens per KV block (paged); must divide prompt-len/max-len
        "block_size": "16",
        # total usable blocks in the arena (paged); empty = enough for
        # every slot at max-len (no memory saving — size it BELOW that
        # to serve more live requests at the same HBM)
        "kv_blocks": "",
        # prefill buckets advanced per pump (paged chunked prefill):
        # bounds how long one request's prompt can stall decoders
        "prefill_chunks": "1",
        # declared KV memory bound for nns-lint NNS-W115 (bytes, K/M/G
        # suffixes); empty = lint stays silent
        "memory_bound": "",
    },
    "executor": {
        # micro-batching defaults for fused segments / batchable filters
        # (pipeline/batching.py); per-element properties on tensor_filter
        # (batching=, max-batch=, ...) override. Env:
        # NNS_TPU_EXECUTOR_BATCHING etc.
        "batching": "false",
        "max_batch": "8",
        "batch_timeout_ms": "1.0",
        # comma list of padded batch sizes; empty = 1,2,4,...,max_batch
        "batch_buckets": "",
        # fault tolerance defaults (pipeline/faults.py); per-element
        # on-error/retry-max/retry-backoff-ms properties override. Env:
        # NNS_TPU_EXECUTOR_ON_ERROR etc.
        "on_error": "stop",
        "retry_max": "3",
        "retry_backoff_ms": "10.0",
        "retry_backoff_cap_ms": "1000.0",
        # stall watchdog: >0 arms the executor monitor thread that turns
        # a no-progress-with-queued-data hang into PipelineStallError
        "watchdog_timeout_ms": "0",
        # device-resilience defaults (pipeline/device_faults.py,
        # docs/resilience.md); per-element oom-policy/device-fallback
        # properties override. Env: NNS_TPU_EXECUTOR_OOM_POLICY etc.
        "oom_policy": "degrade",
        "device_fallback": "true",
        "device_fallback_after": "3",
        "device_probe_every": "64",
        "oom_reprobe_ms": "30000.0",
        # resident streaming executor (pipeline/transfer.py,
        # docs/streaming.md): ring_depth = in-flight frames per device
        # node (H2D of N+1 / compute of N / D2H of N-1 overlap; 1 =
        # synchronous dispatch-and-deliver), donate = hand node-owned
        # activation buffers (staged uploads, stacked batch windows) to
        # the fused program for reuse. Per-element ring-depth property
        # overrides. Env: NNS_TPU_EXECUTOR_RING_DEPTH etc.
        "ring_depth": "2",
        "donate": "true",
        # whole-chain resident programs (pipeline/chain_program.py,
        # docs/chain-analysis.md "Compiled chains"): chain_mode=auto
        # compiles every eligible multi-segment chain into ONE jitted
        # program dispatched once per unrolled window of chain_unroll
        # frames (clamped by the OOM bucket governor rung and the W124
        # transient-HBM bound); off keeps the per-node parity path.
        # Per-element chain-mode property overrides. Env:
        # NNS_TPU_EXECUTOR_CHAIN_MODE / NNS_TPU_EXECUTOR_CHAIN_UNROLL.
        "chain_mode": "auto",
        "chain_unroll": "4",
        # nns-san runtime sanitizer (pipeline/sanitize.py): instrumented
        # channels assert negotiated-spec conformance per frame, latch
        # offered == delivered + dropped + routed per node at EOS, watch
        # lock order, poison batch pad rows, and report leaked threads.
        # The NNS_TPU_SANITIZE env var is the documented one-knob opt-in
        # (checked before this layered key).
        "sanitize": "false",
        # nns-obs live telemetry (obs/): `metrics` turns on per-element
        # latency/queue-wait/queue-depth histograms (p50/p95/p99 in
        # Executor.stats and nns-launch --stats); `metrics_port` > 0
        # additionally serves /metrics (Prometheus) + /metrics.json
        # (nns-top) from a background thread. NNS_TPU_METRICS /
        # NNS_TPU_METRICS_PORT are the documented one-knob env opt-ins
        # (checked before these layered keys).
        "metrics": "false",
        "metrics_port": "0",
        # bind address for the exposition endpoint: loopback unless the
        # operator explicitly widens it (the endpoint has no auth)
        "metrics_host": "127.0.0.1",
    },
}

_ENV_PREFIX = "NNS_TPU_"


class Config:
    """Thread-safe layered config with the reference's 3-level priority."""

    def __init__(self, ini_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._parser = configparser.ConfigParser()
        self._loaded_path: Optional[str] = None
        self.load(ini_path)

    def load(self, ini_path: Optional[str] = None) -> None:
        with self._lock:
            self._parser = configparser.ConfigParser()
            candidates = [
                ini_path,
                os.environ.get(_ENV_PREFIX + "CONF"),
                os.path.expanduser("~/.config/nnstreamer_tpu.ini"),
                "/etc/nnstreamer_tpu.ini",
            ]
            for c in candidates:
                if c and os.path.isfile(c):
                    self._parser.read(c)
                    self._loaded_path = c
                    break

    @property
    def env_enabled(self) -> bool:
        raw = self._layered("common", "enable_envvar", use_env=False)
        return raw.strip().lower() in ("1", "true", "yes", "on")

    def _layered(self, section: str, key: str, use_env: bool = True) -> str:
        if use_env:
            env_key = f"{_ENV_PREFIX}{section.upper()}_{key.upper()}"
            if env_key in os.environ:
                return os.environ[env_key]
        if self._parser.has_option(section, key):
            return self._parser.get(section, key)
        return _DEFAULTS.get(section, {}).get(key, "")

    def get(self, section: str, key: str, default: str = "") -> str:
        val = self._layered(section, key, use_env=self.env_enabled)
        return val if val != "" else default

    def get_bool(self, section: str, key: str, default: bool = False) -> bool:
        raw = self.get(section, key, "")
        if raw == "":
            return default
        return raw.strip().lower() in ("1", "true", "yes", "on")

    def get_int(self, section: str, key: str, default: int = 0) -> int:
        raw = self.get(section, key, "")
        try:
            return int(raw)
        except ValueError:
            return default

    def get_list(self, section: str, key: str, sep: str = ",") -> List[str]:
        raw = self.get(section, key, "")
        return [p.strip() for p in raw.split(sep) if p.strip()]

    def plugin_paths(self, kind: str) -> List[str]:
        """Search paths for out-of-tree subplugins of a kind
        (reference nnsconf_get_fullpath search-path machinery)."""
        return self.get_list(kind, "plugin_paths", sep=":")

    def framework_priority(self, model_ext: str) -> List[str]:
        """Backend priority list for a model file extension
        (reference tensor_filter_common.c:1155-1218 auto-detection)."""
        return self.get_list("filter", f"framework_priority_{model_ext.lstrip('.')}")


_global: Optional[Config] = None
_global_lock = threading.Lock()


def conf() -> Config:
    """Global config singleton (reference nnsconf_loadconf lazy-load)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = Config()
        return _global


def reload_conf(ini_path: Optional[str] = None) -> Config:
    global _global
    with _global_lock:
        _global = Config(ini_path)
        return _global
