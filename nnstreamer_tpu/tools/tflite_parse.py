"""Minimal TFLite flatbuffer reader — no flatbuffers/tensorflow import.

Parses the subset of the public TFLite schema (schema.fbs) needed to
import reference models (weights, topology, quantization params):
Model / SubGraph / Tensor / Operator / Buffer / QuantizationParameters
plus the conv/pool/softmax builtin option tables. The reference loads
these same files through the TFLite C++ interpreter
(ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:154-218);
here the flatbuffer is decoded directly so the graph can be compiled to
XLA instead of interpreted (tools/tflite_exec.py) and its weights
imported into the from-scratch jnp models (models/*).

Flatbuffer wire format (little-endian):
- file starts with an int32 offset to the root table (then optional
  file identifier "TFL3")
- table: int32 soffset at the table position points BACK to its vtable;
  vtable = [u16 vtable_bytes, u16 table_bytes, u16 field_off...] where
  field_off is relative to the table position (0 = field absent)
- string/vector/table fields hold a u32 forward offset to their data;
  vectors and strings are length-prefixed (u32 count)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# -- TensorType enum (schema.fbs) --
TENSOR_DTYPES = {
    0: np.float32, 1: np.float16, 2: np.int32, 3: np.uint8,
    4: np.int64, 6: np.bool_, 7: np.int16, 9: np.int8, 10: np.float64,
}

# BuiltinOperator codes used by the reference fixtures (schema.fbs enum)
OP_NAMES = {
    0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
    4: "DEPTHWISE_CONV_2D", 9: "FULLY_CONNECTED", 14: "LOGISTIC",
    17: "MAX_POOL_2D", 18: "MUL", 22: "RESHAPE", 23: "RESIZE_BILINEAR",
    25: "SOFTMAX", 28: "TANH", 34: "PAD", 40: "MEAN", 42: "SQUEEZE",
    49: "RELU", 21: "RELU6", 83: "PACK", 97: "RESIZE_NEAREST_NEIGHBOR",
    114: "QUANTIZE", 6: "DEQUANTIZE", 27: "SPACE_TO_DEPTH",
    26: "SPLIT", 47: "SUB", 39: "TRANSPOSE", 67: "TRANSPOSE_CONV",
    53: "STRIDED_SLICE", 77: "SHAPE", 88: "EXPAND_DIMS", 99: "LEAKY_RELU",
}

PADDING = {0: "SAME", 1: "VALID"}
ACTIVATION = {0: None, 1: "RELU", 2: "RELU_N1_TO_1", 3: "RELU6",
              4: "TANH", 5: "SIGN_BIT"}


class _Reader:
    """Positioned primitive reads over the flatbuffer bytes."""

    def __init__(self, buf: bytes):
        self.buf = buf

    def u8(self, pos): return self.buf[pos]
    def u16(self, pos): return struct.unpack_from("<H", self.buf, pos)[0]
    def i32(self, pos): return struct.unpack_from("<i", self.buf, pos)[0]
    def u32(self, pos): return struct.unpack_from("<I", self.buf, pos)[0]
    def i64(self, pos): return struct.unpack_from("<q", self.buf, pos)[0]
    def f32(self, pos): return struct.unpack_from("<f", self.buf, pos)[0]


class _Table:
    """One flatbuffer table: field access by schema id."""

    def __init__(self, r: _Reader, pos: int):
        self.r = r
        self.pos = pos
        vt = pos - r.i32(pos)  # soffset points back to the vtable
        self._vt = vt
        self._vt_len = r.u16(vt)

    def _off(self, fid: int) -> int:
        """Byte offset of field `fid` within the table, 0 if absent."""
        slot = 4 + 2 * fid
        if slot + 2 > self._vt_len:
            return 0
        return self.r.u16(self._vt + slot)

    def scalar(self, fid: int, kind: str, default=0):
        o = self._off(fid)
        if not o:
            return default
        return getattr(self.r, kind)(self.pos + o)

    def _indirect(self, fid: int) -> Optional[int]:
        o = self._off(fid)
        if not o:
            return None
        p = self.pos + o
        return p + self.r.u32(p)

    def table(self, fid: int) -> Optional["_Table"]:
        p = self._indirect(fid)
        return _Table(self.r, p) if p is not None else None

    def string(self, fid: int) -> Optional[str]:
        p = self._indirect(fid)
        if p is None:
            return None
        n = self.r.u32(p)
        return self.r.buf[p + 4 : p + 4 + n].decode("utf-8", "replace")

    def vector_len(self, fid: int) -> int:
        p = self._indirect(fid)
        return self.r.u32(p) if p is not None else 0

    def vector_scalars(self, fid: int, fmt: str) -> np.ndarray:
        """Numeric vector as a numpy array (fmt: numpy dtype str)."""
        p = self._indirect(fid)
        if p is None:
            return np.zeros((0,), fmt)
        n = self.r.u32(p)
        return np.frombuffer(self.r.buf, dtype=fmt, count=n, offset=p + 4)

    def vector_tables(self, fid: int) -> List["_Table"]:
        p = self._indirect(fid)
        if p is None:
            return []
        n = self.r.u32(p)
        out = []
        for i in range(n):
            ep = p + 4 + 4 * i
            out.append(_Table(self.r, ep + self.r.u32(ep)))
        return out


@dataclass
class QuantParams:
    scale: np.ndarray          # per-tensor (len 1) or per-channel
    zero_point: np.ndarray
    quantized_dimension: int = 0

    @property
    def quantized(self) -> bool:
        return self.scale.size > 0


@dataclass
class Tensor:
    index: int
    name: str
    shape: Tuple[int, ...]
    dtype: Any
    buffer: int
    quant: Optional[QuantParams]
    data: Optional[np.ndarray] = None  # constant data, raw (quantized) dtype

    def dequantized(self) -> Optional[np.ndarray]:
        """Constant data as float32, dequantizing if quant params exist
        ((q - zero_point) * scale, per-channel aware)."""
        if self.data is None:
            return None
        x = self.data
        if self.quant is None or not self.quant.quantized or \
                not np.issubdtype(x.dtype, np.integer):
            return x.astype(np.float32) if x.dtype != np.float32 else x
        s, z, d = (self.quant.scale, self.quant.zero_point,
                   self.quant.quantized_dimension)
        xf = x.astype(np.float32)
        if s.size == 1:
            return (xf - float(z[0] if z.size else 0)) * float(s[0])
        shape = [1] * xf.ndim
        shape[d] = s.size
        zz = z if z.size == s.size else np.zeros_like(s)
        return (xf - zz.reshape(shape)) * s.reshape(shape)


@dataclass
class Operator:
    opcode: int                 # builtin code
    name: str                   # readable builtin name
    inputs: List[int]
    outputs: List[int]
    options: Dict[str, Any] = field(default_factory=dict)
    custom_code: Optional[str] = None


@dataclass
class TFLiteModel:
    tensors: List[Tensor]
    operators: List[Operator]
    inputs: List[int]
    outputs: List[int]
    description: str = ""

    def tensor_by_name(self, name: str) -> Tensor:
        for t in self.tensors:
            if t.name == name:
                return t
        raise KeyError(name)


def _parse_options(op_name: str, t: Optional[_Table]) -> Dict[str, Any]:
    """Decode the builtin-options union table for the op kinds we run."""
    if t is None:
        return {}
    if op_name == "CONV_2D":
        return {
            "padding": PADDING.get(t.scalar(0, "u8"), "SAME"),
            "stride_w": t.scalar(1, "i32", 1),
            "stride_h": t.scalar(2, "i32", 1),
            "activation": ACTIVATION.get(t.scalar(3, "u8")),
            "dilation_w": t.scalar(4, "i32", 1),
            "dilation_h": t.scalar(5, "i32", 1),
        }
    if op_name == "DEPTHWISE_CONV_2D":
        return {
            "padding": PADDING.get(t.scalar(0, "u8"), "SAME"),
            "stride_w": t.scalar(1, "i32", 1),
            "stride_h": t.scalar(2, "i32", 1),
            "depth_multiplier": t.scalar(3, "i32", 1),
            "activation": ACTIVATION.get(t.scalar(4, "u8")),
            "dilation_w": t.scalar(5, "i32", 1),
            "dilation_h": t.scalar(6, "i32", 1),
        }
    if op_name in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
        return {
            "padding": PADDING.get(t.scalar(0, "u8"), "SAME"),
            "stride_w": t.scalar(1, "i32", 1),
            "stride_h": t.scalar(2, "i32", 1),
            "filter_w": t.scalar(3, "i32", 1),
            "filter_h": t.scalar(4, "i32", 1),
            "activation": ACTIVATION.get(t.scalar(5, "u8")),
        }
    if op_name in ("ADD", "MUL", "SUB"):
        return {"activation": ACTIVATION.get(t.scalar(0, "u8"))}
    if op_name == "SOFTMAX":
        return {"beta": t.scalar(0, "f32", 1.0)}
    if op_name == "RESHAPE":
        return {"new_shape": t.vector_scalars(0, "<i4").tolist()}
    if op_name == "RESIZE_BILINEAR":
        return {
            "align_corners": bool(t.scalar(2, "u8", 0)),
            "half_pixel_centers": bool(t.scalar(3, "u8", 0)),
        }
    if op_name == "CONCATENATION":
        return {"axis": t.scalar(0, "i32", 0),
                "activation": ACTIVATION.get(t.scalar(1, "u8"))}
    if op_name == "MEAN":
        return {"keep_dims": bool(t.scalar(0, "u8", 0))}
    if op_name == "FULLY_CONNECTED":
        return {"activation": ACTIVATION.get(t.scalar(0, "u8"))}
    return {}


def parse(path: str) -> TFLiteModel:
    """Parse a .tflite file into tensors + topologically-ordered ops.

    Only the first subgraph is returned (the reference fixtures are all
    single-subgraph)."""
    with open(path, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    root = _Table(r, r.u32(0))

    # Model: 0 version, 1 operator_codes, 2 subgraphs, 3 description,
    # 4 buffers
    opcodes = []
    for oc in root.vector_tables(1):
        # new-style builtin_code (id 3, int32) supersedes the deprecated
        # int8 field (id 0); files older than the split only carry id 0
        code = oc.scalar(3, "i32", 0) or oc.scalar(0, "u8", 0)
        opcodes.append((code, oc.string(1)))

    buffers: List[Optional[np.ndarray]] = []
    for b in root.vector_tables(4):
        data = b.vector_scalars(0, "<u1")
        buffers.append(data if data.size else None)

    sub = root.vector_tables(2)[0]
    # SubGraph: 0 tensors, 1 inputs, 2 outputs, 3 operators, 4 name
    tensors: List[Tensor] = []
    for i, tt in enumerate(sub.vector_tables(0)):
        shape = tuple(int(v) for v in tt.vector_scalars(0, "<i4"))
        ttype = tt.scalar(1, "u8", 0)
        dtype = TENSOR_DTYPES.get(ttype, np.float32)
        bufidx = tt.scalar(2, "u32", 0)
        quant = None
        qt = tt.table(4)
        if qt is not None:
            quant = QuantParams(
                scale=np.asarray(qt.vector_scalars(2, "<f4"), np.float32),
                zero_point=np.asarray(qt.vector_scalars(3, "<i8")),
                quantized_dimension=qt.scalar(6, "i32", 0),
            )
        data = None
        if 0 < bufidx < len(buffers) and buffers[bufidx] is not None:
            raw = buffers[bufidx]
            data = np.frombuffer(raw.tobytes(), dtype=dtype)
            if shape:
                data = data.reshape(shape)
        tensors.append(Tensor(i, tt.string(3) or f"t{i}", shape, dtype,
                              bufidx, quant, data))

    operators: List[Operator] = []
    for ot in sub.vector_tables(3):
        idx = ot.scalar(0, "u32", 0)
        code, custom = opcodes[idx] if idx < len(opcodes) else (-1, None)
        name = "CUSTOM" if custom else OP_NAMES.get(code, f"OP_{code}")
        operators.append(Operator(
            opcode=code, name=name, custom_code=custom,
            inputs=[int(v) for v in ot.vector_scalars(1, "<i4")],
            outputs=[int(v) for v in ot.vector_scalars(2, "<i4")],
            options=_parse_options(name, ot.table(4)),
        ))

    return TFLiteModel(
        tensors=tensors,
        operators=operators,
        inputs=[int(v) for v in sub.vector_scalars(1, "<i4")],
        outputs=[int(v) for v in sub.vector_scalars(2, "<i4")],
        description=root.string(3) or "",
    )


def summarize(m: TFLiteModel) -> str:
    """Human-readable op-by-op dump (CLI: python -m ...tflite_parse f)."""
    lines = [f"desc: {m.description}",
             f"inputs: {[m.tensors[i].name for i in m.inputs]}",
             f"outputs: {[m.tensors[i].name for i in m.outputs]}"]
    for k, op in enumerate(m.operators):
        ins = ", ".join(
            f"{m.tensors[i].name}{list(m.tensors[i].shape)}"
            f"{'*' if m.tensors[i].data is not None else ''}"
            for i in op.inputs if i >= 0
        )
        outs = ", ".join(
            f"{m.tensors[i].name}{list(m.tensors[i].shape)}"
            for i in op.outputs
        )
        lines.append(f"[{k:3d}] {op.name} {op.options} ({ins}) -> ({outs})")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - debug CLI
    import sys

    print(summarize(parse(sys.argv[1])))
