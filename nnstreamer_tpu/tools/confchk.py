"""Config sanity checker.

Reference: tools/development/confchk — validates /etc/nnstreamer.ini
(sections, subplugin paths, priorities). Checks the layered config
(nnstreamer_tpu/config.py): unknown sections/keys, unreadable
plugin_paths entries, framework priorities naming unregistered backends,
and reports the effective (env>ini>default) value of every key.

Usage: python -m nnstreamer_tpu.tools.confchk [INI_PATH]
Exit code: 0 clean, 1 warnings, 2 errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Tuple

from nnstreamer_tpu import registry
from nnstreamer_tpu.config import _DEFAULTS, Config


def check(ini_path=None) -> Tuple[List[str], List[str], List[str]]:
    """Returns (info, warnings, errors) message lists."""
    info: List[str] = []
    warnings: List[str] = []
    errors: List[str] = []
    cfg = Config(ini_path)

    parser = cfg._parser
    for section in parser.sections():
        if section not in _DEFAULTS:
            warnings.append(f"unknown section [{section}]")
            continue
        for key in parser[section]:
            if key not in _DEFAULTS[section]:
                warnings.append(f"unknown key [{section}] {key}")

    for kind in (registry.KIND_FILTER, registry.KIND_DECODER, registry.KIND_CONVERTER):
        for p in cfg.plugin_paths(kind):
            if not os.path.isdir(p):
                errors.append(f"[{kind}] plugin_paths entry not a directory: {p}")

    for key, val in _DEFAULTS["filter"].items():
        if not key.startswith("framework_priority_"):
            continue
        ext = key[len("framework_priority_"):]
        for backend in cfg.framework_priority(ext):
            try:
                registry.get(registry.KIND_FILTER, backend)
                info.append(f"priority .{ext} → {backend}: available")
            except Exception:
                warnings.append(f"priority .{ext} names unavailable backend {backend!r}")

    for section, keys in _DEFAULTS.items():
        for key in keys:
            info.append(f"[{section}] {key} = {cfg.get(section, key)!r}")
    return info, warnings, errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-confchk", description=__doc__)
    ap.add_argument("ini", nargs="?", default=None)
    ap.add_argument("-q", "--quiet", action="store_true", help="problems only")
    args = ap.parse_args(argv)
    info, warnings, errors = check(args.ini)
    if not args.quiet:
        for m in info:
            print(f"  {m}")
    for m in warnings:
        print(f"WARN: {m}")
    for m in errors:
        print(f"ERROR: {m}")
    if errors:
        return 2
    return 1 if warnings else 0


if __name__ == "__main__":
    sys.exit(main())
