"""Pipeline description → mediapipe-style pbtxt converter.

Reference: tools/development/parser (flex/bison gst-launch grammar +
toplevel.c pbtxt emitter). Here the framework's own parser
(pipeline/parse.py) produces the graph, and this tool renders it as a
mediapipe-style ``node { calculator / input_stream / output_stream }``
text graph — same round-trip the reference's converter provides for
visualizing gst pipelines as dataflow graphs.

Usage: python -m nnstreamer_tpu.tools.pbtxt "videotestsrc ! tensor_converter ! tensor_sink"
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List


def to_pbtxt(description: str) -> str:
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    pipeline = parse_pipeline(description)
    # stream name per (src element, src pad)
    stream_of: Dict = {}
    for link in pipeline.links:
        key = (link.src.name, link.src_pad)
        if key not in stream_of:
            suffix = f"_{link.src_pad}" if link.src_pad else ""
            stream_of[key] = f"{link.src.name}{suffix}"

    lines: List[str] = [f'# pbtxt of pipeline: {description!r}']
    for e in pipeline.elements:
        lines.append("node {")
        lines.append(f'  calculator: "{e.FACTORY_NAME}"')
        lines.append(f'  name: "{e.name}"')
        for link in pipeline.links:
            if link.dst is e:
                lines.append(
                    f'  input_stream: "{stream_of[(link.src.name, link.src_pad)]}"'
                )
        for (src_name, _pad), stream in stream_of.items():
            if src_name == e.name:
                lines.append(f'  output_stream: "{stream}"')
        props = {
            k: v for k, v in (getattr(e, "props", None) or {}).items() if v is not None
        }
        if props:
            lines.append("  node_options {")
            for k, v in sorted(props.items()):
                lines.append(f'    option: "{k}={v}"')
            lines.append("  }")
        lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-pbtxt", description=__doc__)
    ap.add_argument("description", help="pipeline description string")
    ap.add_argument("-o", "--output", default=None, help="write to file")
    args = ap.parse_args(argv)
    text = to_pbtxt(args.description)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
