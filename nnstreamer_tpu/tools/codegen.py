"""Custom-plugin scaffold generator.

Reference: tools/development/nnstreamerCodeGenCustomFilter.py — emits a
buildable skeleton for a custom tensor_filter. Here the plugin ABI is
Python (backends/custom.py, decoders/, converters/ registries), so the
scaffold is a ready-to-run .py the search-path loader picks up
(config [filter]/[decoder]/[converter] plugin_paths).

Usage: python -m nnstreamer_tpu.tools.codegen filter my_op [-o DIR]
"""

from __future__ import annotations

import argparse
import os
import sys

_FILTER_TEMPLATE = '''"""Custom tensor_filter: {name}.

Load with: tensor_filter framework=custom model={name}.py
(python3-subplugin protocol, backends/custom.py CustomScriptBackend).
"""

import jax.numpy as jnp


class CustomFilter:
    TRACEABLE = True  # jnp-only invoke: the pipeline compiler may fuse it

    def setInputDim(self, in_spec):
        """Shape-polymorphic: accept the upstream spec, return the output
        spec (here passthrough). Shape-fixed filters implement
        getInputDim()/getOutputDim() instead."""
        self.in_spec = in_spec
        return in_spec

    def invoke(self, tensors):
        return tuple(jnp.asarray(t) for t in tensors)
'''

_DECODER_TEMPLATE = '''"""Custom tensor_decoder subplugin: {name}.

Use with: tensor_decoder mode=custom-code option1={name}
after register(), or put on a [decoder] plugin_paths directory.
"""

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.decoder_plugin("{name}")
class {cls}Decoder:
    def negotiate(self, in_spec: TensorsSpec, options: dict) -> MediaSpec:
        return MediaSpec("application", format="octet-stream")

    def decode(self, frame: Frame, options: dict) -> Frame:
        data = np.asarray(frame.tensors[0])
        return frame.with_tensors((data.tobytes(),))
'''

_CONVERTER_TEMPLATE = '''"""Custom tensor_converter subplugin: {name}.

Importing registers it; place on a [converter] plugin_paths directory to
load by name (registry search paths), then: tensor_converter mode={name}.
"""

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


@registry.converter_plugin("{name}")
class {cls}Converter:
    def negotiate(self, in_spec, props: dict) -> TensorsSpec:
        return TensorsSpec.of(TensorSpec((1,), DType.UINT8))

    def convert(self, frame: Frame, props: dict) -> Frame:
        data = np.asarray(frame.tensors[0], dtype=np.uint8)
        return frame.with_tensors((data.reshape(1, -1),))
'''

_TEMPLATES = {
    "filter": ("{name}.py", _FILTER_TEMPLATE),
    "decoder": ("{name}_decoder.py", _DECODER_TEMPLATE),
    "converter": ("{name}_converter.py", _CONVERTER_TEMPLATE),
}


def generate(kind: str, name: str, out_dir: str = ".") -> str:
    if kind not in _TEMPLATES:
        raise ValueError(f"unknown kind {kind!r}; one of {sorted(_TEMPLATES)}")
    if not name.isidentifier():
        raise ValueError(f"name must be a python identifier, got {name!r}")
    fname, template = _TEMPLATES[kind]
    cls = "".join(part.capitalize() for part in name.split("_"))
    path = os.path.join(out_dir, fname.format(name=name))
    if os.path.exists(path):
        raise FileExistsError(path)
    with open(path, "w") as f:
        f.write(template.format(name=name, cls=cls))
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-codegen", description=__doc__)
    ap.add_argument("kind", choices=sorted(_TEMPLATES))
    ap.add_argument("name", help="plugin name (python identifier)")
    ap.add_argument("-o", "--out-dir", default=".")
    args = ap.parse_args(argv)
    path = generate(args.kind, args.name, args.out_dir)
    print(f"generated {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
