"""Compile a parsed TFLite graph to one jitted XLA program.

Where the reference hands .tflite files to the TFLite C++ interpreter
(ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:154-218,
op-by-op CPU dispatch), this walks the flatbuffer graph once at build
time and emits the whole network as a single jnp trace — XLA fuses and
tiles it for the MXU, so a reference user's .tflite runs TPU-native with
no interpreter in the loop.

Quantized graphs (uint8 TOCO models like mobilenet_v2_1.0_224_quant)
run in *fake-quant* float: weights are exactly dequantized from their
integer grid and every activation is round-tripped through its tensor's
(scale, zero_point) grid — emulating the integer pipeline's value
clamping/rounding in float, which keeps MXU-friendly dtypes while
tracking the interpreter closely (activation ranges, e.g. the implicit
ReLU6 encoded as a [0,6] quant range, are enforced by the round-trip).

Supported ops cover the reference fixture models: CONV_2D,
DEPTHWISE_CONV_2D, ADD/MUL/SUB, AVERAGE_POOL_2D/MAX_POOL_2D, RESHAPE,
SOFTMAX, RESIZE_BILINEAR (align_corners), CONCATENATION,
FULLY_CONNECTED, MEAN, PAD, LOGISTIC, DEQUANTIZE.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.tools.tflite_parse import TFLiteModel, Tensor, parse

_QRANGE = {np.uint8: (0, 255), np.int8: (-128, 127), np.int16: (-32768, 32767)}


def _act(x, name: Optional[str]):
    if name is None:
        return x
    if name == "RELU":
        return jnp.maximum(x, 0.0)
    if name == "RELU6":
        return jnp.clip(x, 0.0, 6.0)
    if name == "RELU_N1_TO_1":
        return jnp.clip(x, -1.0, 1.0)
    if name == "TANH":
        return jnp.tanh(x)
    raise NotImplementedError(f"activation {name}")


def _qdq(x, t: Tensor):
    """Round-trip a float activation through tensor t's integer grid —
    the float emulation of the interpreter's requantize step."""
    if t.quant is None or not t.quant.quantized:
        return x
    rng = _QRANGE.get(np.dtype(t.dtype).type)
    if rng is None:  # float / int32-accumulator tensors aren't gridded
        return x
    s = float(t.quant.scale[0])
    z = float(t.quant.zero_point[0]) if t.quant.zero_point.size else 0.0
    q = jnp.clip(jnp.round(x / s) + z, rng[0], rng[1])
    return (q - z) * s


def _resize_bilinear(x, oh: int, ow: int, align: bool, half_pixel: bool):
    """TF-semantics bilinear resize (jax.image.resize has no
    align_corners mode, which the DeepLab graph uses throughout)."""
    ih, iw = x.shape[1], x.shape[2]

    def coords(o, i):
        if align and o > 1:
            return jnp.linspace(0.0, i - 1.0, o)
        if half_pixel:
            return jnp.maximum((jnp.arange(o) + 0.5) * (i / o) - 0.5, 0.0)
        return jnp.arange(o) * (i / o)

    ys, xs = coords(oh, ih), coords(ow, iw)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, ih - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, iw - 1)
    y1, x1 = jnp.minimum(y0 + 1, ih - 1), jnp.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    g = lambda yi, xi: x[:, yi][:, :, xi]  # noqa: E731
    top = g(y0, x0) * (1.0 - wx) + g(y0, x1) * wx
    bot = g(y1, x0) * (1.0 - wx) + g(y1, x1) * wx
    return top * (1.0 - wy) + bot * wy


class TFLiteProgram:
    """A .tflite graph compiled to a single jitted function.

    ``fn(x)``: input in the graph's declared dtype (uint8 for quantized
    graphs — dequantization is part of the program) → list of float32
    outputs (quantized outputs are dequantized on-device)."""

    def __init__(self, model: TFLiteModel | str, fake_quant: Optional[bool]
                 = None, compute_dtype=jnp.float32):
        m = parse(model) if isinstance(model, str) else model
        self.model = m
        if fake_quant is None:
            fake_quant = any(
                t.quant is not None and t.quant.quantized
                and np.dtype(t.dtype).type in _QRANGE
                for t in m.tensors
            )
        self.fake_quant = fake_quant
        self.compute_dtype = compute_dtype
        # constants: dequantized once at build; shipped to device as the
        # closure's captured params (jit keeps them resident)
        self._consts: Dict[int, jnp.ndarray] = {}
        for t in m.tensors:
            if t.data is not None:
                d = t.dequantized()
                self._consts[t.index] = jnp.asarray(
                    d if d is not None else t.data
                )
        self.input_shapes = [m.tensors[i].shape for i in m.inputs]
        self.input_dtypes = [np.dtype(m.tensors[i].dtype) for i in m.inputs]
        self.input_shape = self.input_shapes[0]   # single-input shorthand
        self.input_dtype = self.input_dtypes[0]
        self.output_shapes = [m.tensors[o].shape for o in m.outputs]
        # consts are CLOSED OVER, not jit args: shape-operands (resize
        # sizes, reduce axes, pad widths) must be concrete at trace
        # time, and XLA folds the weight constants into the executable
        self._fn = jax.jit(lambda *xs: self._run(self._consts, xs))

    # the traced body: env maps tensor index -> live array
    def _run(self, consts: Dict[int, jnp.ndarray], xs):
        m = self.model
        if len(xs) != len(m.inputs):
            raise ValueError(
                f"graph takes {len(m.inputs)} inputs, got {len(xs)}"
            )
        env: Dict[int, Any] = dict(consts)
        for idx, x in zip(m.inputs, xs):
            t_in = m.tensors[idx]
            if np.issubdtype(np.dtype(t_in.dtype), np.integer) and \
                    t_in.quant is not None and t_in.quant.quantized:
                s = float(t_in.quant.scale[0])
                z = float(t_in.quant.zero_point[0])
                x = (x.astype(self.compute_dtype) - z) * s
            else:
                x = x.astype(self.compute_dtype)
            env[idx] = x

        for op in m.operators:
            o = op.options
            outs = op.outputs
            a = env[op.inputs[0]] if op.inputs and op.inputs[0] >= 0 else None
            if op.name == "CONV_2D":
                w = env[op.inputs[1]]  # [O, KH, KW, I] -> HWIO
                y = jax.lax.conv_general_dilated(
                    a, jnp.transpose(w, (1, 2, 3, 0)),
                    window_strides=(o["stride_h"], o["stride_w"]),
                    padding=o["padding"],
                    rhs_dilation=(o["dilation_h"], o["dilation_w"]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                if len(op.inputs) > 2 and op.inputs[2] >= 0:
                    y = y + env[op.inputs[2]]
                y = _act(y, o.get("activation"))
            elif op.name == "DEPTHWISE_CONV_2D":
                w = env[op.inputs[1]]  # [1, KH, KW, C*mult]
                cin = a.shape[-1]
                y = jax.lax.conv_general_dilated(
                    a, jnp.transpose(w, (1, 2, 0, 3)),  # HW1(C*mult)
                    window_strides=(o["stride_h"], o["stride_w"]),
                    padding=o["padding"],
                    rhs_dilation=(o["dilation_h"], o["dilation_w"]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=cin,
                )
                if len(op.inputs) > 2 and op.inputs[2] >= 0:
                    y = y + env[op.inputs[2]]
                y = _act(y, o.get("activation"))
            elif op.name in ("ADD", "MUL", "SUB"):
                b = env[op.inputs[1]]
                y = {"ADD": a + b, "MUL": a * b, "SUB": a - b}[op.name]
                y = _act(y, o.get("activation"))
            elif op.name in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
                win = (1, o["filter_h"], o["filter_w"], 1)
                strides = (1, o["stride_h"], o["stride_w"], 1)
                if op.name == "MAX_POOL_2D":
                    y = jax.lax.reduce_window(
                        a, -jnp.inf, jax.lax.max, win, strides, o["padding"]
                    )
                else:
                    y = jax.lax.reduce_window(
                        a, 0.0, jax.lax.add, win, strides, o["padding"]
                    )
                    ones = jnp.ones(a.shape[1:3], a.dtype)[None, :, :, None]
                    cnt = jax.lax.reduce_window(
                        ones, 0.0, jax.lax.add, win, strides, o["padding"]
                    )
                    y = y / cnt
                y = _act(y, o.get("activation"))
            elif op.name == "RESHAPE":
                shape = list(m.tensors[outs[0]].shape)
                if shape:
                    shape[0] = a.shape[0]  # batch-general
                y = jnp.reshape(a, shape)
            elif op.name == "SQUEEZE":
                y = jnp.reshape(a, m.tensors[outs[0]].shape)
            elif op.name == "SOFTMAX":
                y = jax.nn.softmax(a * o.get("beta", 1.0), axis=-1)
            elif op.name == "LOGISTIC":
                y = jax.nn.sigmoid(a)
            elif op.name == "RESIZE_BILINEAR":
                size = np.asarray(env[op.inputs[1]])
                y = _resize_bilinear(
                    a, int(size[0]), int(size[1]),
                    o.get("align_corners", False),
                    o.get("half_pixel_centers", False),
                )
            elif op.name == "CONCATENATION":
                y = jnp.concatenate(
                    [env[i] for i in op.inputs], axis=o.get("axis", -1)
                )
                y = _act(y, o.get("activation"))
            elif op.name == "FULLY_CONNECTED":
                w = env[op.inputs[1]]  # [out, in]
                y = a.reshape(a.shape[0], -1) @ w.T
                if len(op.inputs) > 2 and op.inputs[2] >= 0:
                    y = y + env[op.inputs[2]]
                y = _act(y, o.get("activation"))
            elif op.name == "MEAN":
                axes = tuple(int(v) for v in np.asarray(env[op.inputs[1]]))
                y = jnp.mean(a, axis=axes, keepdims=o.get("keep_dims", False))
            elif op.name == "PAD":
                pads = np.asarray(env[op.inputs[1]])
                y = jnp.pad(a, [(int(lo), int(hi)) for lo, hi in pads])
            elif op.name == "DEQUANTIZE":
                y = a  # constants were dequantized at build time
            else:
                raise NotImplementedError(
                    f"tflite op {op.name} (code {op.opcode})"
                )
            if self.fake_quant:
                y = _qdq(y, m.tensors[outs[0]])
            env[outs[0]] = y

        outs = []
        for oi in m.outputs:
            y = env[oi]
            t = m.tensors[oi]
            if self.fake_quant and t.quant is not None and t.quant.quantized \
                    and np.dtype(t.dtype).type in _QRANGE:
                pass  # already on the grid, in float — leave dequantized
            outs.append(y.astype(jnp.float32))
        return outs

    def trace(self, *xs):
        """Unjitted traceable body — embed the program inside a larger
        jit (e.g. the jax backend fuses pre/post ops around it)."""
        return self._run(self._consts, xs)

    def __call__(self, *xs):
        return self._fn(*(jnp.asarray(x) for x in xs))


def compile_tflite(path: str, **kw) -> TFLiteProgram:
    """Parse + compile a .tflite file to a TPU-ready program."""
    return TFLiteProgram(path, **kw)
