"""Developer tools (reference tools/development/, SURVEY.md §2.5):
codegen (custom-plugin scaffolds), confchk (config sanity checker),
pbtxt (pipeline description → mediapipe-style pbtxt). Each runs as
``python -m nnstreamer_tpu.tools.<name>``."""
