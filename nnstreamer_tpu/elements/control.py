"""Control-flow elements: tensor_if (data-dependent branch), tensor_crop
(crop by a detection stream), tensor_repo{sink,src} (feedback loops).

Reference: gsttensor_if.c (compared-value/operator/actions,
gsttensor_if.h:79-90 + custom cb include/tensor_if.h), gsttensor_crop.c
(crop raw stream by another stream's region tensors, flexible output),
gsttensor_repo{,sink,src}.c (slot-indexed global repository enabling
RNN/LSTM cycles outside the pad graph).

TPU note: tensor_if and crop force device→host syncs on *small* tensors
(the condition scalar / the crop boxes) — the big payload stays device-
resident; this matches SURVEY.md §7's guidance on data-dependent control.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    HostElement,
    NegotiationError,
    PropSpec,
    Routing,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors import data as tdata
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorSpec, TensorsSpec

# ---------------------------------------------------------------------------
# tensor_if

_if_custom_lock = threading.Lock()
_if_custom: Dict[str, Callable] = {}


def register_if_condition(name: str, fn: Callable[[Frame], bool]) -> None:
    """nnstreamer_if_custom_register analogue (include/tensor_if.h:30-37)."""
    with _if_custom_lock:
        _if_custom[name] = fn


def unregister_if_condition(name: str) -> bool:
    with _if_custom_lock:
        return _if_custom.pop(name, None) is not None


_OPERATORS = (
    "EQ", "NE", "GT", "GE", "LT", "LE",
    "RANGE_INCLUSIVE", "RANGE_EXCLUSIVE",
    "NOT_IN_RANGE_INCLUSIVE", "NOT_IN_RANGE_EXCLUSIVE",
)
_ACTIONS = (
    "PASSTHROUGH", "SKIP", "FILL_ZERO", "FILL_VALUES",
    "FILL_WITH_FILE", "FILL_WITH_FILE_RPT",
    "REPEAT_PREVIOUS_FRAME", "TENSORPICK",
)


@registry.element("tensor_if")
class TensorIf(HostElement):
    """Per-frame predicate with then/else actions (single src pad; build
    exclusive branches with two complementary tensor_if + join, as the
    reference does).

    Props: compared-value {A_VALUE, TENSOR_AVERAGE_VALUE, CUSTOM},
    compared-value-option (A_VALUE: 'D1:D2:D3:D4,N' innermost-first coords
    + tensor index; TENSOR_AVERAGE_VALUE: tensor index; CUSTOM: registered
    name), operator (10 ops), supplied-value 'V' or 'V1:V2' (ranges),
    then / then-option, else / else-option.
    """

    FACTORY_NAME = "tensor_if"

    PROPERTIES = {
        "compared-value": PropSpec(
            "enum", "A_VALUE",
            ("A_VALUE", "TENSOR_AVERAGE_VALUE", "CUSTOM"),
        ),
        "compared-value-option": PropSpec("str", "0,0"),
        "operator": PropSpec("str", "GT", desc="EQ/NE/GT/GE/LT/LE/..."),
        "supplied-value": PropSpec("str", "0", desc="'V' or 'V1:V2' range"),
        "then": PropSpec("str", "PASSTHROUGH"),
        "then-option": PropSpec("str", ""),
        "else": PropSpec("str", "SKIP"),
        "else-option": PropSpec("str", ""),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.cv = str(self.get_property("compared-value", "A_VALUE")).upper()
        self.cv_option = str(self.get_property("compared-value-option", "0,0"))
        self.operator = str(self.get_property("operator", "GT")).upper()
        sv = str(self.get_property("supplied-value", "0"))
        self.supplied = [float(x) for x in sv.split(":") if x != ""]
        self.then_action = str(self.get_property("then", "PASSTHROUGH")).upper()
        self.then_option = str(self.get_property("then-option", ""))
        self.else_action = str(self.get_property("else", "SKIP")).upper()
        self.else_option = str(self.get_property("else-option", ""))
        if self.operator not in _OPERATORS:
            raise ValueError(f"{self.name}: unknown operator {self.operator}")
        for a in (self.then_action, self.else_action):
            if a not in _ACTIONS:
                raise ValueError(f"{self.name}: unknown action {a}")
        self._prev: Optional[Frame] = None
        self._skipped = 0
        self._file_cache: dict = {}

    def _file_blob(self, path: str) -> bytes:
        if not path:
            raise RuntimeError(
                f"{self.name}: FILL_WITH_FILE needs then/else-option=<path>"
            )
        blob = self._file_cache.get(path)
        if blob is None:
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as exc:
                raise RuntimeError(
                    f"{self.name}: cannot read fill file {path}: {exc}"
                ) from exc
            self._file_cache[path] = blob
        return blob

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        # TENSORPICK changes the output tensor list; both branches must
        # agree on the spec, so TENSORPICK output spec = picked subset and
        # the other branch must be SKIP (reference restriction)
        then_a, else_a = self.then_action, self.else_action
        if "TENSORPICK" in (then_a, else_a):
            if then_a == "TENSORPICK" and else_a == "TENSORPICK":
                if self.then_option != self.else_option:
                    raise NegotiationError(
                        f"{self.name}: then/else TENSORPICK options must match "
                        "(both branches share one output spec)"
                    )
            else:
                other = else_a if then_a == "TENSORPICK" else then_a
                if other != "SKIP":
                    raise NegotiationError(
                        f"{self.name}: TENSORPICK pairs only with SKIP or an "
                        "identical TENSORPICK"
                    )
            option = self.then_option if then_a == "TENSORPICK" else self.else_option
            picks = [int(x) for x in option.split(",") if x != ""]
            return [
                TensorsSpec(tuple(spec[i] for i in picks), spec.format, spec.rate)
            ]
        return [spec]

    # -- predicate ---------------------------------------------------------
    def _compared_value(self, frame: Frame) -> float:
        # SURVEY §7: data-dependent control flow syncs on SMALL values —
        # index/reduce the (possibly device-resident) tensor in place and
        # transfer one scalar, never the whole payload
        if self.cv == "A_VALUE":
            bits = self.cv_option.split(",")
            coords_ref = [int(x) for x in bits[0].split(":")] if bits[0] else [0]
            nth = int(bits[1]) if len(bits) > 1 else 0
            a = frame.tensors[nth]
            coords = tuple(reversed(coords_ref))  # innermost-first → canonical
            # pad missing leading coords with 0
            while len(coords) < a.ndim:
                coords = (0,) + coords
            if len(coords) > a.ndim:
                # reference pipelines always pass 4 coords (fixed uint32[4]
                # dims); excess *leading* (outermost) coords address the
                # padded 1-sized dims — valid only when 0
                extra, coords = coords[: len(coords) - a.ndim], coords[-a.ndim:]
                if any(c != 0 for c in extra):
                    raise RuntimeError(
                        f"{self.name}: compared-value-option coords "
                        f"{coords_ref} out of range for rank-{a.ndim} tensor"
                    )
            return float(a[coords])
        if self.cv == "TENSOR_AVERAGE_VALUE":
            # option = tensor index; tolerate the A_VALUE-style default
            # ("coords,N") an unset option falls back to
            nth = int((self.cv_option or "0").split(",")[-1])
            t = frame.tensors[nth]
            if hasattr(t, "devices"):  # jax array: reduce on device
                import jax
                import jax.numpy as jnp

                # match the host path's float64 accumulation when x64 is
                # on; otherwise accumulate in float32 (TPUs have no f64)
                # — the documented tolerance of the device branch
                acc = (
                    jnp.float64
                    if jax.config.jax_enable_x64
                    else jnp.float32
                )
                return float(jnp.mean(t, dtype=acc))
            return tdata.tensor_average(t)
        if self.cv == "CUSTOM":
            with _if_custom_lock:
                fn = _if_custom.get(self.cv_option)
            if fn is None:
                raise RuntimeError(
                    f"{self.name}: custom condition {self.cv_option!r} not registered"
                )
            return fn(frame)
        raise RuntimeError(f"{self.name}: unknown compared-value {self.cv}")

    def _test(self, v: float) -> bool:
        op = self.operator
        s = self.supplied
        if op in ("EQ", "NE", "GT", "GE", "LT", "LE"):
            return tdata.compare(v, op, s[0])
        if len(s) < 2:
            raise RuntimeError(f"{self.name}: range operator needs 'V1:V2'")
        lo, hi = min(s[0], s[1]), max(s[0], s[1])
        if op == "RANGE_INCLUSIVE":
            return lo <= v <= hi
        if op == "RANGE_EXCLUSIVE":
            return lo < v < hi
        if op == "NOT_IN_RANGE_INCLUSIVE":
            return not (lo <= v <= hi)
        if op == "NOT_IN_RANGE_EXCLUSIVE":
            return not (lo < v < hi)
        raise AssertionError(op)

    # -- actions -----------------------------------------------------------
    def _apply(self, frame: Frame, action: str, option: str) -> Optional[Frame]:
        if action == "PASSTHROUGH":
            out = frame
        elif action == "SKIP":
            return None
        elif action == "FILL_ZERO":
            out = frame.with_tensors(
                [np.zeros_like(np.asarray(t)) for t in frame.tensors]
            )
        elif action == "FILL_VALUES":
            val = float(option or 0)
            out = frame.with_tensors(
                [np.full_like(np.asarray(t), val) for t in frame.tensors]
            )
        elif action in ("FILL_WITH_FILE", "FILL_WITH_FILE_RPT"):
            # reference gsttensor_if.h: replace payload with file content;
            # plain variant zero-pads a short file, _RPT repeats it
            blob = self._file_blob(option)
            outs = []
            for t in frame.tensors:
                a = np.asarray(t)
                n = a.nbytes
                if action.endswith("_RPT") and blob:
                    raw = (blob * (-(-n // len(blob))))[:n]
                else:
                    raw = blob[:n].ljust(n, b"\0")
                outs.append(np.frombuffer(raw, a.dtype).reshape(a.shape))
            out = frame.with_tensors(outs)
        elif action == "REPEAT_PREVIOUS_FRAME":
            out = (
                self._prev.with_pts(frame.pts, frame.duration)
                if self._prev is not None
                else frame.with_tensors(
                    [np.zeros_like(np.asarray(t)) for t in frame.tensors]
                )
            )
        elif action == "TENSORPICK":
            picks = [int(x) for x in option.split(",") if x != ""]
            out = frame.with_tensors([frame.tensors[i] for i in picks])
        else:
            raise AssertionError(action)
        return out

    def process(self, frame: Frame) -> Optional[Frame]:
        cond = self._test(self._compared_value(frame)) if self.cv != "CUSTOM" else bool(
            self._compared_value(frame)
        )
        action, option = (
            (self.then_action, self.then_option)
            if cond
            else (self.else_action, self.else_option)
        )
        out = self._apply(frame, action, option)
        # Reference semantics (gsttensor_if.h): REPEAT_PREVIOUS_FRAME resends
        # the previous *output* frame, so remember what was emitted, not what
        # arrived.
        if out is not None:
            self._prev = out
        else:
            self._skipped += 1
        return out

    def drop_stats(self) -> dict:
        """Frame-accounting surface (Executor.totals)."""
        return {"if-skip": self._skipped}


# ---------------------------------------------------------------------------
# tensor_crop

@registry.element("tensor_crop")
class TensorCrop(Routing):
    """Crop a raw tensor stream by a region stream.

    sink 0 = raw (N,H,W,C); sink 1 = regions, flexible or static tensor of
    shape (num_objects, 4) with [x, y, w, h] per object (reference
    gsttensor_crop.c info format). Frames pair by arrival order (the
    reference pairs corresponding buffers the same way). Two modes:

    - default (reference-faithful): variable-size exact-pixel crops on
      HOST, one tensor per object, format=flexible output. Every frame
      pays a device→host readback of the full raw tensor AND re-triggers
      downstream compilation per crop shape — the composable form of the
      cascade, 2-3 orders of magnitude off the fused form on TPU.
    - ``out-size=W:H`` (+ ``max-crops=K``, default 16): DEVICE-RESIDENT
      crops — one jitted crop+resample (ops/image.crop_and_resize) maps
      every region to a canonical KxHxWxC batch entirely in HBM. Output
      spec is STATIC, so a downstream landmark filter compiles ONCE and
      runs all K crops as one MXU batch; region values never cross to
      host (they ride in ``meta["crop_regions"]`` as a device array;
      zero-size regions yield zeroed rows). This is the TPU-first form
      of gsttensor_crop.c's cascade and closes the element-vs-fused
      cliff (BENCH r2: 1.8 fps element vs 1547 fused).
    """

    FACTORY_NAME = "tensor_crop"
    N_SINKS = 2
    N_SRCS = 1

    PROPERTIES = {
        "out-size": PropSpec(
            "str", "", desc="'W:H' enables device-resident crop batch"
        ),
        "max-crops": PropSpec("int", 16),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._raw: deque = deque()
        self._info: deque = deque()
        out_size = str(self.get_property("out-size", "") or "")
        self.out_size: Optional[Tuple[int, int]] = None
        if out_size:
            w, _, h = out_size.partition(":")
            self.out_size = (int(w), int(h or w))  # (W, H)
        self.max_crops = int(self.get_property("max-crops", 16))
        self._jit_crop = None

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        raw, info = in_specs
        if not isinstance(raw, TensorsSpec) or raw.num_tensors != 1:
            raise NegotiationError(f"{self.name}: raw input must be one tensor")
        if raw[0].rank != 4:
            raise NegotiationError(f"{self.name}: raw must be NHWC, got {raw[0]}")
        if self.out_size is None:
            return [TensorsSpec(format=TensorFormat.FLEXIBLE, rate=raw.rate)]
        # device mode: static [K, outH, outW, C] spec — downstream
        # negotiates (and compiles) once
        if raw[0].shape[0] not in (1, None):
            raise NegotiationError(
                f"{self.name}: out-size mode crops one image per frame "
                f"(raw batch {raw[0].shape[0]})"
            )
        ow, oh = self.out_size
        out = TensorSpec((self.max_crops, oh, ow, raw[0].shape[3]), raw[0].dtype)
        self._build_jit_crop(raw[0].dtype)
        return [TensorsSpec.of(out, rate=raw.rate)]

    def _build_jit_crop(self, dtype) -> None:
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.ops.image import crop_regions

        ow, oh = self.out_size
        k = self.max_crops
        np_dtype = dtype.np_dtype

        def fn(img, boxes):
            img = img[0]
            b = boxes.reshape(-1, 4).astype(jnp.float32)
            n = b.shape[0]
            b = b[:k] if n >= k else jnp.pad(b, ((0, k - n), (0, 0)))
            xyxy = jnp.concatenate([b[:, :2], b[:, :2] + b[:, 2:4]], axis=-1)
            # zero-size regions → zeroed rows, integer round+clip: the
            # shared tensor_crop conventions (ops/image.crop_regions —
            # one home for this epilogue, docs/on-device-ops.md)
            crops = crop_regions(
                img, xyxy, oh, ow,
                valid=(b[:, 2] > 0) & (b[:, 3] > 0), out_dtype=np_dtype,
            )
            return crops, b.astype(jnp.int32)

        self._jit_crop = jax.jit(fn)

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        (self._raw if pad == 0 else self._info).append(frame)
        out = []
        while self._raw and self._info:
            rf = self._raw.popleft()
            inf = self._info.popleft()
            crop = self._crop_device if self.out_size else self._crop_host
            out.append((0, crop(rf, inf)))
        return out

    def _crop_device(self, raw: Frame, info: Frame) -> Frame:
        crops, regions = self._jit_crop(raw.tensors[0], info.tensors[0])
        meta = dict(raw.meta)
        meta["crop_regions"] = regions  # device array — no host sync
        return Frame((crops,), pts=raw.pts, duration=raw.duration, meta=meta)

    def _crop_host(self, raw: Frame, info: Frame) -> Frame:
        img = np.asarray(raw.tensors[0])  # NHWC
        boxes = np.asarray(info.tensors[0]).reshape(-1, 4).astype(np.int64)
        _, h, w, _ = img.shape
        crops = []
        for x, y, bw, bh in boxes[:16]:  # max 16 tensors per frame
            x0, y0 = max(0, int(x)), max(0, int(y))
            x1, y1 = min(w, int(x) + int(bw)), min(h, int(y) + int(bh))
            if x1 <= x0 or y1 <= y0:
                continue
            crops.append(img[:, y0:y1, x0:x1, :])
        return Frame(
            tuple(crops), pts=raw.pts, duration=raw.duration, meta=dict(raw.meta)
        )


# ---------------------------------------------------------------------------
# tensor_repo: feedback loops

class _RepoSlot:
    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.frame: Optional[Frame] = None
        self.eos = False


class _TensorRepo:
    """Global slot-indexed frame repository (gsttensor_repo.c)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slots: Dict[int, _RepoSlot] = {}

    def slot(self, index: int) -> _RepoSlot:
        with self._lock:
            if index not in self._slots:
                self._slots[index] = _RepoSlot()
            return self._slots[index]

    def set(self, index: int, frame: Optional[Frame], eos: bool = False) -> None:
        s = self.slot(index)
        with s.cond:
            if frame is not None:
                s.frame = frame
            if eos:
                s.eos = True
            s.cond.notify_all()

    def get(self, index: int, timeout: float) -> Tuple[Optional[Frame], bool]:
        s = self.slot(index)
        with s.cond:
            if s.frame is None and not s.eos:
                s.cond.wait(timeout)
            f, s.frame = s.frame, None
            return f, s.eos

    def reset(self, index: int) -> None:
        with self._lock:
            self._slots.pop(index, None)


REPO = _TensorRepo()


@registry.element("tensor_reposink")
class TensorRepoSink(Sink):
    """Write frames into a repo slot (slot-index=N)."""

    FACTORY_NAME = "tensor_reposink"

    PROPERTIES = {
        "slot-index": PropSpec("int", 0),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.slot_index = int(self.get_property("slot-index", 0))

    def render(self, frame: Frame) -> None:
        REPO.set(self.slot_index, frame)

    def on_eos(self) -> None:
        REPO.set(self.slot_index, None, eos=True)


@registry.element("tensor_reposrc")
class TensorRepoSrc(Source):
    """Read frames from a repo slot. Emits one zero frame first when the
    slot is empty (bootstrap for RNN-style cycles, reference reposrc dummy
    buffer). Props: slot-index, dimensions, types."""

    FACTORY_NAME = "tensor_reposrc"

    PROPERTIES = {
        "slot-index": PropSpec("int", 0),
        "dimensions": PropSpec("str", "1"),
        "types": PropSpec("str", "float32"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.slot_index = int(self.get_property("slot-index", 0))
        self.spec = TensorsSpec.from_strings(
            str(self.get_property("dimensions", "1")),
            str(self.get_property("types", "float32")),
        )
        self._bootstrapped = False

    def output_spec(self) -> Spec:
        return self.spec

    def start(self) -> None:
        self._bootstrapped = False

    def generate(self):
        if not self._bootstrapped:
            self._bootstrapped = True
            return Frame(
                tuple(np.zeros(t.shape, t.dtype.np_dtype) for t in self.spec)
            )
        frame, eos = REPO.get(self.slot_index, timeout=0.1)
        if frame is not None:
            return frame
        if eos:
            return EOS_FRAME
        return None  # poll again (keeps stop event responsive)
