"""Routing elements: tensor_mux, tensor_demux, tensor_merge, tensor_split,
join — N↔M stream combination with timestamp sync policies.

Reference: gsttensor_mux.c / gsttensor_demux.c / gsttensor_merge.c /
gsttensor_split.c / gst/join/gstjoin.c; sync policy semantics from
Documentation/synchronization-policies-at-mux-merge.md and the shared impl
gst_tensor_time_sync_* (nnstreamer_plugin_api_impl.c:20-198).

Sync policies (sync-mode property):
- nosync  — combine in arrival order.
- slowest — output at the slowest pad's cadence: wait for every pad, take
  the largest head timestamp as base, drop older frames on faster pads.
- basepad — like slowest but one designated pad (sync-option=PAD:DURATION)
  is the base.
- refresh — emit on every new frame on any pad, reusing the last frame of
  the others.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import NegotiationError, PropSpec, Routing, Spec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import (
    NNS_TENSOR_SIZE_LIMIT,
    TensorSpec,
    TensorsSpec,
)

_POLICIES = ("nosync", "slowest", "basepad", "refresh")


class SyncCombiner:
    """Shared timestamp-sync machinery for mux/merge."""

    def __init__(self, mode: str, option: str, n_pads: int) -> None:
        if mode not in _POLICIES:
            raise NegotiationError(f"unknown sync-mode {mode!r}")
        self.mode = mode
        self.n = n_pads
        self.queues: List[Deque[Frame]] = [deque() for _ in range(n_pads)]
        self.last: List[Optional[Frame]] = [None] * n_pads
        self.eos: List[bool] = [False] * n_pads
        self.base_pad = 0
        self.base_slack = 0
        if mode == "basepad" and option:
            bits = option.split(":")
            self.base_pad = int(bits[0])
            if len(bits) > 1:
                self.base_slack = int(bits[1])
        if not (0 <= self.base_pad < n_pads):
            raise NegotiationError(
                f"basepad index {self.base_pad} out of range for {n_pads} pads"
            )

    def push(self, pad: int, frame: Frame) -> List[List[Frame]]:
        """Feed one frame; return list of combined frame-groups ready."""
        if self.mode == "refresh":
            return self._refresh_push(pad, frame)
        self.queues[pad].append(frame)
        self.last[pad] = frame
        out = []
        while True:
            group = self._try_combine(pad)
            if group is None:
                break
            out.append(group)
        return out

    def mark_eos(self, pad: int) -> List[List[Frame]]:
        """A pad reached EOS; release any groups it was gating."""
        self.eos[pad] = True
        if self.mode == "refresh":
            return self._refresh_drain()
        out = []
        while True:
            group = self._try_combine(pad)
            if group is None:
                return out
            out.append(group)

    def _refresh_primed(self) -> bool:
        return all(l is not None for l in self.last)

    def _refresh_push(self, pad: int, frame: Frame) -> List[List[Frame]]:
        """SYNC_REFRESH: once every pad has delivered ("primed"), a new
        frame on ANY pad emits a group immediately, the other pads
        contributing their last (possibly stale) frame — the reference
        marks refresh collect-pads non-waiting
        (nnstreamer_plugin_api_impl.c SYNC_REFRESH pop/reuse path), so a
        fast pad is never gated on a slow one and nothing queues after
        priming (a live mixed-rate mux stays bounded at one frame per
        pad). Priming itself is PTS-merged lock-step (below) — the one
        deliberate divergence (docs/PARITY.md): the reference's pre-roll
        also waits on every pad, but in arrival order; merging by PTS
        keeps the executor's racing source threads out of golden
        outputs."""
        if self._refresh_primed():
            self.last[pad] = frame
            return [list(self.last)]
        self.queues[pad].append(frame)
        return self._refresh_drain()

    def _refresh_drain(self) -> List[List[Frame]]:
        """PTS-merged drain of queued (pre-priming) frames: pads'
        timelines merge in pts order, one group per distinct instant,
        each pad contributing its newest frame at-or-before that
        instant. Instants before every pad has delivered produce no
        output (priming); once primed, remaining queued frames emit
        per-instant without any gate."""
        out: List[List[Frame]] = []
        while True:
            if not self._refresh_primed() and any(
                not self.queues[i] and not self.eos[i] for i in range(self.n)
            ):
                return out  # still priming and a pad may yet deliver
            heads = [
                (-1 if q[0].pts is None else q[0].pts, i)
                for i, q in enumerate(self.queues)
                if q
            ]
            if not heads:
                return out
            t = min(h[0] for h in heads)
            for pts, i in heads:
                if pts == t:
                    self.last[i] = self.queues[i].popleft()
            if self._refresh_primed():
                out.append(list(self.last))

    def _try_combine(self, trigger_pad: int) -> Optional[List[Frame]]:
        if any(not q for q in self.queues):
            return None
        if self.mode == "nosync":
            return [q.popleft() for q in self.queues]
        # slowest / basepad: pick base timestamp, drop stale frames
        if self.mode == "slowest":
            base_ts = max(
                (q[0].pts for q in self.queues if q[0].pts is not None),
                default=None,
            )
        else:
            base_ts = self.queues[self.base_pad][0].pts
        if base_ts is None:
            return [q.popleft() for q in self.queues]  # untimed: arrival order
        # phase 1: drop stale frames and check viability WITHOUT popping
        # heads — an abort must leave every queue intact
        for q in self.queues:
            # drop frames that are definitely older than base (their
            # successor is still ≤ base): keeps the closest-not-newer frame
            while len(q) > 1 and q[1].pts is not None and q[1].pts <= base_ts:
                q.popleft()
            head = q[0]
            # basepad's DURATION option widens the match window: a head
            # within [base_ts - slack, base_ts] pairs immediately instead of
            # waiting for a closer frame (reference
            # gst_tensor_time_sync_buffer duration-window matching).
            if (
                head.pts is not None
                and head.pts < base_ts - self.base_slack
                and len(q) <= 1
            ):
                # not enough data to know if a closer frame is coming
                return None
        # phase 2: all pads viable — pop the group atomically
        return [q.popleft() for q in self.queues]


def _combined_pts(group: List[Frame]) -> Tuple[Optional[int], Optional[int]]:
    pts = max((f.pts for f in group if f.pts is not None), default=None)
    dur = group[0].duration
    return pts, dur


def _combined_rate(mode: str, base_pad: int, in_specs):
    """Output cadence by sync policy: slowest → min pad rate, basepad → the
    base pad's rate, refresh → max (emits per any new frame), nosync →
    first known."""
    rates = [s.rate for s in in_specs if getattr(s, "rate", None) is not None]
    if not rates:
        return None
    if mode == "slowest":
        return min(rates)
    if mode == "basepad":
        return getattr(in_specs[base_pad], "rate", None) or rates[0]
    if mode == "refresh":
        return max(rates)
    return rates[0]


@registry.element("tensor_mux")
class TensorMux(Routing):
    """N × other/tensors → 1 frame with the tensor lists concatenated
    (num_tensors grows; reference gsttensor_mux.c)."""

    FACTORY_NAME = "tensor_mux"
    N_SINKS = None
    N_SRCS = 1

    PROPERTIES = {
        "sync-mode": PropSpec(
            "enum", "slowest", ("nosync", "slowest", "basepad", "refresh")
        ),
        "sync-option": PropSpec(
            "str", "", desc="basepad: 'PAD' or 'PAD:DURATION' slack"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.sync_mode = str(self.get_property("sync-mode", "slowest"))
        self.sync_option = str(self.get_property("sync-option", ""))
        self._comb: Optional[SyncCombiner] = None

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        tensors: List[TensorSpec] = []
        for s in in_specs:
            if not isinstance(s, TensorsSpec):
                raise NegotiationError(f"{self.name}: non-tensor input {s}")
            tensors.extend(s.tensors)
        if len(tensors) > NNS_TENSOR_SIZE_LIMIT:
            raise NegotiationError(
                f"{self.name}: combined {len(tensors)} tensors exceeds limit"
            )
        self._comb = SyncCombiner(self.sync_mode, self.sync_option, self._n_sinks)
        rate = _combined_rate(self.sync_mode, self._comb.base_pad, in_specs)
        return [TensorsSpec(tuple(tensors), rate=rate)]

    def _frames(self, groups) -> List[Tuple[int, Frame]]:
        out = []
        for group in groups:
            tensors = tuple(t for f in group for t in f.tensors)
            pts, dur = _combined_pts(group)
            meta = {}
            for f in group:
                meta.update(f.meta)
            out.append((0, Frame(tensors, pts=pts, duration=dur, meta=meta)))
        return out

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        return self._frames(self._comb.push(pad, frame))

    def eos(self, pad: int) -> List[Tuple[int, Frame]]:
        # refresh groups gated on this pad having data release at its EOS
        return self._frames(self._comb.mark_eos(pad))


@registry.element("tensor_merge")
class TensorMerge(Routing):
    """N single-tensor streams → 1 tensor concatenated along a dimension
    (mode=linear option=<ref dim index>; reference gsttensor_merge.c)."""

    FACTORY_NAME = "tensor_merge"
    N_SINKS = None
    N_SRCS = 1

    PROPERTIES = {
        "mode": PropSpec("enum", "linear", ("linear",)),
        "option": PropSpec("int", 0, desc="reference dim index to merge on"),
        "sync-mode": PropSpec(
            "enum", "slowest", ("nosync", "slowest", "basepad", "refresh")
        ),
        "sync-option": PropSpec(
            "str", "", desc="basepad: 'PAD' or 'PAD:DURATION' slack"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        mode = str(self.get_property("mode", "linear"))
        if mode != "linear":
            raise ValueError(f"{self.name}: only mode=linear supported, got {mode}")
        self.ref_dim = int(self.get_property("option", 0))
        self.sync_mode = str(self.get_property("sync-mode", "slowest"))
        self.sync_option = str(self.get_property("sync-option", ""))
        self._comb: Optional[SyncCombiner] = None
        self._axis: int = 0

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        specs: List[TensorSpec] = []
        for s in in_specs:
            if not isinstance(s, TensorsSpec) or s.num_tensors != 1:
                raise NegotiationError(
                    f"{self.name}: each input must be a single tensor, got {s}"
                )
            specs.append(s[0])
        rank = specs[0].rank
        self._axis = rank - 1 - self.ref_dim
        if not (0 <= self._axis < rank):
            raise NegotiationError(f"{self.name}: merge dim {self.ref_dim} out of range")
        base = list(specs[0].shape)
        total = 0
        for t in specs:
            if t.rank != rank or t.dtype != specs[0].dtype:
                raise NegotiationError(f"{self.name}: incompatible inputs")
            for ax in range(rank):
                if ax != self._axis and t.shape[ax] != base[ax]:
                    raise NegotiationError(
                        f"{self.name}: shape mismatch on non-merge axis {ax}"
                    )
            total += t.shape[self._axis]
        base[self._axis] = total
        self._comb = SyncCombiner(self.sync_mode, self.sync_option, self._n_sinks)
        rate = _combined_rate(self.sync_mode, self._comb.base_pad, in_specs)
        return [TensorsSpec.of(TensorSpec(tuple(base), specs[0].dtype), rate=rate)]

    def _frames(self, groups) -> List[Tuple[int, Frame]]:
        import jax.numpy as jnp

        out = []
        for group in groups:
            merged = jnp.concatenate([f.tensors[0] for f in group], axis=self._axis)
            pts, dur = _combined_pts(group)
            out.append((0, Frame((merged,), pts=pts, duration=dur)))
        return out

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        return self._frames(self._comb.push(pad, frame))

    def eos(self, pad: int) -> List[Tuple[int, Frame]]:
        return self._frames(self._comb.mark_eos(pad))


@registry.element("tensor_demux")
class TensorDemux(Routing):
    """1 multi-tensor stream → N streams. tensorpick selects/reorders:
    'tensorpick=0,2' or grouped 'tensorpick=0:1,2' (reference
    gsttensor_demux.c)."""

    FACTORY_NAME = "tensor_demux"
    N_SINKS = 1
    N_SRCS = None

    PROPERTIES = {
        "tensorpick": PropSpec(
            "str", "", desc="select/reorder: '0,2' or grouped '0:1,2'"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        pick = str(self.get_property("tensorpick", ""))
        self.picks: Optional[List[List[int]]] = None
        if pick:
            self.picks = [
                [int(x) for x in grp.split(":")] for grp in pick.split(",") if grp
            ]

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        picks = self.picks or [[i] for i in range(spec.num_tensors)]
        if len(picks) != self._n_srcs:
            raise NegotiationError(
                f"{self.name}: {len(picks)} pick groups vs {self._n_srcs} linked pads"
            )
        outs = []
        for grp in picks:
            for i in grp:
                if i >= spec.num_tensors:
                    raise NegotiationError(f"{self.name}: pick {i} out of range")
            outs.append(
                TensorsSpec(tuple(spec[i] for i in grp), spec.format, spec.rate)
            )
        self._resolved_picks = picks
        return outs

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        return [
            (p, frame.with_tensors([frame.tensors[i] for i in grp]))
            for p, grp in enumerate(self._resolved_picks)
        ]


@registry.element("tensor_split")
class TensorSplit(Routing):
    """1 tensor → N tensors split along a dim. tensorseg gives per-output
    sizes along the split axis: 'tensorseg=2:4:4:1,1:4:4:1' (reference
    gsttensor_split.c)."""

    FACTORY_NAME = "tensor_split"
    N_SINKS = 1
    N_SRCS = None

    PROPERTIES = {
        "tensorseg": PropSpec(
            "str", None, desc="per-output dims along the split axis"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        seg = str(self.get_property("tensorseg", ""))
        if not seg:
            raise ValueError(f"{self.name}: tensor_split needs tensorseg=")
        from nnstreamer_tpu.tensors.spec import parse_dimension

        self.segs = [parse_dimension(s) for s in seg.split(",") if s]

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec) or spec.num_tensors != 1:
            raise NegotiationError(f"{self.name}: needs a single-tensor input")
        t = spec[0]
        if len(self.segs) != self._n_srcs:
            raise NegotiationError(
                f"{self.name}: {len(self.segs)} segments vs {self._n_srcs} pads"
            )
        # find the split axis: the one where segment sizes sum to the input
        rank = t.rank
        axis = None
        for ax in range(rank):
            if all(len(s) == rank for s in self.segs) and sum(
                s[ax] for s in self.segs
            ) == t.shape[ax] and all(
                s[a2] == t.shape[a2] for s in self.segs for a2 in range(rank) if a2 != ax
            ):
                axis = ax
                break
        if axis is None:
            raise NegotiationError(
                f"{self.name}: tensorseg {self.segs} does not tile input {t.shape}"
            )
        self._axis = axis
        self._sizes = [s[axis] for s in self.segs]
        return [
            TensorsSpec.of(TensorSpec(tuple(s), t.dtype), rate=spec.rate)
            for s in self.segs
        ]

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        import jax.numpy as jnp

        x = frame.tensors[0]
        out = []
        offset = 0
        for p, size in enumerate(self._sizes):
            sl = [slice(None)] * x.ndim
            sl[self._axis] = slice(offset, offset + size)
            out.append((p, frame.with_tensors((jnp.asarray(x)[tuple(sl)],))))
            offset += size
        return out


@registry.element("join")
class Join(Routing):
    """N→1 first-come-forward (no sync): whichever pad delivers, forwards.
    For exclusive branches after tensor_if (reference gst/join/gstjoin.c —
    unlike funnel, only the active branch forwards; here branches are
    exclusive by construction when upstream used SKIP actions)."""

    FACTORY_NAME = "join"
    N_SINKS = None
    N_SRCS = 1

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        first = in_specs[0]
        for s in in_specs[1:]:
            if isinstance(first, TensorsSpec) and isinstance(s, TensorsSpec):
                if not first.is_compatible(s):
                    raise NegotiationError(
                        f"{self.name}: branch specs differ: {first} vs {s}"
                    )
        return [first]

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        return [(0, frame)]
