"""tensor_src_iio: Linux Industrial-I/O sensor source.

Reference: gst/nnstreamer/elements/gsttensor_srciio.c (2604 LoC) — scans
/sys/bus/iio/devices for iio:deviceN entries, resolves a device by name or
number, enumerates in_*_raw scan channels, configures sampling frequency,
and merges enabled channels into one tensor per capture (registration is
Linux-only, registerer/nnstreamer.c:113-119).

TPU-native redesign: the sysfs scanning/config logic is host-side and
stays faithful (same device/channel resolution, scale/offset application:
value = (raw + offset) * scale). Two capture modes:

- ``mode=oneshot`` (default): poll in_<ch>_raw at ``frequency`` Hz with a
  bounded wait so the executor's stop event is honored (the reference's
  poll() timeout, gsttensor_srciio.c:379-381).
- ``mode=buffer``: the /dev/iio:deviceN character-device path
  (gsttensor_srciio.c:2511) — enables scan_elements channels
  (in_<ch>_en), parses each channel's packed format from in_<ch>_type
  (``le:s12/16>>0`` = endianness : sign realbits / storagebits >> shift),
  orders by in_<ch>_index, sets buffer/length and buffer/enable, then
  reads fixed-size records from the device node and decodes them
  vectorized with numpy (mask, shift, sign-extend).

``base-dir`` points the scanner at any sysfs root and ``dev-dir`` at the
device-node directory, which is how tests provide a fake device tree
(the reference tests do the same with mock sysfs dirs).

Output: one float32 tensor [1, n_channels] per capture (merge-channels
layout), framerate = frequency; pts in integer nanoseconds.
"""

from __future__ import annotations

import os
import re
import time
from fractions import Fraction
from typing import Dict, List, Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    ElementError,
    NegotiationError,
    PropSpec,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

import numpy as np

DEFAULT_BASE_DIR = "/sys/bus/iio/devices"
DEFAULT_DEV_DIR = "/dev"
_CHANNEL_RE = re.compile(r"^in_(.+)_raw$")
_SCAN_EN_RE = re.compile(r"^in_(.+)_en$")
# scan_elements type string: "le:s12/16>>4" (IIO ABI buffer format)
_TYPE_RE = re.compile(r"^(be|le):(s|u)(\d+)/(\d+)>>(\d+)$")


class ChannelFormat:
    """One scan_elements channel's packed wire format."""

    def __init__(self, type_str: str) -> None:
        m = _TYPE_RE.match(type_str.strip())
        if not m:
            raise ElementError(f"bad IIO channel type {type_str!r}")
        endian, sign, real, storage, shift = m.groups()
        self.big_endian = endian == "be"
        self.signed = sign == "s"
        self.realbits = int(real)
        self.storagebits = int(storage)
        self.shift = int(shift)
        if self.storagebits % 8 or self.storagebits not in (8, 16, 32, 64):
            raise ElementError(f"unsupported storage bits in {type_str!r}")
        self.nbytes = self.storagebits // 8

    def decode(self, raw: np.ndarray) -> np.ndarray:
        """uint storage words → float32 channel values (shift, mask to
        realbits, sign-extend)."""
        v = (raw >> np.uint64(self.shift)) & np.uint64((1 << self.realbits) - 1)
        v = v.astype(np.int64)
        if self.signed:
            sign_bit = np.int64(1) << (self.realbits - 1)
            v = (v ^ sign_bit) - sign_bit
        return v.astype(np.float32)


def _read(path: str, default: Optional[str] = None) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return default


def scan_devices(base_dir: str = DEFAULT_BASE_DIR) -> Dict[str, str]:
    """name → device dir for every iio:deviceN under base_dir."""
    out: Dict[str, str] = {}
    try:
        entries = sorted(os.listdir(base_dir))
    except OSError:
        return out
    for entry in entries:
        if not entry.startswith("iio:device"):
            continue
        d = os.path.join(base_dir, entry)
        name = _read(os.path.join(d, "name"), entry)
        out[name] = d
    return out


@registry.element("tensor_src_iio")
class TensorSrcIIO(Source):
    """Props: device (name), device-number, frequency (Hz, default 10),
    channels (comma list of channel names, default all), num-frames
    (-1 = endless), mode=oneshot|buffer (buffer = packed records from the
    /dev/iio:deviceN node via scan_elements), buffer-length,
    base-dir (sysfs root) / dev-dir (node dir) for tests/containers."""

    FACTORY_NAME = "tensor_src_iio"

    PROPERTIES = {
        "device": PropSpec("str", None, desc="iio device name"),
        "device-number": PropSpec("int", None),
        "frequency": PropSpec("float", 10.0, desc="sampling rate (Hz)"),
        "num-frames": PropSpec("int", -1, desc="-1 = endless"),
        "mode": PropSpec("enum", "oneshot", ("oneshot", "buffer")),
        "buffer-length": PropSpec("int", 16),
        "channels": PropSpec("str", "", desc="comma list; empty = all"),
        "base-dir": PropSpec("str", None, desc="sysfs root override"),
        "dev-dir": PropSpec("str", None, desc="device node dir override"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.base_dir = str(self.get_property("base-dir", DEFAULT_BASE_DIR))
        self.dev_dir = str(self.get_property("dev-dir", DEFAULT_DEV_DIR))
        self.device = self.get_property("device", None)
        self.device_number = self.get_property("device-number", None)
        self.frequency = float(self.get_property("frequency", 10.0))
        self.num_frames = int(self.get_property("num-frames", -1))
        self.mode = str(self.get_property("mode", "oneshot"))
        if self.mode not in ("oneshot", "buffer"):
            raise ElementError(f"{self.name}: mode must be oneshot|buffer")
        self.buffer_length = int(self.get_property("buffer-length", 16))
        chans = str(self.get_property("channels", ""))
        self._want_channels = [c for c in chans.split(",") if c] or None
        self._dir: Optional[str] = None
        self._channels: List[str] = []
        self._scales: Optional[np.ndarray] = None
        self._offsets: Optional[np.ndarray] = None
        self._i = 0
        self._next_t: Optional[float] = None
        # buffered-mode state
        self._fd: Optional[int] = None
        self._formats: List[ChannelFormat] = []
        self._record_size = 0
        self._pending = b""

    # -- device resolution (reference: scan + match by name/number) --------
    def _resolve_buffer_channels(self) -> None:
        """Buffered mode: channels come from scan_elements (in_<ch>_en /
        _index / _type), ordered by index; enable the wanted set and the
        buffer (gsttensor_srciio.c buffered setup)."""
        scan_dir = os.path.join(self._dir, "scan_elements")
        if not os.path.isdir(scan_dir):
            raise ElementError(
                f"{self.name}: device has no scan_elements (no buffer support)"
            )
        found = sorted(
            m.group(1)
            for m in (_SCAN_EN_RE.match(f) for f in os.listdir(scan_dir))
            if m
        )
        want = self._want_channels or found
        missing = [c for c in want if c not in found]
        if missing:
            raise ElementError(f"{self.name}: scan channels not found: {missing}")

        def _write(path: str, value: str) -> None:
            try:
                with open(path, "w") as f:
                    f.write(value)
            except OSError:
                pass  # read-only fake sysfs trees are fine

        ordered = []
        for c in found:
            _write(os.path.join(scan_dir, f"in_{c}_en"), "1" if c in want else "0")
            if c not in want:
                continue
            idx_s = _read(os.path.join(scan_dir, f"in_{c}_index"), "0")
            type_s = _read(os.path.join(scan_dir, f"in_{c}_type"))
            if type_s is None:
                raise ElementError(f"{self.name}: missing in_{c}_type")
            ordered.append((int(idx_s), c, ChannelFormat(type_s)))
        ordered.sort()
        self._channels = [c for _, c, _ in ordered]
        self._formats = [f for _, _, f in ordered]
        # field layout: each element aligned to its own storage size, record
        # padded to the largest element's alignment (Linux IIO buffer ABI)
        off = 0
        self._field_offsets = []
        for f in self._formats:
            off = (off + f.nbytes - 1) // f.nbytes * f.nbytes
            self._field_offsets.append(off)
            off += f.nbytes
        align = max(f.nbytes for f in self._formats)
        self._record_size = (off + align - 1) // align * align
        _write(os.path.join(self._dir, "buffer", "length"),
               str(self.buffer_length))
        _write(os.path.join(self._dir, "buffer", "enable"), "1")

    def _resolve(self) -> None:
        if self._dir is not None:
            return
        if self.device_number is not None:
            d = os.path.join(self.base_dir, f"iio:device{int(self.device_number)}")
            if not os.path.isdir(d):
                raise ElementError(f"{self.name}: no such IIO device dir {d}")
            self._dir = d
        else:
            devices = scan_devices(self.base_dir)
            if not devices:
                raise ElementError(
                    f"{self.name}: no IIO devices under {self.base_dir}"
                )
            if self.device is None:
                self._dir = next(iter(devices.values()))
            elif str(self.device) in devices:
                self._dir = devices[str(self.device)]
            else:
                raise ElementError(
                    f"{self.name}: IIO device {self.device!r} not found; "
                    f"available: {sorted(devices)}"
                )
        if self.mode == "buffer":
            self._resolve_buffer_channels()
        else:
            found = sorted(
                m.group(1)
                for m in (_CHANNEL_RE.match(f) for f in os.listdir(self._dir))
                if m
            )
            if self._want_channels:
                missing = [c for c in self._want_channels if c not in found]
                if missing:
                    raise ElementError(
                        f"{self.name}: channels not found: {missing}"
                    )
                self._channels = list(self._want_channels)
            else:
                self._channels = found
        if not self._channels:
            raise ElementError(f"{self.name}: device has no capture channels")
        # per-channel scale/offset with device-wide fallback (IIO ABI)
        def per_channel(suffix: str, default: float) -> np.ndarray:
            dev_wide = _read(os.path.join(self._dir, f"in_{suffix}"))
            vals = []
            for c in self._channels:
                v = _read(os.path.join(self._dir, f"in_{c}_{suffix}"), dev_wide)
                vals.append(float(v) if v is not None else default)
            return np.asarray(vals, np.float32)

        self._scales = per_channel("scale", 1.0)
        self._offsets = per_channel("offset", 0.0)
        # push requested sampling frequency if the device exposes the knob
        freq_path = os.path.join(self._dir, "sampling_frequency")
        if os.path.isfile(freq_path) and os.access(freq_path, os.W_OK):
            try:
                with open(freq_path, "w") as f:
                    f.write(str(self.frequency))
            except OSError:
                pass

    def output_spec(self) -> Spec:
        self._resolve()
        rate = Fraction(self.frequency).limit_denominator(1000)
        return TensorsSpec.of(
            TensorSpec((1, len(self._channels)), DType.FLOAT32, name="iio"),
            rate=rate,
        )

    def _emit(self, raw: np.ndarray):
        data = ((raw + self._offsets) * self._scales).reshape(1, -1)
        pts = int(self._i * 1_000_000_000 / self.frequency)
        self._i += 1
        return Frame((data,), pts=pts,
                     duration=int(1_000_000_000 / self.frequency))

    def _generate_buffered(self):
        """Read one fixed-size record from the device node and decode it
        (the reference's poll()+read loop, gsttensor_srciio.c:2511)."""
        if self._fd is None:
            node = os.path.join(self.dev_dir, os.path.basename(self._dir))
            try:
                self._fd = os.open(node, os.O_RDONLY | os.O_NONBLOCK)
            except OSError as exc:
                raise ElementError(
                    f"{self.name}: cannot open IIO device node {node}: {exc}"
                )
        try:
            chunk = os.read(self._fd, self._record_size - len(self._pending))
        except BlockingIOError:
            chunk = b""
        if chunk:
            self._pending += chunk
        if len(self._pending) < self._record_size:
            if not chunk:
                time.sleep(0.01)  # bounded wait (reference poll timeout)
            return None
        rec, self._pending = (
            self._pending[: self._record_size],
            self._pending[self._record_size:],
        )
        raw = np.empty((len(self._channels),), np.float32)
        for j, (fmt, off) in enumerate(zip(self._formats, self._field_offsets)):
            word = int.from_bytes(
                rec[off : off + fmt.nbytes],
                "big" if fmt.big_endian else "little",
            )
            raw[j] = fmt.decode(np.asarray([word], np.uint64))[0]
        return self._emit(raw)

    def generate(self):
        if self.num_frames >= 0 and self._i >= self.num_frames:
            return EOS_FRAME
        if self.mode == "buffer":
            return self._generate_buffered()
        now = time.monotonic()
        if self._next_t is None:
            self._next_t = now
        if now < self._next_t:
            # bounded wait so the executor can stop us (reference poll timeout)
            time.sleep(min(self._next_t - now, 0.1))
            if time.monotonic() < self._next_t:
                return None
        self._next_t += 1.0 / self.frequency
        raw = np.empty((len(self._channels),), np.float32)
        for j, c in enumerate(self._channels):
            v = _read(os.path.join(self._dir, f"in_{c}_raw"), "0")
            try:
                raw[j] = float(v)
            except ValueError:
                raise ElementError(f"{self.name}: bad raw value {v!r} for {c}")
        return self._emit(raw)

    def stop(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        if self.mode == "buffer" and self._dir is not None:
            try:
                with open(os.path.join(self._dir, "buffer", "enable"), "w") as f:
                    f.write("0")
            except OSError:
                pass
