"""tensor_src_iio: Linux Industrial-I/O sensor source.

Reference: gst/nnstreamer/elements/gsttensor_srciio.c (2604 LoC) — scans
/sys/bus/iio/devices for iio:deviceN entries, resolves a device by name or
number, enumerates in_*_raw scan channels, configures sampling frequency,
and merges enabled channels into one tensor per capture (registration is
Linux-only, registerer/nnstreamer.c:113-119).

TPU-native redesign: the sysfs scanning/config logic is host-side and
stays faithful (same device/channel resolution, scale/offset application:
value = (raw + offset) * scale); the capture loop is the polled one-shot
path (reading in_<ch>_raw at ``frequency`` Hz with a bounded wait, so the
executor's stop event is honored — the reference's poll() timeout,
gsttensor_srciio.c:379-381). The buffered /dev/iio:deviceN character-device
path needs kernel trigger support and is intentionally not emulated; a
``base-dir`` property points the scanner at any sysfs root, which is how
tests provide a fake device tree (the reference tests do the same with
mock sysfs dirs).

Output: one float32 tensor [1, n_channels] per capture (merge-channels
layout), framerate = frequency.
"""

from __future__ import annotations

import os
import re
import time
from fractions import Fraction
from typing import Dict, List, Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import ElementError, NegotiationError, Source, Spec
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

import numpy as np

DEFAULT_BASE_DIR = "/sys/bus/iio/devices"
_CHANNEL_RE = re.compile(r"^in_(.+)_raw$")


def _read(path: str, default: Optional[str] = None) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return default


def scan_devices(base_dir: str = DEFAULT_BASE_DIR) -> Dict[str, str]:
    """name → device dir for every iio:deviceN under base_dir."""
    out: Dict[str, str] = {}
    try:
        entries = sorted(os.listdir(base_dir))
    except OSError:
        return out
    for entry in entries:
        if not entry.startswith("iio:device"):
            continue
        d = os.path.join(base_dir, entry)
        name = _read(os.path.join(d, "name"), entry)
        out[name] = d
    return out


@registry.element("tensor_src_iio")
class TensorSrcIIO(Source):
    """Props: device (name), device-number, frequency (Hz, default 10),
    channels (comma list of channel names, default all), num-frames
    (-1 = endless), base-dir (sysfs root, for tests/containers)."""

    FACTORY_NAME = "tensor_src_iio"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.base_dir = str(self.get_property("base-dir", DEFAULT_BASE_DIR))
        self.device = self.get_property("device", None)
        self.device_number = self.get_property("device-number", None)
        self.frequency = float(self.get_property("frequency", 10.0))
        self.num_frames = int(self.get_property("num-frames", -1))
        chans = str(self.get_property("channels", ""))
        self._want_channels = [c for c in chans.split(",") if c] or None
        self._dir: Optional[str] = None
        self._channels: List[str] = []
        self._scales: Optional[np.ndarray] = None
        self._offsets: Optional[np.ndarray] = None
        self._i = 0
        self._next_t: Optional[float] = None

    # -- device resolution (reference: scan + match by name/number) --------
    def _resolve(self) -> None:
        if self._dir is not None:
            return
        if self.device_number is not None:
            d = os.path.join(self.base_dir, f"iio:device{int(self.device_number)}")
            if not os.path.isdir(d):
                raise ElementError(f"{self.name}: no such IIO device dir {d}")
            self._dir = d
        else:
            devices = scan_devices(self.base_dir)
            if not devices:
                raise ElementError(
                    f"{self.name}: no IIO devices under {self.base_dir}"
                )
            if self.device is None:
                self._dir = next(iter(devices.values()))
            elif str(self.device) in devices:
                self._dir = devices[str(self.device)]
            else:
                raise ElementError(
                    f"{self.name}: IIO device {self.device!r} not found; "
                    f"available: {sorted(devices)}"
                )
        found = sorted(
            m.group(1)
            for m in (_CHANNEL_RE.match(f) for f in os.listdir(self._dir))
            if m
        )
        if self._want_channels:
            missing = [c for c in self._want_channels if c not in found]
            if missing:
                raise ElementError(f"{self.name}: channels not found: {missing}")
            self._channels = list(self._want_channels)
        else:
            self._channels = found
        if not self._channels:
            raise ElementError(f"{self.name}: device has no in_*_raw channels")
        # per-channel scale/offset with device-wide fallback (IIO ABI)
        def per_channel(suffix: str, default: float) -> np.ndarray:
            dev_wide = _read(os.path.join(self._dir, f"in_{suffix}"))
            vals = []
            for c in self._channels:
                v = _read(os.path.join(self._dir, f"in_{c}_{suffix}"), dev_wide)
                vals.append(float(v) if v is not None else default)
            return np.asarray(vals, np.float32)

        self._scales = per_channel("scale", 1.0)
        self._offsets = per_channel("offset", 0.0)
        # push requested sampling frequency if the device exposes the knob
        freq_path = os.path.join(self._dir, "sampling_frequency")
        if os.path.isfile(freq_path) and os.access(freq_path, os.W_OK):
            try:
                with open(freq_path, "w") as f:
                    f.write(str(self.frequency))
            except OSError:
                pass

    def output_spec(self) -> Spec:
        self._resolve()
        rate = Fraction(self.frequency).limit_denominator(1000)
        return TensorsSpec.of(
            TensorSpec((1, len(self._channels)), DType.FLOAT32, name="iio"),
            rate=rate,
        )

    def generate(self):
        if self.num_frames >= 0 and self._i >= self.num_frames:
            return EOS_FRAME
        now = time.monotonic()
        if self._next_t is None:
            self._next_t = now
        if now < self._next_t:
            # bounded wait so the executor can stop us (reference poll timeout)
            time.sleep(min(self._next_t - now, 0.1))
            if time.monotonic() < self._next_t:
                return None
        self._next_t += 1.0 / self.frequency
        raw = np.empty((len(self._channels),), np.float32)
        for j, c in enumerate(self._channels):
            v = _read(os.path.join(self._dir, f"in_{c}_raw"), "0")
            try:
                raw[j] = float(v)
            except ValueError:
                raise ElementError(f"{self.name}: bad raw value {v!r} for {c}")
        data = ((raw + self._offsets) * self._scales).reshape(1, -1)
        pts = Fraction(self._i) / Fraction(self.frequency).limit_denominator(1000)
        self._i += 1
        return Frame((data,), pts=pts)
