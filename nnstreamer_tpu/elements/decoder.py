"""tensor_decoder: tensor → media/result egress.

Reference: gst/nnstreamer/elements/gsttensor_decoder.c — dispatches to
decoder subplugins by ``mode=`` + generic ``option1..option9`` strings
(:67-76), subplugin API include/nnstreamer_plugin_api_decoder.h:38-97.

Decoder subplugins here are objects with:
    negotiate(in_spec: TensorsSpec, options: dict) -> Spec
    decode(frame: Frame, options: dict) -> Frame
registered under registry kind "decoder" (see nnstreamer_tpu/decoders/).
Custom in-process decoders (reference tensor_decoder_custom.h) register a
callable via register_custom_decoder().
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    FAULT_PROPS,
    NegotiationError,
    PropSpec,
    Spec,
    TensorOp,
    install_error_pad,
)
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_custom_lock = threading.Lock()
_custom_decoders: Dict[str, Callable] = {}


def register_custom_decoder(name: str, fn: Callable[[Frame, dict], Frame]) -> None:
    """nnstreamer_decoder_custom_register analogue."""
    with _custom_lock:
        _custom_decoders[name] = fn


def unregister_custom_decoder(name: str) -> bool:
    with _custom_lock:
        return _custom_decoders.pop(name, None) is not None


@registry.element("tensor_decoder")
class TensorDecoder(TensorOp):
    """A TensorOp so device-computable decodes (e.g. image_labeling's
    argmax) FUSE into the upstream filter's XLA program — the egress
    payload shrinks to the decoded result ([1] uint32 instead of [1, V]
    logits) before it ever leaves the device, and the pipeline never
    blocks per frame on a host readback. Subplugins opt in by exposing
    ``make_fn(in_spec, options) -> traceable fn | None``; everything else
    (host rasterization, label lookup, byte codecs) runs as a host node."""

    FACTORY_NAME = "tensor_decoder"

    PROPERTIES = dict(
        {"mode": PropSpec("str", None, desc="decoder subplugin name"),
         "postproc": PropSpec(
             "enum", "auto", ("auto", "device", "host"),
             desc="where the decode math runs (docs/on-device-ops.md): "
             "device = fuse the subplugin's tensor math into the "
             "adjacent XLA segment and emit the structured result "
             "tensor (no host rasterization); host = force the host "
             "node; auto = fuse only decodes whose negotiated output "
             "is already a tensor (e.g. image_labeling)",
         ),
         # per-frame error policy (pipeline/faults.py)
         **FAULT_PROPS},
        **{
            f"option{i}": PropSpec("str", "", desc="mode-specific option")
            for i in range(1, 10)
        },
    )

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.mode = str(self.get_property("mode", ""))
        self.postproc = str(self.get_property("postproc", "auto")).lower()
        if self.postproc not in ("auto", "device", "host"):
            raise ValueError(
                f"{self.name}: postproc={self.postproc!r} not "
                "auto/device/host"
            )
        if not self.mode:
            raise ValueError(f"{self.name}: tensor_decoder needs mode=")
        self.options = {
            f"option{i}": str(self.get_property(f"option{i}", "")) for i in range(1, 10)
        }
        self._sub = None
        self._custom_fn = None
        self._traceable_fn = None
        install_error_pad(self)

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        self._traceable_fn = None
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input, got {spec}")
        if self.mode == "custom-code":
            if self.postproc == "device":
                raise NegotiationError(
                    f"{self.name}: custom-code decoders are host "
                    "callbacks; postproc=device cannot trace them"
                )
            name = self.options["option1"]
            with _custom_lock:
                fn = _custom_decoders.get(name)
            if fn is None:
                raise NegotiationError(
                    f"{self.name}: custom decoder {name!r} not registered"
                )
            self._custom_fn = fn
            return [spec]  # custom decoders declare no static out spec
        sub = registry.get(registry.KIND_DECODER, self.mode)
        self._sub = sub() if isinstance(sub, type) else sub
        if self.postproc == "device":
            # device post-processing (docs/on-device-ops.md): the
            # subplugin contributes its decode math as a traceable fn
            # and the negotiated output becomes the structured result
            # tensor — the pipeline compiler folds it into the adjacent
            # FusedSegment, so the decode never leaves the device. Host
            # tails (rasterization, label lookup) are dropped here; a
            # downstream host element consumes the tensor instead.
            dd = getattr(self._sub, "device_decode", None)
            got = dd(spec, self.options) if dd is not None else None
            if got is None:
                raise NegotiationError(
                    f"{self.name}: mode {self.mode!r} (with these "
                    "options) has no device decode path; use "
                    "postproc=host (docs/on-device-ops.md)"
                )
            out_spec, fn = got
            self._traceable_fn = fn
            return [out_spec]
        out = [self._sub.negotiate(spec, self.options)]
        if self.postproc != "host":
            mk = getattr(self._sub, "make_fn", None)
            if mk is not None:
                self._traceable_fn = mk(spec, self.options)
        return out

    def is_traceable(self) -> bool:
        return self._traceable_fn is not None

    def make_fn(self):
        return self._traceable_fn

    def host_process(self, frame: Frame):
        if self._custom_fn is not None:
            return self._custom_fn(frame, self.options)
        if self.postproc == "device" and self._traceable_fn is not None:
            # a device-path decoder can still land on the host loop (a
            # LINKED error pad is a fusion barrier; NNS_NO_FUSE): serve
            # the same traced math per frame so the negotiated
            # structured-tensor spec holds — never the video tail
            return frame.with_tensors(tuple(self._traceable_fn(frame.tensors)))
        return self._sub.decode(frame, self.options)
