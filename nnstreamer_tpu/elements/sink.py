"""Sink elements: tensor_sink (signal/callback), appsink, filesink, fakesink.

Reference: gst/nnstreamer/elements/gsttensor_sink.c — appsink-like element
emitting new-data/stream-start/eos signals with signal-rate limiting;
filesink/multifilesink are what the SSAT golden tests dump through.
"""

from __future__ import annotations

import queue as queue_mod
import time
from typing import Callable, List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import PropSpec, Sink, Spec
from nnstreamer_tpu.tensors.frame import Frame


@registry.element("tensor_sink")
class TensorSink(Sink):
    """Collects frames and fires callbacks.

    Props: max-stored (ring of retained frames, default unlimited),
    signal-rate (max new-data callbacks/sec, 0 = every frame; reference
    'signal-rate' property), sync (unused placeholder for clock sync).
    Callback registration: ``sink.connect("new-data", fn)`` / "eos".
    """

    FACTORY_NAME = "tensor_sink"

    PROPERTIES = {
        "max-stored": PropSpec("int", 0, desc="retained frames; 0 = all"),
        "signal-rate": PropSpec(
            "float", 0, desc="max new-data callbacks/sec; 0 = every frame"
        ),
        "sync": PropSpec("bool", False, desc="unused placeholder"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.max_stored = int(self.get_property("max-stored", 0))
        self.signal_rate = float(self.get_property("signal-rate", 0))
        self.frames: List[Frame] = []
        self.eos_seen = False
        self._callbacks = {"new-data": [], "eos": []}
        self._last_signal_t = 0.0
        self.rendered = 0

    def connect(self, signal: str, fn: Callable) -> None:
        self._callbacks[signal].append(fn)

    def render(self, frame: Frame) -> None:
        frame = frame.to_host()
        self.rendered += 1
        self.frames.append(frame)
        if self.max_stored > 0 and len(self.frames) > self.max_stored:
            self.frames.pop(0)
        now = time.monotonic()
        if self.signal_rate > 0 and (now - self._last_signal_t) < 1.0 / self.signal_rate:
            return  # rate-limited: store but skip signal (reference behavior)
        self._last_signal_t = now
        for fn in self._callbacks["new-data"]:
            fn(frame)

    def on_eos(self) -> None:
        self.eos_seen = True
        for fn in self._callbacks["eos"]:
            fn()


@registry.element("appsink")
class AppSink(Sink):
    """Blocking pop() interface for application threads."""

    FACTORY_NAME = "appsink"

    PROPERTIES = {
        "max-buffers": PropSpec("int", 0, desc="pop queue bound; 0 = unbounded"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._queue: queue_mod.Queue = queue_mod.Queue(
            maxsize=int(self.get_property("max-buffers", 0)) or 0
        )
        self.eos_seen = False

    def render(self, frame: Frame) -> None:
        self._queue.put(frame.to_host())

    def on_eos(self) -> None:
        self.eos_seen = True
        self._queue.put(None)

    def pop(self, timeout: Optional[float] = None) -> Optional[Frame]:
        """Next frame, or None at EOS."""
        return self._queue.get(timeout=timeout)


@registry.element("filesink")
class FileSink(Sink):
    """Dump raw tensor bytes. location with ``%d`` → one file per frame
    (multifilesink parity, what SSAT golden tests compare)."""

    FACTORY_NAME = "filesink"

    PROPERTIES = {
        "location": PropSpec(
            "str", "", desc="output path; %d = one file per frame"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.location = str(self.get_property("location", ""))
        if not self.location:
            raise ValueError(f"{self.name}: filesink needs location=")
        self._multi = "%" in self.location
        self._file = None
        self._index = 0

    def start(self) -> None:
        if not self._multi:
            self._file = open(self.location, "wb")
        self._index = 0

    def render(self, frame: Frame) -> None:
        frame = frame.to_host()
        payload = b"".join(
            np.ascontiguousarray(t).tobytes() for t in frame.tensors
        )
        if self._multi:
            with open(self.location % self._index, "wb") as f:
                f.write(payload)
        else:
            self._file.write(payload)
        self._index += 1

    def stop(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


@registry.element("fakesink")
class FakeSink(Sink):
    """Discard frames (keeps a count). Completes device futures so
    backpressure reflects real compute."""

    FACTORY_NAME = "fakesink"
    # never reads tensor data: the executor must not prefetch host
    # copies on its behalf (SinkNode sync-window path)
    READS_HOST = False

    PROPERTIES = {
        "sync-device": PropSpec(
            "bool", True, desc="block until the device future completes"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.rendered = 0
        self.sync_device = bool(self.get_property("sync-device", True))

    def render(self, frame: Frame) -> None:
        if self.sync_device:
            frame.block_until_ready()
        self.rendered += 1
