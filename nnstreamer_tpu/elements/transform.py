"""tensor_transform: elementwise ops on tensor streams — fused into XLA.

Reference: gst/nnstreamer/elements/gsttensor_transform.c (modes
gsttensor_transform.h:57-67, option regexes :73-77). The reference needs a
runtime SIMD compiler (ORC) for speed (:459-530); here every mode is a jnp
expression that the pipeline compiler fuses into the adjacent XLA program —
preprocessing costs zero extra HBM round-trips when followed by a filter.

Option-string syntax is reference-compatible (dim indices are the
reference's innermost-first; translated to canonical axes internally):

- mode=typecast option=TYPE
- mode=arithmetic option=[typecast:TYPE,][per-channel:true@DIM,]
    {add|sub|mul|div}:NUM[@CH_IDX][,...]
- mode=transpose option=D1:D2:D3:D4   (innermost-first permutation)
- mode=dimchg option=FROM:TO          (move innermost-first dim FROM to TO)
- mode=clamp option=MIN:MAX
- mode=stand option={default|dc-average}[:TYPE][,per-channel:true]

Applied to every tensor in the frame (multi-tensor parity).

Image modes (docs/on-device-ops.md) — the pre-processing the reference
delegates to host videoscale/videocrop, as fusable device ops
(ops/image.py; Pallas-kernel-backed on TPU):

- mode=resize option=H:W — bilinear resize of every HWC/NHWC image
  tensor to H×W (dtype preserved).
- mode=crop-resize option=H:W — the frame is (image, boxes) in either
  order: image [H,W,C] or [1,H,W,C]; boxes [N,4] int (x,y,w,h) pixel
  regions (tensor_crop convention — zero-size rows zero their crop),
  [N,4] float (x1,y1,x2,y2) pixels, [N,6] decoded detections or [N,7]
  OV rows (normalized coords, scaled by the image size). Emits ONE
  [N,H,W,C] crop batch in the image dtype — the tensor_crop out-size=
  cascade as a 1→1 fusable op, so detect→crop→landmark chains entirely
  in device segments.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Tuple

import jax.numpy as jnp

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    FAULT_PROPS,
    NegotiationError,
    PropSpec,
    Spec,
    TensorOp,
    install_error_pad,
)
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

_ARITH_OP = re.compile(
    r"^(typecast:(?P<cast>[a-z0-9]+)|per-channel:(?P<pc>true|false)(@(?P<pcdim>\d+))?|"
    r"(?P<op>add|sub|mul|div):(?P<num>-?[0-9.eE+-]+)(@(?P<ch>\d+))?)$"
)


def _ref_axis(canonical_rank: int, ref_dim: int) -> int:
    """Reference innermost-first dim index → canonical axis."""
    if ref_dim >= canonical_rank:
        raise NegotiationError(
            f"dim index {ref_dim} out of range for rank {canonical_rank}"
        )
    return canonical_rank - 1 - ref_dim


@registry.element("tensor_transform")
class TensorTransform(TensorOp):
    FACTORY_NAME = "tensor_transform"
    SAN_ONE_TO_ONE = True  # pure per-frame tensor fn (sanitizer accounting)

    PROPERTIES = {
        "mode": PropSpec(
            "enum", None,
            ("typecast", "arithmetic", "transpose", "dimchg", "clamp",
             "stand", "resize", "crop-resize"),
        ),
        "option": PropSpec("str", "", desc="per-mode option string"),
        # image modes only (resize / crop-resize): which implementation
        # the device op dispatches (ops/image.py). An explicit pallas
        # request that would degrade (unsupported dtype, kill switch,
        # non-image mode) is flagged by nns-lint NNS-W129.
        "impl": PropSpec(
            "enum", "auto", ("auto", "jnp", "pallas"),
            desc="image-mode kernel dispatch: auto | jnp | pallas",
        ),
        # per-frame error policy (pipeline/faults.py)
        **FAULT_PROPS,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.mode = str(self.get_property("mode", "")).lower()
        self.option = str(self.get_property("option", ""))
        if self.mode not in (
            "typecast",
            "arithmetic",
            "transpose",
            "dimchg",
            "clamp",
            "stand",
            "resize",
            "crop-resize",
        ):
            raise ValueError(f"{self.name}: unknown mode {self.mode!r}")
        install_error_pad(self)

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input, got {spec}")
        if self.mode == "crop-resize":
            # cross-tensor mode: (image, boxes) → one crop batch
            return [self._crop_resize_spec(spec)]
        outs = [self._transform_spec(t) for t in spec]
        return [TensorsSpec(tuple(outs), spec.format, spec.rate)]

    def _parse_hw(self) -> Tuple[int, int]:
        try:
            h, w = (int(x) for x in self.option.split(":"))
        except ValueError as exc:
            raise NegotiationError(
                f"{self.name}: bad {self.mode} size {self.option!r} "
                "(want H:W)"
            ) from exc
        if h <= 0 or w <= 0:
            raise NegotiationError(
                f"{self.name}: {self.mode} size must be positive, got "
                f"{h}:{w}"
            )
        return h, w

    def _crop_resize_layout(self, spec: TensorsSpec):
        """Resolve the (image, boxes) tensor roles statically from the
        negotiated spec: image is the rank-3 HWC / rank-4 [1,H,W,C]
        tensor, boxes the rank-2 [N, 4|6|7] one."""
        if spec.num_tensors != 2:
            raise NegotiationError(
                f"{self.name}: crop-resize needs (image, boxes), got "
                f"{spec.num_tensors} tensors"
            )
        img_idx = next(
            (i for i, t in enumerate(spec) if t.rank >= 3), None
        )
        if img_idx is None:
            raise NegotiationError(
                f"{self.name}: crop-resize found no image tensor "
                f"(rank ≥ 3) in {spec}"
            )
        box_idx = 1 - img_idx
        img, box = spec[img_idx], spec[box_idx]
        if img.rank == 4 and img.shape[0] not in (1, None):
            raise NegotiationError(
                f"{self.name}: crop-resize crops one image per frame "
                f"(batch {img.shape[0]})"
            )
        if img.rank not in (3, 4):
            raise NegotiationError(
                f"{self.name}: image must be HWC or [1,H,W,C], got {img}"
            )
        if box.rank != 2 or box.shape[-1] not in (4, 6, 7):
            raise NegotiationError(
                f"{self.name}: boxes must be [N, 4|6|7] (pixel regions, "
                f"decoded detections, or OV rows), got {box}"
            )
        return img_idx, box_idx

    def _crop_resize_spec(self, spec: TensorsSpec) -> TensorsSpec:
        h, w = self._parse_hw()
        img_idx, box_idx = self._crop_resize_layout(spec)
        img, box = spec[img_idx], spec[box_idx]
        c = img.shape[-1]
        out = TensorSpec((box.shape[0], h, w, c), img.dtype, name="crops")
        return TensorsSpec.of(out, rate=spec.rate)

    def _transform_spec(self, t: TensorSpec) -> TensorSpec:
        m = self.mode
        if m == "typecast":
            return t.with_dtype(DType.from_any(self.option))
        if m == "arithmetic":
            cast, _, _, _ = self._parse_arith()
            return t.with_dtype(cast) if cast else t
        if m == "transpose":
            perm = self._canonical_perm(t.rank)
            return t.with_shape(tuple(t.shape[a] for a in perm))
        if m == "dimchg":
            src, dst = self._parse_dimchg(t.rank)
            shape = list(t.shape)
            shape.insert(dst, shape.pop(src))
            return t.with_shape(tuple(shape))
        if m == "clamp":
            self._parse_clamp()
            return t
        if m == "stand":
            _, _, out_type = self._parse_stand()
            if out_type:
                return t.with_dtype(out_type)
            return t if t.dtype.is_float else t.with_dtype(DType.FLOAT32)
        if m == "resize":
            h, w = self._parse_hw()
            if t.rank == 3:
                return t.with_shape((h, w, t.shape[2]))
            if t.rank == 4:
                return t.with_shape((t.shape[0], h, w, t.shape[3]))
            raise NegotiationError(
                f"{self.name}: resize needs HWC/NHWC image tensors, "
                f"got {t}"
            )
        raise AssertionError(m)

    # -- option parsing ----------------------------------------------------
    def _parse_arith(self):
        cast: Optional[DType] = None
        per_channel = False
        pc_axis_ref = 0
        ops: List[Tuple[str, float, Optional[int]]] = []
        for part in self.option.split(","):
            part = part.strip()
            if not part:
                continue
            m = _ARITH_OP.match(part)
            if not m:
                raise NegotiationError(f"{self.name}: bad arithmetic option {part!r}")
            if m.group("cast"):
                cast = DType.from_any(m.group("cast"))
            elif m.group("pc"):
                per_channel = m.group("pc") == "true"
                if m.group("pcdim"):
                    pc_axis_ref = int(m.group("pcdim"))
            else:
                ch = int(m.group("ch")) if m.group("ch") else None
                ops.append((m.group("op"), float(m.group("num")), ch))
        return cast, per_channel, pc_axis_ref, ops

    def _canonical_perm(self, rank: int) -> Tuple[int, ...]:
        ref_perm = [int(p) for p in self.option.split(":") if p != ""]
        if sorted(ref_perm) != list(range(len(ref_perm))):
            raise NegotiationError(f"{self.name}: bad transpose {self.option!r}")
        while len(ref_perm) < rank:
            ref_perm.append(len(ref_perm))
        # out canonical axis a = in canonical axis rank-1-ref_perm[rank-1-a]
        return tuple(rank - 1 - ref_perm[rank - 1 - a] for a in range(rank))

    def _parse_dimchg(self, rank: int) -> Tuple[int, int]:
        try:
            frm, to = (int(x) for x in self.option.split(":"))
        except ValueError as exc:
            raise NegotiationError(f"{self.name}: bad dimchg {self.option!r}") from exc
        return _ref_axis(rank, frm), _ref_axis(rank, to)

    def _parse_clamp(self) -> Tuple[float, float]:
        try:
            lo, hi = (float(x) for x in self.option.split(":"))
        except ValueError as exc:
            raise NegotiationError(f"{self.name}: bad clamp {self.option!r}") from exc
        if lo > hi:
            raise NegotiationError(f"{self.name}: clamp min {lo} > max {hi}")
        return lo, hi

    def _parse_stand(self):
        mode, per_channel, out_type = "default", False, None
        for i, part in enumerate(p.strip() for p in self.option.split(",")):
            if not part:
                continue
            if part.startswith("per-channel:"):
                per_channel = part.split(":", 1)[1] == "true"
                continue
            bits = part.split(":")
            mode = bits[0] or "default"
            if len(bits) > 1:
                out_type = DType.from_any(bits[1])
        if mode not in ("default", "dc-average"):
            raise NegotiationError(f"{self.name}: bad stand mode {mode!r}")
        return mode, per_channel, out_type

    # -- fused fn ----------------------------------------------------------
    def make_fn(self) -> Callable:
        mode = self.mode
        in_spec: TensorsSpec = self.in_specs[0]
        out_spec: TensorsSpec = self.out_specs[0]

        if mode == "typecast":
            dt = DType.from_any(self.option).np_dtype

            def fn(tensors):
                return tuple(jnp.asarray(t).astype(dt) for t in tensors)

        elif mode == "arithmetic":
            cast, per_channel, pc_axis_ref, ops = self._parse_arith()

            def apply_one(x, rank):
                y = jnp.asarray(x)
                if cast is not None:
                    y = y.astype(cast.np_dtype)
                elif not jnp.issubdtype(y.dtype, jnp.floating):
                    # integer arithmetic without explicit cast follows the
                    # input dtype (reference semantics)
                    pass
                axis = _ref_axis(rank, pc_axis_ref) if per_channel else None
                for op, num, ch in ops:
                    if ch is not None and axis is not None:
                        # per-channel constant applied to one channel index
                        sel = [slice(None)] * rank
                        sel[axis] = ch
                        upd = y[tuple(sel)]
                        upd = _arith(upd, op, num)
                        y = y.at[tuple(sel)].set(upd)
                    else:
                        y = _arith(y, op, num)
                return y

            def fn(tensors):
                return tuple(
                    apply_one(t, s.rank) for t, s in zip(tensors, in_spec)
                )

        elif mode == "transpose":
            perms = [self._canonical_perm(s.rank) for s in in_spec]

            def fn(tensors):
                return tuple(
                    jnp.transpose(jnp.asarray(t), p) for t, p in zip(tensors, perms)
                )

        elif mode == "dimchg":
            moves = [self._parse_dimchg(s.rank) for s in in_spec]

            def fn(tensors):
                return tuple(
                    jnp.moveaxis(jnp.asarray(t), s, d)
                    for t, (s, d) in zip(tensors, moves)
                )

        elif mode == "clamp":
            lo, hi = self._parse_clamp()

            def fn(tensors):
                return tuple(
                    jnp.clip(jnp.asarray(t), *_clamp_bounds(t, lo, hi)) for t in tensors
                )

        elif mode == "resize":
            out_h, out_w = self._parse_hw()
            impl = str(self.get_property("impl", "auto"))
            from nnstreamer_tpu.ops.image import resize_bilinear

            def fn(tensors):
                return tuple(
                    resize_bilinear(jnp.asarray(t), out_h, out_w, impl=impl)
                    for t in tensors
                )

        elif mode == "crop-resize":
            out_h, out_w = self._parse_hw()
            img_idx, box_idx = self._crop_resize_layout(in_spec)
            img_spec, box_spec = in_spec[img_idx], in_spec[box_idx]
            img_rank4 = img_spec.rank == 4
            ih, iw = (
                img_spec.shape[1:3] if img_rank4 else img_spec.shape[0:2]
            )
            bcols = box_spec.shape[-1]
            box_is_int = not box_spec.dtype.is_float
            np_dtype = img_spec.dtype.np_dtype
            impl = str(self.get_property("impl", "auto"))
            from nnstreamer_tpu.ops.image import crop_regions

            def fn(tensors):
                img = tensors[img_idx]
                if img_rank4:
                    img = img[0]
                b = jnp.asarray(tensors[box_idx]).astype(jnp.float32)
                if bcols == 4 and box_is_int:
                    # tensor_crop pixel regions (x, y, w, h)
                    xyxy = jnp.concatenate(
                        [b[:, :2], b[:, :2] + b[:, 2:4]], axis=-1
                    )
                    valid = (b[:, 2] > 0) & (b[:, 3] > 0)
                elif bcols == 4:
                    xyxy = b  # pixel x1,y1,x2,y2 — all rows live
                    valid = None
                elif bcols == 6:
                    # decoded detections (normalized; score col 5)
                    xyxy = b[:, :4] * jnp.asarray(
                        [iw, ih, iw, ih], jnp.float32
                    )
                    valid = b[:, 5] > 0
                else:
                    # OV rows (image_id, label, conf, x1, y1, x2, y2)
                    xyxy = b[:, 3:7] * jnp.asarray(
                        [iw, ih, iw, ih], jnp.float32
                    )
                    valid = b[:, 2] > 0
                # zeroed invalid rows + integer round/clip: the shared
                # tensor_crop conventions (ops/image.crop_regions)
                return (crop_regions(
                    jnp.asarray(img), xyxy, out_h, out_w,
                    valid=valid, out_dtype=np_dtype, impl=impl,
                ),)

        elif mode == "stand":
            smode, per_channel, out_type = self._parse_stand()

            def stand_one(x, out_dtype):
                y = jnp.asarray(x).astype(jnp.float32)
                axes = tuple(range(y.ndim - 1)) if per_channel else None
                mean = jnp.mean(y, axis=axes, keepdims=per_channel)
                if smode == "default":
                    std = jnp.std(y, axis=axes, keepdims=per_channel)
                    y = (y - mean) / (std + 1e-10)
                else:  # dc-average
                    y = y - mean
                return y.astype(out_dtype)

            def fn(tensors):
                return tuple(
                    stand_one(t, s.dtype.np_dtype)
                    for t, s in zip(tensors, out_spec)
                )

        else:
            raise AssertionError(mode)
        return fn


def _arith(y, op: str, num: float):
    const = jnp.asarray(num, dtype=y.dtype)
    if op == "add":
        return y + const
    if op == "sub":
        return y - const
    if op == "mul":
        return y * const
    if op == "div":
        return y / const
    raise AssertionError(op)


def _clamp_bounds(t, lo: float, hi: float):
    # integer clamps round the bounds like the reference's typed clamp
    if jnp.issubdtype(jnp.asarray(t).dtype, jnp.integer):
        return int(lo), int(hi)
    return lo, hi
