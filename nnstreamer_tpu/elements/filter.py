"""tensor_filter: THE inference element.

Reference: gst/nnstreamer/tensor_filter/tensor_filter.c (+ the shared
property engine tensor_filter_common.c). Dispatches to a Backend subplugin
(backends/). TPU-first differences from the reference's per-frame
map→invoke→unmap (SURVEY.md §3.2):

- a jax-traceable backend contributes its fn to the surrounding fused XLA
  segment, so transform→filter→decode chains become ONE program and tensors
  never leave HBM between elements;
- host-library backends (torch/tflite) run as host nodes — explicit fusion
  barriers, device transfer only at their edges.

Properties (reference tensor_filter_common.c:103-128 parity): framework,
model, input/inputtype (spec override), output/outputtype, custom,
accelerator, input-combination (select a subset/reorder of input tensors
for the model), output-combination (compose output frame from model outputs
``o#`` and passthrough inputs ``i#``), invoke-dynamic, is-updatable (model
reload via reload_model()), shared-tensor-filter-key (filters with the
same key share one opened backend — one weight copy, reload swaps for
all). Read-only: latency, throughput.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import Backend, BackendError, FilterProps, InvokeStats
from nnstreamer_tpu.elements.base import (
    DEVICE_PROPS,
    FAULT_PROPS,
    STREAM_PROPS,
    NegotiationError,
    PropSpec,
    Spec,
    TensorOp,
    install_error_pad,
)
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_log = get_logger("filter")

# shared-model table (reference shared_tensor_filter_key,
# tensor_filter_common.c shared-model support): filters with the same key
# share ONE opened backend instance — one copy of the weights on device,
# and a reload through any sharer swaps the model for all of them.
_shared_lock = threading.Lock()
# key -> {"backend", "refs", "sig", "open_lock"}
_shared_backends: Dict[str, Dict] = {}


def _props_signature(p: FilterProps) -> tuple:
    """Everything that shapes an opened backend: sharers must agree on the
    full configuration, not just the model path."""
    return (
        p.framework, p.model, p.custom, p.accelerator, p.invoke_dynamic,
        str(p.input_spec), str(p.output_spec),
    )


def _shared_acquire(key: str, props: FilterProps, opener):
    sig = _props_signature(props)
    with _shared_lock:
        entry = _shared_backends.get(key)
        if entry is None:
            entry = {"backend": None, "refs": 0, "sig": sig,
                     "open_lock": threading.Lock()}
            _shared_backends[key] = entry
        elif entry["sig"] != sig:
            raise NegotiationError(
                f"shared-tensor-filter-key={key!r} already bound to "
                f"{entry['sig']}, cannot rebind to {sig}"
            )
        entry["refs"] += 1
    try:
        # per-key open lock: model opens (jit compiles) for DIFFERENT keys
        # must not serialize behind one global lock
        with entry["open_lock"]:
            if entry["backend"] is None:
                backend = opener()
                # stateful host backends (tflite set_tensor/invoke/
                # get_tensor, custom scripts) are not reentrant; sharers
                # run on separate executor threads, so serialize invokes
                backend.shared_invoke_lock = threading.Lock()
                entry["backend"] = backend
        return entry["backend"]
    except Exception:
        with _shared_lock:
            entry["refs"] -= 1
            if entry["refs"] <= 0 and entry["backend"] is None:
                _shared_backends.pop(key, None)
        raise


def _shared_release(key: str, backend) -> bool:
    """Drop one ref; True if the caller should actually close the backend."""
    with _shared_lock:
        entry = _shared_backends.get(key)
        if entry is None or entry["backend"] is not backend:
            return True  # not (or no longer) shared: caller owns it
        entry["refs"] -= 1
        if entry["refs"] <= 0:
            del _shared_backends[key]
            return True
        return False


def _parse_combination(s: str, prefix_ok=("i", "o")) -> Optional[List[Tuple[str, int]]]:
    """'i0,o1,i2' → [('i',0),('o',1),('i',2)]; plain ints mean 'i' for
    input-combination and 'o' for output-combination (resolved by caller)."""
    s = (s or "").strip()
    if not s:
        return None
    out = []
    for part in s.split(","):
        part = part.strip().lower()
        if not part:
            raise ValueError(f"empty token in combination string {s!r}")
        if part[0] in prefix_ok and part[1:].isdigit():
            out.append((part[0], int(part[1:])))
        elif part.isdigit():
            out.append(("", int(part)))
        else:
            raise ValueError(f"bad combination token {part!r}")
    return out


@registry.element("tensor_filter")
class TensorFilter(TensorOp):
    FACTORY_NAME = "tensor_filter"
    # one invoke per frame on every path (fused, host, batched-split):
    # the sanitizer may enforce per-node frame accounting
    SAN_ONE_TO_ONE = True

    PROPERTIES = {
        "framework": PropSpec("str", "auto", desc="backend subplugin name"),
        "model": PropSpec("str", "", desc="model path(s), comma-separated"),
        "input": PropSpec("str", None, desc="input spec override (dims)"),
        "inputtype": PropSpec("str", "float32"),
        "inputname": PropSpec("str", ""),
        "output": PropSpec("str", None, desc="output spec override (dims)"),
        "outputtype": PropSpec("str", "float32"),
        "outputname": PropSpec("str", ""),
        "custom": PropSpec("str", "", desc="backend options 'k:v,k2:v2'"),
        "accelerator": PropSpec("str", ""),
        "invoke-dynamic": PropSpec("bool", False),
        "is-updatable": PropSpec("bool", False, desc="allow reload_model()"),
        "shared-tensor-filter-key": PropSpec(
            "str", "", desc="filters with one key share one opened backend"
        ),
        "input-combination": PropSpec("str", ""),
        "output-combination": PropSpec("str", ""),
        # micro-batching (pipeline/batching.py): per-element overrides of
        # the executor-level [executor] defaults. Unset = inherit.
        "batching": PropSpec(
            "bool", None,
            desc="micro-batch queued frames into one device invoke",
        ),
        "max-batch": PropSpec(
            "int", None, desc="micro-batch frame cap (default 8)"
        ),
        "batch-timeout-ms": PropSpec(
            "float", None,
            desc="straggler wait when trickle-fed (default 1.0; 0 = never wait)",
        ),
        "batch-buckets": PropSpec(
            "str", None,
            desc="comma list of padded batch sizes (default 1,2,4,...,max-batch)",
        ),
        # resident streaming (pipeline/transfer.py, docs/streaming.md):
        # in-flight frame ring depth for this filter's device node
        **STREAM_PROPS,
        # per-frame error policy (pipeline/faults.py)
        **FAULT_PROPS,
        # device-resilience policy (pipeline/device_faults.py): OOM
        # bucket degradation + compiled-path fallback circuit
        **DEVICE_PROPS,
        # graceful degradation: after fallback-after CONSECUTIVE backend
        # failures the filter hot-swaps to the fallback backend (circuit
        # breaker) instead of dying, probing the primary every
        # fallback-probe-every frames for recovery
        "fallback-framework": PropSpec(
            "str", "", desc="degraded-mode backend (circuit breaker)"
        ),
        "fallback-model": PropSpec(
            "str", "", desc="degraded-mode model path(s)"
        ),
        "fallback-after": PropSpec(
            "int", 3, desc="consecutive failures that open the circuit"
        ),
        "fallback-probe-every": PropSpec(
            "int", 64, desc="frames between primary recovery probes"
        ),
        # replica failover (parallel/replicas.py, docs/resilience.md):
        # replicas=N opens N backend instances and load-balances frames
        # over them; a replica with replica-unhealthy-after consecutive
        # device faults leaves the rotation (its in-flight frame fails
        # over), probed for recovery every replica-probe-every frames
        "replicas": PropSpec(
            "int", None,
            desc="open N backend replicas with failover (default off)",
        ),
        "replica-devices": PropSpec(
            "str", "",
            desc="comma list of device indices to pin replicas to "
            "(round-robin when fewer than replicas)",
        ),
        "replica-unhealthy-after": PropSpec(
            "int", 3,
            desc="consecutive device faults that bench a replica",
        ),
        "replica-probe-every": PropSpec(
            "int", 64,
            desc="frames between benched-replica recovery probes",
        ),
        # per-stage device placement (serving_plane/placement.py,
        # docs/serving-plane.md): pin this filter's backend to one jax
        # device; inter-stage hops become staged device_put transfers
        "device": PropSpec(
            "int", None,
            desc="pin this stage to jax device N (Hermes placement; "
            "default: planner/runtime choice)",
        ),
        # serving plane (serving_plane/, docs/serving-plane.md): filters
        # naming one plane share ONE continuously-batched device program
        # across executors — N client streams, one model instance
        "plane": PropSpec(
            "str", "",
            desc="attach to the named process-wide serving plane "
            "(cross-executor continuous batching)",
        ),
        "plane-weight": PropSpec(
            "float", None,
            desc="this stream's weighted-fair share on the plane "
            "(default 1.0)",
        ),
        "plane-mode": PropSpec(
            "enum", None, ("single", "shard", "replicas"),
            desc="plane backing: one device / data-sharded mesh / "
            "K failover replicas (default [plane] mode)",
        ),
        "plane-devices": PropSpec(
            "int", None,
            desc="devices backing the plane: mesh size (shard) or "
            "replica count (replicas); default [plane] devices",
        ),
        "plane-max-batch": PropSpec(
            "int", None,
            desc="cross-stream batch cap per plane dispatch "
            "(default [plane] max_batch = 8)",
        ),
        "plane-timeout-ms": PropSpec(
            "float", None,
            desc="plane straggler wait when trickle-fed "
            "(default [plane] timeout_ms = 1.0)",
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        models = str(self.get_property("model", ""))
        model_list = tuple(m for m in models.split(",") if m)
        framework = str(self.get_property("framework", "auto"))
        if framework == "auto":
            detected = (
                registry.detect_filter_framework(model_list[0]) if model_list else None
            )
            if detected is None:
                raise ValueError(f"{self.name}: cannot auto-detect framework")
            framework = detected
        in_override = None
        if self.get_property("input"):
            in_override = TensorsSpec.from_strings(
                str(self.get_property("input")),
                str(self.get_property("inputtype", "float32")),
                str(self.get_property("inputname", "")),
            )
        out_override = None
        if self.get_property("output"):
            out_override = TensorsSpec.from_strings(
                str(self.get_property("output")),
                str(self.get_property("outputtype", "float32")),
                str(self.get_property("outputname", "")),
            )
        custom = str(self.get_property("custom", ""))
        # device= placement pin (serving_plane/placement.py): rides the
        # custom string so the jax backend's existing per-stage
        # placement path (open() reads options["device"]) serves both
        # the explicit prop and the Hermes planner
        dev_raw = self.get_property("device")
        if dev_raw is not None and str(dev_raw).strip() != "":
            try:
                dev_idx = int(dev_raw)
            except (TypeError, ValueError) as exc:
                raise ValueError(
                    f"{self.name}: bad device={dev_raw!r}: {exc}"
                ) from exc
            custom = ",".join(
                x for x in (custom, f"device:{dev_idx}") if x
            )
        self.fprops = FilterProps(
            framework=framework,
            model=model_list,
            input_spec=in_override,
            output_spec=out_override,
            custom=custom,
            accelerator=str(self.get_property("accelerator", "")),
            invoke_dynamic=bool(self.get_property("invoke-dynamic", False)),
        )
        self.shared_key = str(
            self.get_property("shared-tensor-filter-key", "")
        )
        self.in_combination = _parse_combination(
            str(self.get_property("input-combination", ""))
        )
        self.out_combination = _parse_combination(
            str(self.get_property("output-combination", ""))
        )
        self.backend: Optional[Backend] = None
        self._traceable: Optional[Callable] = None
        install_error_pad(self)
        # circuit-breaker fallback (docs/fault-tolerance.md): a configured
        # fallback forces the host path (is_traceable False) so the swap
        # can happen per frame — a fused program can't change backends
        self.fallback_framework = str(
            self.get_property("fallback-framework", "") or ""
        )
        self.fallback_model = str(self.get_property("fallback-model", "") or "")
        self._fallback_conf = bool(self.fallback_framework or self.fallback_model)
        self.fallback_after = max(1, int(self.get_property("fallback-after", 3)))
        self.fallback_probe_every = max(
            1, int(self.get_property("fallback-probe-every", 64))
        )
        # replica failover (parallel/replicas.py): replicas=N dispatches
        # per-frame over N opened backends — a fusion barrier like the
        # fallback circuit (health is per-frame, a fused program is not)
        self.replicas = int(self.get_property("replicas", 0) or 0)
        self.replica_devices = [
            int(d) for d in str(
                self.get_property("replica-devices", "") or ""
            ).split(",") if str(d).strip()
        ]
        self.replica_unhealthy_after = max(
            1, int(self.get_property("replica-unhealthy-after", 3))
        )
        self.replica_probe_every = max(
            1, int(self.get_property("replica-probe-every", 64))
        )
        self._replica_set = None  # ReplicaSet, built lazily post-negotiate
        self._replica_backends: list = []
        # serving plane (serving_plane/plane.py, docs/serving-plane.md):
        # plane=<name> attaches this filter as ONE client stream of a
        # process-wide shared batcher — a fusion barrier like replicas
        # (cross-executor batching is per-frame dispatch by definition)
        self.plane = str(self.get_property("plane", "") or "")
        raw_w = self.get_property("plane-weight")
        self.plane_weight = float(raw_w) if raw_w is not None else 1.0
        self._plane = None          # ModelPlane once acquired
        self._plane_stream = None   # this filter's PlaneStream
        self._plane_cfg = None      # resolved PlaneConfig
        self._plane_last_stats: Dict[str, Any] = {}
        self.plane_inflight = 1     # async ring depth (1 = blocking)
        if self.plane:
            # cross-stream batching rides the host batched loop: the
            # LOCAL collector drains a window per round-trip (one
            # submit amortizes two thread wakes over the window), the
            # plane flattens windows from many streams into one device
            # batch. Default the collector on, window-matched to the
            # plane; explicit batching= / max-batch= props still win.
            from nnstreamer_tpu.serving_plane.plane import (
                resolve_plane_config,
            )

            self._plane_cfg = resolve_plane_config([self])
            # async in-flight ring depth for THIS stream
            # (docs/serving-plane.md): the PR-8 ring-depth property
            # outranks the [plane] inflight config default; 1 keeps
            # blocking submits. Resolved here (not plan time) because
            # the plane path rides the host batched loop, which only
            # arms a ring when the element asks.
            raw_rd = self.get_property("ring-depth")
            if raw_rd is not None:
                from nnstreamer_tpu.pipeline.transfer import (
                    resolve_ring_depth,
                )

                self.plane_inflight = resolve_ring_depth([self])
            else:
                self.plane_inflight = self._plane_cfg.inflight
            if self.get_property("batching") is None:
                self.set_property("batching", "true")
            if self.get_property("max-batch") is None:
                self.set_property(
                    "max-batch", str(self._plane_cfg.max_batch)
                )
            if self.get_property("batch-timeout-ms") is None:
                self.set_property(
                    "batch-timeout-ms", str(self._plane_cfg.timeout_ms)
                )
        # warm-restart state arriving before the backend/replica set
        # exist (both build lazily on the first frame) — stashed here
        # and applied as each comes up, the Node._pending_restore
        # discipline one level down
        self._pending_state: Optional[Dict[str, Any]] = None
        if self.replicas > 1 and self.shared_key:
            # shared key = ONE opened backend for all sharers; replicas =
            # N independent copies. Both at once is a contradiction.
            raise ValueError(
                f"{self.name}: replicas={self.replicas} cannot combine "
                "with shared-tensor-filter-key (one shared instance vs "
                "N independent copies)"
            )
        if self.plane:
            # the plane owns sharing, replication, and degradation for
            # its model instance; the per-filter variants of the same
            # mechanisms would silently fight it
            if self.shared_key:
                raise ValueError(
                    f"{self.name}: plane={self.plane!r} cannot combine "
                    "with shared-tensor-filter-key (the plane IS the "
                    "shared instance)"
                )
            if self.replicas > 1:
                raise ValueError(
                    f"{self.name}: plane={self.plane!r} cannot combine "
                    "with replicas=N (use plane-mode=replicas — the "
                    "plane replicates its own program)"
                )
            if self._fallback_conf:
                raise ValueError(
                    f"{self.name}: plane={self.plane!r} cannot combine "
                    "with fallback-framework/fallback-model (plane "
                    "faults dispose per stream via on-error)"
                )
        if self.replicas > 1 and self._fallback_conf:
            # host_process dispatches through the replica set before the
            # fallback circuit is ever consulted — accepting both would
            # silently never open the fallback backend. Survival past
            # replica exhaustion is the on-error policy's job
            # (docs/resilience.md degradation ladder).
            raise ValueError(
                f"{self.name}: replicas={self.replicas} cannot combine "
                "with fallback-framework/fallback-model (failover "
                "replaces the fallback circuit; use on-error for "
                "post-exhaustion disposal)"
            )
        self._fb_backend: Optional[Backend] = None
        self._fb_open_error: Optional[Exception] = None
        self._consec_failures = 0
        self._circuit_open = False
        self._since_probe = 0
        self._cb = {
            "primary_failures": 0, "circuit_opens": 0,
            "circuit_closes": 0, "fallback_invokes": 0,
        }
        # Per-ELEMENT invoke stats, like the reference's (latency/
        # throughput live in the element private data, tensor_filter.c:
        # 334-433) — backends keep their own cumulative stats (the
        # per-framework statistics analogue), but filters sharing one
        # backend must not report each other's invokes as their own.
        self._elem_stats = InvokeStats()

    # -- lifecycle ---------------------------------------------------------
    def _open_backend(self, custom_extra: str = "") -> Backend:
        cls = registry.get(registry.KIND_FILTER, self.fprops.framework)
        b: Backend = cls()
        props = self.fprops
        if custom_extra:
            joined = ",".join(x for x in (props.custom, custom_extra) if x)
            props = dataclasses.replace(props, custom=joined)
        b.open(props)
        return b

    def _replica_custom(self, i: int) -> str:
        """Per-replica custom-string suffix: the index (chaos injectors
        scope device-plane faults to one replica via ``only_replica``)
        plus the pinned device when ``replica-devices`` says so."""
        extra = f"_replica:{i}"
        if self.replica_devices:
            dev = self.replica_devices[i % len(self.replica_devices)]
            # `device` is the key the jax backend's per-stage placement
            # actually reads (jax_backend.open) — pinning replicas to
            # distinct chips is the whole point of replica-devices
            extra += f",device:{dev}"
        return extra

    def _ensure_open(self) -> Backend:
        if self.backend is None:
            if self.plane:
                self.backend = self._acquire_plane().backend
            elif self.shared_key:
                self.backend = _shared_acquire(
                    self.shared_key, self.fprops, self._open_backend
                )
            elif self.replicas > 1:
                # replica 0 doubles as the negotiation/model-info backend
                self.backend = self._open_backend(self._replica_custom(0))
            else:
                self.backend = self._open_backend()
            self._apply_pending_state()
        return self.backend

    def stop(self) -> None:
        if self._plane is not None:
            # the plane owns the backend(s); this filter only drops its
            # stream + registry ref (last sharer out closes everything)
            from nnstreamer_tpu.serving_plane import plane as plane_mod

            self._plane_last_stats = self.plane_stats()
            if self._plane_stream is not None:
                self._plane.detach(self._plane_stream)
                self._plane_stream = None
            plane_mod.release(self.plane, self._plane)
            self._plane = None
            self.backend = None
            self._traceable = None
        if self.backend is not None:
            if not self.shared_key or _shared_release(
                self.shared_key, self.backend
            ):
                self.backend.close()
            self.backend = None
            self._traceable = None
        # replicas 1..N-1 (replica 0 IS self.backend, closed above)
        for b in self._replica_backends[1:]:
            try:
                b.close()
            except Exception as exc:  # noqa: BLE001 — teardown best-effort
                _log.warning("%s: replica close failed: %s", self.name, exc)
        self._replica_backends = []
        if self._replica_set is not None:
            # stats survive teardown (like _elem_stats): post-run
            # assertions and nns-top's final poll read them after stop
            self._replica_last_stats = self._replica_set.stats()
            self._replica_set = None
        if self._fb_backend is not None:
            self._fb_backend.close()
            self._fb_backend = None

    def reload_model(self, model: str) -> None:
        """Hot swap (reference is-updatable + RELOAD_MODEL event)."""
        self._ensure_open().reload(tuple(m for m in model.split(",") if m))
        self._traceable = None
        # invalidate fused-segment cache entries that embed the old fn
        # (shapes unchanged ⇒ same signature key, so the version must tick)
        self.fn_version += 1

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(
                f"{self.name}: needs other/tensors input (add tensor_converter), got {spec}"
            )
        b = self._ensure_open()
        model_in = self._select_model_inputs_spec(spec)
        self._negotiated_model_in = model_in  # fallback opens to this spec
        if not model_in.is_static:
            # flexible input stream (e.g. from a query serversrc or edge
            # src): the model's own spec governs; per-frame tensors are
            # validated at invoke, like the reference parses the flexible
            # header per buffer (tensor_filter.c:617-625)
            self._flexible_input = True
            try:
                _, cur_out = b.get_model_info()
            except Exception as exc:
                raise NegotiationError(
                    f"{self.name}: flexible input needs a model with known "
                    f"input spec (or input=/inputtype= properties): {exc}"
                ) from exc
        else:
            self._flexible_input = False
            try:
                cur_in, _ = b.get_model_info()
            except Exception:
                cur_in = None  # shape-polymorphic: model info needs input
            if cur_in is not None and cur_in.is_compatible(model_in):
                _, cur_out = b.get_model_info()
            else:
                cur_out = b.set_input_info(model_in)
        self._model_out_spec = cur_out
        out = self._compose_output_spec(spec, cur_out)
        return [out.with_rate(spec.rate)]

    def _select_model_inputs_spec(self, spec: TensorsSpec) -> TensorsSpec:
        if self.in_combination is None:
            return spec
        if not spec.is_static:
            # flexible stream: per-frame tensor count is unknown until the
            # frame arrives; the combination indexes are applied (and
            # bounds-checked) at invoke time instead
            return spec
        picks = []
        for kind, idx in self.in_combination:
            if kind == "o":
                raise NegotiationError(f"{self.name}: 'o' not valid in input-combination")
            if idx >= spec.num_tensors:
                raise NegotiationError(
                    f"{self.name}: input-combination index {idx} out of range"
                )
            picks.append(spec[idx])
        return TensorsSpec(tuple(picks), spec.format, spec.rate)

    def _compose_output_spec(
        self, in_spec: TensorsSpec, model_out: TensorsSpec
    ) -> TensorsSpec:
        if self.out_combination is None:
            return model_out
        outs = []
        for kind, idx in self.out_combination:
            src = in_spec if kind == "i" else model_out
            if idx >= src.num_tensors:
                raise NegotiationError(
                    f"{self.name}: output-combination index {idx} out of range"
                )
            outs.append(src[idx])
        return TensorsSpec(tuple(outs), model_out.format, in_spec.rate)

    # -- execution ---------------------------------------------------------
    def is_traceable(self) -> bool:
        if getattr(self, "_flexible_input", False):
            # per-frame shapes: can't be part of a statically-jitted segment
            return False
        if self._fallback_conf:
            # circuit-breaker hot swap needs per-frame invokes: the filter
            # is a deliberate fusion barrier in degradable mode
            return False
        if self.replicas > 1:
            # replica failover is per-frame health-tracked dispatch —
            # a fused program cannot change replicas mid-stream
            return False
        if self.plane:
            # cross-executor batching happens IN the plane: this filter
            # must dispatch per frame into the shared queue
            return False
        b = self._ensure_open()
        return b.traceable_fn() is not None

    def _apply_combinations(self, invoke: Callable) -> Callable:
        in_comb, out_comb = self.in_combination, self.out_combination

        def fn(tensors: Tuple[Any, ...]) -> Tuple[Any, ...]:
            model_in = (
                tensors
                if in_comb is None
                else tuple(tensors[i] for _, i in in_comb)
            )
            model_out = tuple(invoke(model_in))
            if out_comb is None:
                return model_out
            return tuple(
                tensors[i] if kind == "i" else model_out[i]
                for kind, i in out_comb
            )

        return fn

    def make_fn(self) -> Callable:
        b = self._ensure_open()
        traced = b.traceable_fn()
        if traced is None:
            raise RuntimeError(f"{self.name}: backend not traceable")
        return self._apply_combinations(traced)

    def is_identity(self) -> bool:
        """True when the backend declares IS_IDENTITY and no pad
        combination rewires tensors: the fused segment then serves the
        frame without any device program (docs/streaming.md)."""
        if self.in_combination is not None or self.out_combination is not None:
            return False
        try:
            b = self._ensure_open()
        except Exception:  # noqa: BLE001 — open failures surface later
            return False
        return getattr(type(b), "IS_IDENTITY", False)

    # -- replica failover (parallel/replicas.py) ---------------------------
    def _ensure_replicas(self):
        """Open replicas 1..N-1 beside the negotiation backend (replica
        0) and build the ReplicaSet over all N. Lazy: model copies load
        on the first frame, after negotiation settled the specs."""
        if self._replica_set is None:
            from nnstreamer_tpu.parallel.replicas import ReplicaSet

            backends = [self._ensure_open()]
            try:
                for i in range(1, self.replicas):
                    backends.append(
                        self._open_backend(self._replica_custom(i))
                    )
            except Exception:
                # replica 0 is self.backend (stop() owns it); close the
                # partially-opened tail or a retried first frame leaks a
                # fresh copy of every model arena per attempt
                for b in backends[1:]:
                    try:
                        b.close()
                    except Exception as exc:  # noqa: BLE001 — best-effort
                        _log.warning(
                            "%s: replica close failed: %s", self.name, exc
                        )
                raise
            self._replica_backends = backends
            self._replica_set = ReplicaSet(
                [self._make_replica_invoke(b) for b in backends],
                unhealthy_after=self.replica_unhealthy_after,
                probe_every=self.replica_probe_every,
            )
            self._apply_pending_state()
        return self._replica_set

    def _make_replica_invoke(self, b: Backend):
        def invoke(frame: Frame) -> Frame:
            fn = self._apply_combinations(b.invoke_timed)
            t0 = time.perf_counter_ns()
            out = fn(frame.tensors)
            self._elem_stats.record(time.perf_counter_ns() - t0)
            return frame.with_tensors(out)

        return invoke

    def replica_stats(self) -> Dict[str, Any]:
        """Failover observability (Executor.stats() surfaces these as
        ``rep_*``); {} when replicas are off so stats stay noise-free."""
        if self._replica_set is None:
            return getattr(self, "_replica_last_stats", {})
        return self._replica_set.stats()

    # -- serving plane (serving_plane/plane.py) ----------------------------
    def _acquire_plane(self):
        """Get-or-create the named plane and attach this filter as one
        stream. Lazy like _ensure_replicas, but reached at NEGOTIATION
        (the plane's backend doubles as the model-info surface), so the
        plane's service thread predates every executor start."""
        if self._plane is None:
            from nnstreamer_tpu.serving_plane import plane as plane_mod

            cfg = self._plane_cfg or plane_mod.resolve_plane_config(
                [self]
            )
            # a sharer that set no plane-* knobs INHERITS the first
            # attacher's bound config instead of colliding with it
            explicit = any(
                self.get_property(k) is not None
                for k in ("plane-max-batch", "plane-timeout-ms",
                          "plane-mode", "plane-devices")
            )

            def opener(i: int, replicated: bool) -> Backend:
                if replicated:
                    # the _replica:<i> suffix keeps chaos scoping
                    # (FaultyBackend only_replica) working at plane
                    # granularity too
                    return self._open_backend(f"_replica:{i}")
                return self._open_backend()

            self._plane = plane_mod.acquire(
                self.plane, _props_signature(self.fprops), cfg, opener,
                cfg_explicit=explicit,
            )
        if self._plane_stream is None:
            try:
                self._plane_stream = self._plane.attach(
                    self.name, self.plane_weight
                )
            except ValueError:
                # same element name in another pipeline of this process:
                # disambiguate rather than refuse (names are only unique
                # per pipeline)
                self._plane_stream = self._plane.attach(
                    f"{self.name}@{id(self) & 0xffff:04x}",
                    self.plane_weight,
                )
        return self._plane

    def plane_stats(self) -> Dict[str, Any]:
        """Plane observability (Executor.stats() surfaces these as
        ``plane_*``, nns-top --models aggregates them); {} when this
        filter serves no plane. Plane-wide numbers plus THIS stream's
        admit/serve counters (sharers must not report each other's)."""
        if not self.plane:
            return {}
        if self._plane is None:
            return self._plane_last_stats
        d = self._plane.stats()
        s = self._plane_stream
        if s is not None:
            d["stream"] = s.sid
            d["stream_admitted"] = s.admitted
            d["stream_served"] = s.served
            d["stream_errors"] = s.errors
        return d

    def wants_host_input(self) -> bool:
        """Link-level placement negotiation hook (executor
        ``_out_wants_host``, docs/streaming.md): False when this
        filter's backend accepts device-resident inputs (it stages /
        reshards them itself — the jax backend's device_put path), so
        an upstream device node hands frames over WITHOUT forcing a
        coalesced D2H. Host-library backends (torch/tflite) keep True:
        they read tensor bytes on host and want the prefetch."""
        b = self.backend
        if b is None:
            return True
        return not getattr(type(b), "DEVICE_INPUT_OK", False)

    # -- warm restart (docs/resilience.md) ---------------------------------
    def state_snapshot(self) -> Dict[str, Any]:
        """Executor.snapshot() hook: the opened backend's own state (a
        framecounter-style stateful backend) plus replica health, so a
        drain/snapshot/resume round-trip neither re-serves a benched
        replica nor re-discovers its sickness frame by frame."""
        d: Dict[str, Any] = {}
        hook = getattr(self.backend, "state_snapshot", None)
        if callable(hook):
            d["backend"] = hook()
        if self._replica_set is not None:
            d["replica_set"] = self._replica_set.snapshot()
            # replicas 1..N-1 are independent backend copies with their
            # own state (replica 0 IS self.backend, captured above) —
            # index-aligned list, None for stateless replicas
            reps = []
            for b in self._replica_backends[1:]:
                h = getattr(b, "state_snapshot", None)
                reps.append(h() if callable(h) else None)
            if any(r is not None for r in reps):
                d["replica_backends"] = reps
        return d

    def state_restore(self, snap: Dict[str, Any]) -> None:
        """Restoring into a FRESH executor happens before the first
        frame, when the backend is unopened and the replica set unbuilt
        — applying eagerly would silently drop replica health and
        backend state. Stash and apply what exists now; _ensure_open /
        _ensure_replicas re-apply the rest once their target is up."""
        self._pending_state = dict(snap)
        self._apply_pending_state()

    def _apply_pending_state(self) -> None:
        snap = self._pending_state
        if not snap:
            return
        if "backend" in snap and self.backend is not None:
            hook = getattr(self.backend, "state_restore", None)
            if callable(hook):
                hook(snap["backend"])
            del snap["backend"]
        if "replica_set" in snap and self._replica_set is not None:
            self._replica_set.restore(snap["replica_set"])
            del snap["replica_set"]
        if "replica_backends" in snap and self._replica_backends:
            for b, s in zip(
                self._replica_backends[1:], snap["replica_backends"]
            ):
                if s is None:
                    continue
                h = getattr(b, "state_restore", None)
                if callable(h):
                    h(s)
            del snap["replica_backends"]
        if not snap:
            self._pending_state = None

    def host_process(self, frame: Frame) -> Frame:
        if self.plane:
            # one stream's frame into the shared cross-executor batch;
            # plane invoke errors surface HERE, per frame, where this
            # node's on-error policy (and, for admitted edge requests,
            # the NACK/release accounting) already applies per stream
            plane = self._acquire_plane()
            in_comb, out_comb = self.in_combination, self.out_combination
            send = frame
            if in_comb is not None:
                send = frame.with_tensors(
                    tuple(frame.tensors[i] for _, i in in_comb)
                )
            t0 = time.perf_counter_ns()
            served = plane.submit(self._plane_stream, send)
            self._elem_stats.record(time.perf_counter_ns() - t0)
            if out_comb is None:
                return frame.with_tensors(served.tensors)
            model_out = served.tensors
            return frame.with_tensors(tuple(
                frame.tensors[i] if kind == "i" else model_out[i]
                for kind, i in out_comb
            ))
        if self.replicas > 1:
            # device faults fail the frame over to the next healthy
            # replica; ReplicaExhaustedError (nothing healthy) falls to
            # this node's on-error policy — for admitted edge requests
            # that NACKs the client and releases its admission budget
            # exactly once (PR-6 accounting)
            return self._ensure_replicas().dispatch(frame)
        if not self._fallback_conf:
            return self._invoke_primary(frame)
        # circuit breaker (docs/fault-tolerance.md): consecutive primary
        # failures open the circuit and the fallback backend serves;
        # periodic probes close it again once the primary recovers
        if self._circuit_open:
            self._since_probe += 1
            if self._since_probe >= self.fallback_probe_every:
                self._since_probe = 0
                try:
                    out = self._invoke_primary(frame)
                except Exception as exc:  # noqa: BLE001 — probe failed
                    self._cb["primary_failures"] += 1
                    _log.debug("%s: recovery probe failed: %s", self.name, exc)
                else:
                    self._circuit_open = False
                    self._consec_failures = 0
                    self._cb["circuit_closes"] += 1
                    _log.warning(
                        "%s: primary backend recovered; circuit closed",
                        self.name,
                    )
                    return out
            return self._invoke_fallback(frame)
        try:
            out = self._invoke_primary(frame)
        except Exception:
            self._consec_failures += 1
            self._cb["primary_failures"] += 1
            if self._consec_failures >= self.fallback_after:
                self._circuit_open = True
                self._since_probe = 0
                self._cb["circuit_opens"] += 1
                _log.warning(
                    "%s: %d consecutive backend failures; circuit OPEN — "
                    "serving from fallback %s",
                    self.name, self._consec_failures,
                    self.fallback_framework or self.fprops.framework,
                )
                # this frame survives on the fallback instead of dying
                return self._invoke_fallback(frame)
            # below the threshold: the node's on-error policy decides
            raise
        self._consec_failures = 0
        return out

    def _invoke_primary(self, frame: Frame) -> Frame:
        b = self._ensure_open()
        fn = self._apply_combinations(b.invoke_timed)
        lock = getattr(b, "shared_invoke_lock", None)
        # time inside the shared lock so per-element stats report this
        # element's invoke, not other sharers' lock-wait
        if lock is not None:
            with lock:
                t0 = time.perf_counter_ns()
                out = fn(frame.tensors)
                dt = time.perf_counter_ns() - t0
        else:
            t0 = time.perf_counter_ns()
            out = fn(frame.tensors)
            dt = time.perf_counter_ns() - t0
        self._elem_stats.record(dt)
        return frame.with_tensors(out)

    # -- circuit-breaker fallback ------------------------------------------
    def _ensure_fallback(self) -> Backend:
        if self._fb_open_error is not None:
            # an unopenable fallback is latched: re-loading the model per
            # frame while the circuit is open would turn a misconfigured
            # path into a model-load attempt per frame
            raise BackendError(
                f"{self.name}: fallback backend failed to open: "
                f"{self._fb_open_error}"
            ) from self._fb_open_error
        if self._fb_backend is None:
            try:
                self._fb_backend = self._open_fallback()
            except Exception as exc:
                self._fb_open_error = exc
                raise
        return self._fb_backend

    def _open_fallback(self) -> Backend:
        fw = self.fallback_framework or self.fprops.framework
        models = tuple(
            m for m in self.fallback_model.split(",") if m
        ) or self.fprops.model
        props = dataclasses.replace(
            self.fprops, framework=fw, model=models
        )
        cls = registry.get(registry.KIND_FILTER, fw)
        b: Backend = cls()
        b.open(props)
        # the swap is invisible downstream only if the fallback keeps
        # the negotiated output spec — verify once at open
        model_in = getattr(self, "_negotiated_model_in", None)
        if model_in is not None and model_in.is_static:
            try:
                cur_in, fb_out = b.get_model_info()
            except Exception:
                fb_out = b.set_input_info(model_in)
            else:
                if not cur_in.is_compatible(model_in):
                    fb_out = b.set_input_info(model_in)
            want = getattr(self, "_model_out_spec", None)
            if want is not None and not fb_out.is_compatible(want):
                b.close()
                raise BackendError(
                    f"{self.name}: fallback output spec {fb_out} is not "
                    f"compatible with the negotiated {want}"
                )
        return b

    def _invoke_fallback(self, frame: Frame) -> Frame:
        b = self._ensure_fallback()
        fn = self._apply_combinations(b.invoke_timed)
        t0 = time.perf_counter_ns()
        out = fn(frame.tensors)
        self._elem_stats.record(time.perf_counter_ns() - t0)
        self._cb["fallback_invokes"] += 1
        return frame.with_tensors(out)

    def circuit_stats(self) -> Dict[str, float]:
        """Circuit-breaker observability (Executor.stats() surfaces these
        as ``cb_*`` next to latency/throughput); {} when no fallback is
        configured so stats stay noise-free."""
        if not self._fallback_conf:
            return {}
        return {
            **self._cb,
            "fallback_active": 1 if self._circuit_open else 0,
        }

    # -- host micro-batching (pipeline/batching.py) ------------------------
    def is_batch_capable(self) -> bool:
        """Host path may micro-batch only when the backend declared the
        capability; flexible per-frame shapes can't share one invoke, and
        a degradable filter (fallback configured) stays per-frame so the
        circuit breaker counts and swaps at frame granularity."""
        if getattr(self, "_flexible_input", False):
            return False
        if self._fallback_conf:
            return False
        if self.replicas > 1:
            # failover granularity is one frame: a window dispatched to
            # a dying replica would fail over whole
            return False
        if self.plane:
            # the local window IS the plane submission unit: one
            # round-trip per collected window instead of per frame
            return True
        return bool(getattr(self._ensure_open(), "batchable", False))

    def _plane_window_inputs(self, frames: List[Frame]) -> List[tuple]:
        """Per-frame model input tuples for one plane window
        (input-combination applied) — shared by the blocking and async
        submit paths."""
        in_comb = self.in_combination
        return [
            f.tensors if in_comb is None
            else tuple(f.tensors[i] for _, i in in_comb)
            for f in frames
        ]

    def _finish_plane_window(
        self, frames: List[Frame], model_outs, per: int
    ) -> List[Frame]:
        """Rebuild output frames from one served plane window
        (output-combination applied, ``per``-ns stat per frame) — ONE
        implementation so the blocking and async paths cannot drift."""
        out_comb = self.out_combination
        outs: List[Frame] = []
        for f, model_out in zip(frames, model_outs):
            self._elem_stats.record(per)
            if out_comb is None:
                tensors = tuple(model_out)
            else:
                tensors = tuple(
                    f.tensors[i] if kind == "i" else model_out[i]
                    for kind, i in out_comb
                )
            outs.append(f.with_tensors(tensors))
        return outs

    def host_submit_window_async(self, frames: List[Frame]):
        """Non-blocking plane submit of one collected window
        (docs/serving-plane.md): returns an opaque ticket for
        :meth:`host_collect_window`. The executor's plane ring parks up
        to ``plane_inflight`` tickets so window N+1 submits while N
        computes on the plane and N−1 delivers downstream."""
        plane = self._acquire_plane()
        req = plane.submit_window_async(
            self._plane_stream, self._plane_window_inputs(frames)
        )
        return (req, frames)

    def host_collect_window(self, ticket) -> List[Frame]:
        """Redeem one async plane ticket (strictly in submission order
        — the executor ring is FIFO, so per-stream order is
        structural). Raises the window's invoke error whole; the
        executor then splits it per frame through this node's error
        policy via :meth:`host_process`, the blocking re-invoke unit.
        Plane outputs pass through UNTOUCHED — device arrays stay
        device-resident for downstream consumers (the PR-8 handoff)."""
        req, frames = ticket
        t0 = time.perf_counter_ns()
        model_outs = self._plane.wait_window(self._plane_stream, req)
        # per-frame share of the RESIDUAL wait (overlap ate the rest) —
        # the honest async latency, matching nns_plane_submit_wait_ms
        per = (time.perf_counter_ns() - t0) // max(1, len(frames))
        return self._finish_plane_window(frames, model_outs, per)

    def host_process_batch(self, frames: List[Frame]) -> List[Frame]:
        """One invoke_batched() call for the window: combinations applied
        per frame, ONE timed section (and one shared-lock acquisition)
        amortized over the whole batch."""
        if self.plane:
            # the whole local window rides ONE plane round-trip; the
            # plane flattens it with other streams' windows into one
            # device dispatch (serving_plane/plane.py). A window error
            # raises whole — the executor's ladder then splits per
            # frame through host_process, per-stream accounting intact.
            plane = self._acquire_plane()
            t0 = time.perf_counter_ns()
            model_outs = plane.submit_window(
                self._plane_stream, self._plane_window_inputs(frames)
            )
            per = (time.perf_counter_ns() - t0) // max(1, len(frames))
            return self._finish_plane_window(frames, model_outs, per)
        sig0 = tuple((t.shape, t.dtype) for t in frames[0].tensors)
        if any(
            tuple((t.shape, t.dtype) for t in f.tensors) != sig0
            for f in frames[1:]
        ):
            # heterogeneous window (flexible-ish source): frames can't
            # share one stacked invoke — per-frame fallback, same
            # semantics (parity with FusedSegment.process_batch)
            return [self.host_process(f) for f in frames]
        b = self._ensure_open()
        in_comb, out_comb = self.in_combination, self.out_combination
        model_ins = [
            f.tensors if in_comb is None
            else tuple(f.tensors[i] for _, i in in_comb)
            for f in frames
        ]
        lock = getattr(b, "shared_invoke_lock", None)
        t0 = time.perf_counter_ns()
        if lock is not None:
            with lock:
                model_outs = b.invoke_batched(model_ins)
        else:
            model_outs = b.invoke_batched(model_ins)
        dt = time.perf_counter_ns() - t0
        # per-frame share so latency_us stays per-invoke comparable
        per = dt // max(1, len(frames))
        for _ in frames:
            self._elem_stats.record(per)
            b.stats.record(per)
        outs: List[Frame] = []
        for f, model_out in zip(frames, model_outs):
            model_out = tuple(model_out)
            if out_comb is None:
                tensors = model_out
            else:
                tensors = tuple(
                    f.tensors[i] if kind == "i" else model_out[i]
                    for kind, i in out_comb
                )
            outs.append(f.with_tensors(tensors))
        return outs

    # -- stats (reference read-only latency/throughput props) -------------
    @property
    def invoke_stats(self) -> InvokeStats:
        """This element's own invokes only (survives teardown; sharers of
        one backend do not see each other's numbers)."""
        return self._elem_stats

    @property
    def latency_us(self) -> float:
        return self._elem_stats.latency_us

    @property
    def throughput_fps(self) -> float:
        return self._elem_stats.throughput_fps

    # micro-batching observability (read-only, like latency/throughput):
    # stats live on the fused segment (or this element on the host path)
    # via the shared BatchStats assigned at plan time.
    @property
    def avg_batch_size(self) -> float:
        s = self.batch_stats
        return s.avg_batch_size if s is not None else 0.0

    @property
    def pad_waste_pct(self) -> float:
        s = self.batch_stats
        return s.pad_waste_pct if s is not None else 0.0

    @property
    def batch_wait_ms(self) -> float:
        s = self.batch_stats
        return s.batch_wait_ms if s is not None else 0.0
