"""Flow elements: tee, queue, capsfilter.

(The remaining routing/sync elements — mux/demux/merge/split/aggregator/
rate/if/crop/repo/sparse/join — live in routing.py / sync.py.)
"""

from __future__ import annotations

from typing import List, Tuple

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    NegotiationError,
    PROPS_ANY,
    PropSpec,
    Routing,
    Spec,
    TensorOp,
)
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


@registry.element("tee")
class Tee(Routing):
    """1→N fan-out. Device arrays are immutable, so branching is free —
    no buffer copy-on-write like GStreamer refcounting needs."""

    FACTORY_NAME = "tee"
    N_SINKS = 1
    N_SRCS = None  # request pads

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        return [spec] * self._n_srcs

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        return [(i, frame) for i in range(self._n_srcs)]


@registry.element("queue")
class Queue(TensorOp):
    """Explicit buffering boundary. In this runtime every element already
    has bounded input queues (executor), so queue only tunes the downstream
    element's depth via max-size-buffers and forces a segment split (it is
    intentionally NOT fused so its two sides pipeline on separate threads,
    exactly the reference's use of queue for parallelism)."""

    FACTORY_NAME = "queue"

    # never reads tensor bytes: device arrays pass through, so adjacent
    # fused segments hand off device-resident ACROSS a queue
    # (docs/streaming.md)
    DEVICE_PASSTHROUGH = True

    PROPERTIES = {
        "max-size-buffers": PropSpec(
            "int", 64, desc="depth of the downstream element's input queue"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        # matches the executor's default channel depth (elements/base.py):
        # an explicit queue should not silently SHRINK the link it tunes
        self.queue_size = int(self.get_property("max-size-buffers", 64))

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        return list(in_specs)

    def is_traceable(self) -> bool:
        return False  # fusion barrier by design

    def host_process(self, frame: Frame) -> Frame:
        return frame


@registry.element("capsfilter")
class CapsFilter(TensorOp):
    """Constrain/refine the negotiated spec (gst capsfilter / the caps
    string between ! in a description).

    Tensor links: props dimensions/types/format/framerate are matched and
    merged into the upstream spec. Media links: props media/width/height/
    format are validated against the MediaSpec. Identity at runtime — fused
    to zero cost on tensor links, host passthrough on media links."""

    FACTORY_NAME = "capsfilter"

    # identity over tensor bytes: device-resident handoff chains across
    # it like queue (docs/streaming.md)
    DEVICE_PASSTHROUGH = True

    # caps tokens carry arbitrary media fields (media/width/height/...):
    # the schema is open-ended, so PROPS_ANY opts out of unknown-property
    # linting for this element only
    PROPERTIES = {
        "dimensions": PropSpec("str", None),
        "types": PropSpec("str", "float32"),
        "format": PropSpec("str", None),
        "framerate": PropSpec("fraction", None),
        "media": PropSpec("str", None),
        "width": PropSpec("int", None),
        "height": PropSpec("int", None),
        PROPS_ANY: PropSpec("str", None, desc="raw caps fields pass through"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        dims = self.get_property("dimensions")
        self._constraint = None
        if dims:
            self._constraint = TensorsSpec.from_strings(
                str(dims),
                str(self.get_property("types", "float32")),
                format=str(self.get_property("format", "static")),
                rate=self.get_property("framerate"),
            )

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if isinstance(spec, TensorsSpec):
            if self._constraint is None:
                return [spec]
            if not spec.is_compatible(self._constraint):
                raise NegotiationError(
                    f"{self.name}: caps mismatch {spec} vs {self._constraint}"
                )
            return [spec.merge(self._constraint)]
        # media link: validate the declared fields
        for key, attr in (("width", "width"), ("height", "height")):
            want = self.get_property(key)
            if want is not None and getattr(spec, attr) != int(want):
                raise NegotiationError(
                    f"{self.name}: media {key} {getattr(spec, attr)} != {want}"
                )
        want_fmt = self.get_property("format")
        if want_fmt and spec.format != want_fmt:
            raise NegotiationError(
                f"{self.name}: media format {spec.format} != {want_fmt}"
            )
        return [spec]

    def is_traceable(self) -> bool:
        from nnstreamer_tpu.tensors.spec import TensorsSpec as _TS

        return bool(self.in_specs) and isinstance(self.in_specs[0], _TS)

    def make_fn(self):
        return lambda tensors: tensors

    def host_process(self, frame: Frame) -> Frame:
        return frame
