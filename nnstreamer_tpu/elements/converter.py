"""tensor_converter: media → other/tensors ingress.

Reference: gst/nnstreamer/elements/gsttensor_converter.c (chain :1015,
media-type dispatch :1046-1270). Direct converters for video/audio/text/
octet media, flexible→static, plus converter subplugins (mode=) for
arbitrary formats, plus in-process custom callbacks
(``mode=custom-code:<name>``, the nnstreamer_converter_custom_register
analogue — :1220-1250 _NNS_MEDIA_ANY dispatch). This is the host→device
boundary: output tensors are handed (as tight numpy arrays) to the first
fused XLA segment, which uploads once — no per-element map/unmap.

Video: HWC uint8 → (frames-per-tensor, H, W, C); the reference's innermost-
first dim string C:W:H:N describes the same canonical NHWC layout.
frames-per-tensor > 1 batches frames (GstAdapter parity, :701-712); a
partial batch at EOS is dropped like leftover adapter bytes.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    FAULT_PROPS,
    MediaSpec,
    NegotiationError,
    PropSpec,
    Spec,
    TensorOp,
    install_error_pad,
)
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorFormat, TensorSpec, TensorsSpec

_custom_lock = threading.Lock()
_custom_converters: Dict[str, Callable] = {}


def register_custom_converter(
    name: str, fn: Callable[[Frame, dict], Frame]
) -> None:
    """nnstreamer_converter_custom_register analogue: an in-process
    callable ``fn(frame, props) -> Frame`` invoked per input buffer.
    Output frames are self-describing (format=flexible) downstream."""
    with _custom_lock:
        _custom_converters[name] = fn


def unregister_custom_converter(name: str) -> bool:
    with _custom_lock:
        return _custom_converters.pop(name, None) is not None


@registry.element("tensor_converter")
class TensorConverter(TensorOp):
    """A TensorOp so the hot ingress paths FUSE into the downstream XLA
    program (the batch-dim reshape happens inside the same compiled
    segment as the filter — SURVEY §7's device-resident mandate); the
    stateful/byte-level paths (frames-per-tensor batching, octet framing,
    subplugins, flexible→static) run as a host node instead."""

    FACTORY_NAME = "tensor_converter"

    PROPERTIES = {
        "frames-per-tensor": PropSpec("int", 1, desc="batch N frames"),
        "mode": PropSpec(
            "str", None,
            desc="converter subplugin, custom-code:<name>, or "
            "custom-script:<path.py>",
        ),
        "input-dim": PropSpec("str", None, desc="octet framing dims"),
        "input-type": PropSpec("str", "uint8"),
        "input-norm": PropSpec(
            "str", None,
            desc="MEAN:STD — fuse (x - MEAN)/STD uint8→float32 "
            "normalization into the ingress (video input; the op "
            "rides the downstream XLA segment, docs/on-device-ops.md)",
        ),
        "script": PropSpec("str", None, desc="python3 subplugin script path"),
        # per-frame error policy (pipeline/faults.py)
        **FAULT_PROPS,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.frames_per_tensor = int(self.get_property("frames-per-tensor", 1))
        self.mode = self.get_property("mode")  # converter subplugin name
        self.input_dims = self.get_property("input-dim")
        self.input_types = self.get_property("input-type", "uint8")
        raw_norm = self.get_property("input-norm")
        self.input_norm = None
        if raw_norm:
            mean, sep, std = str(raw_norm).partition(":")
            if not sep or not std:
                # a missing STD must not silently default: (x-MEAN)/1.0
                # is exactly the wrongly-scaled-features failure this
                # property exists to prevent
                raise ValueError(
                    f"{self.name}: input-norm={raw_norm!r} (want MEAN:STD)"
                )
            try:
                self.input_norm = (float(mean), float(std))
            except ValueError as exc:
                raise ValueError(
                    f"{self.name}: input-norm={raw_norm!r} (want MEAN:STD)"
                ) from exc
            if self.input_norm[1] == 0.0:
                raise ValueError(f"{self.name}: input-norm STD must be nonzero")
        self._batch: List[np.ndarray] = []
        self._batch_pts = None
        self._subplugin = None
        self._custom_fn = None
        self._traceable_fn = None
        install_error_pad(self)

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        self._traceable_fn = None
        if self.input_norm is not None and (
            self.mode
            or not (
                isinstance(spec, MediaSpec) and spec.media_type == "video"
            )
        ):
            # fail loudly: a silently un-applied normalization would
            # feed downstream models un-normalized pixels (subplugin/
            # custom modes convert on their own terms)
            raise NegotiationError(
                f"{self.name}: input-norm applies to direct video "
                f"conversion only, got mode={self.mode!r} over {spec}"
            )
        if self.mode and self.mode.startswith("custom-code"):
            _, _, name = self.mode.partition(":")
            with _custom_lock:
                fn = _custom_converters.get(name)
            if fn is None:
                raise NegotiationError(
                    f"{self.name}: custom converter {name!r} not registered"
                )
            self._custom_fn = fn
            # custom callbacks declare no static shape; frames are
            # self-describing (the reference emits flexible caps here too)
            rate = getattr(spec, "rate", None)
            return [TensorsSpec(format=TensorFormat.FLEXIBLE, rate=rate)]
        if self.mode and self.mode.startswith("custom-script"):
            # reference spelling for the python script converter:
            # mode=custom-script:<path.py> (gsttensor_converter.c mode prop)
            _, _, path = self.mode.partition(":")
            if path:
                self.props.setdefault("script", path)
            self.mode = "python3"
        if self.mode:
            self._subplugin = registry.get(registry.KIND_CONVERTER, self.mode)
            sub = self._subplugin() if isinstance(self._subplugin, type) else self._subplugin
            self._subplugin = sub
            return [sub.negotiate(spec, dict(self.props))]
        if isinstance(spec, MediaSpec):
            if spec.media_type == "video":
                if spec.width is None or spec.height is None:
                    raise NegotiationError(f"{self.name}: video size unknown")
                c = spec.channels_per_pixel
                dtype = DType.FLOAT32 if self.input_norm else DType.UINT8
                out = TensorSpec(
                    (self.frames_per_tensor, spec.height, spec.width, c), dtype
                )
                rate = spec.rate / self.frames_per_tensor if spec.rate else None
                if self.frames_per_tensor == 1:
                    # HWC → NHWC is one reshape: fuse it into the
                    # downstream XLA program (no host copy, no queue
                    # hop). input-norm folds the uint8→float
                    # normalization into the same fused op, so the
                    # classic preprocessing transform costs zero extra
                    # HBM round trips (docs/on-device-ops.md).
                    if self.input_norm:
                        mean, std = self.input_norm

                        def _norm_fn(tensors):
                            import jax.numpy as jnp

                            x = jnp.asarray(tensors[0]).astype(jnp.float32)
                            return (((x - mean) / std)[None, ...],)

                        self._traceable_fn = _norm_fn
                    else:
                        self._traceable_fn = (
                            lambda tensors: (tensors[0][None, ...],)
                        )
                return [TensorsSpec.of(out, rate=rate)]
            if spec.media_type == "audio":
                if spec.channels is None:
                    raise NegotiationError(f"{self.name}: audio channels unknown")
                dt = {"S16LE": DType.INT16, "U8": DType.UINT8, "F32LE": DType.FLOAT32}[
                    spec.sample_format
                ]
                # per-buffer sample count is data-dependent; wildcard until
                # first frame unless frames-per-tensor pins it
                return [
                    TensorsSpec.of(TensorSpec((None, spec.channels), dt))
                ]
            if spec.media_type in ("octet", "text"):
                if not self.input_dims:
                    raise NegotiationError(
                        f"{self.name}: {spec.media_type} input needs input-dim="
                    )
                out = TensorsSpec.from_strings(self.input_dims, self.input_types)
                return [out]
            raise NegotiationError(f"{self.name}: unsupported media {spec.media_type}")
        if isinstance(spec, TensorsSpec):
            if spec.format is TensorFormat.FLEXIBLE:
                # flexible → static requires declared dims (reference
                # flexible-to-static path)
                if not self.input_dims:
                    raise NegotiationError(
                        f"{self.name}: flexible→static needs input-dim="
                    )
                return [TensorsSpec.from_strings(self.input_dims, self.input_types)]
            self._traceable_fn = lambda tensors: tensors
            return [spec]  # static passthrough
        raise NegotiationError(f"{self.name}: cannot convert {spec!r}")

    # -- execution classification -------------------------------------------
    def is_traceable(self) -> bool:
        return self._traceable_fn is not None

    def make_fn(self):
        return self._traceable_fn

    # -- streaming (host path: batching/subplugins/byte framing) -----------
    def host_process(self, frame: Frame) -> Union[Frame, List[Frame], None]:
        if self._custom_fn is not None:
            return self._custom_fn(frame, dict(self.props))
        if self._subplugin is not None:
            return self._subplugin.convert(frame, dict(self.props))
        in_spec = self.in_specs[0]
        if isinstance(in_spec, MediaSpec):
            if in_spec.media_type == "video":
                return self._convert_video(frame)
            if in_spec.media_type == "audio":
                chunk = np.asarray(frame.tensors[0])
                if self.frames_per_tensor <= 1:
                    return frame.with_tensors((chunk,))
                # batch N chunks along the sample axis (GstAdapter parity)
                self._batch.append(chunk)
                if len(self._batch) == 1:
                    self._batch_pts = frame.pts
                if len(self._batch) < self.frames_per_tensor:
                    return None
                merged = np.concatenate(self._batch, axis=0)
                self._batch.clear()
                dur = (
                    frame.duration * self.frames_per_tensor
                    if frame.duration is not None
                    else None
                )
                return Frame(
                    (merged,), pts=self._batch_pts, duration=dur, meta=dict(frame.meta)
                )
            if in_spec.media_type in ("octet", "text"):
                return self._convert_octet(frame)
        out_spec: TensorsSpec = self.out_specs[0]
        if isinstance(in_spec, TensorsSpec) and in_spec.format is TensorFormat.FLEXIBLE:
            # validate per-frame shapes against declared static spec
            tensors = []
            for t, s in zip(frame.tensors, out_spec):
                a = np.asarray(t)
                if a.size != s.element_count:
                    raise ValueError(
                        f"{self.name}: flexible frame size {a.size} != {s.element_count}"
                    )
                tensors.append(a.reshape(s.shape).astype(s.dtype.np_dtype, copy=False))
            return frame.with_tensors(tensors)
        return frame

    def _convert_video(self, frame: Frame) -> Optional[Frame]:
        # device-resident frames batch ON DEVICE (jnp.stack — one async
        # dispatch), never through np.asarray: forcing a device frame to
        # host here would cost a D2H round trip PER FRAME exactly on the
        # chained-device-pipeline path the frames-per-tensor batching
        # exists to accelerate (gsttensor_converter.c:701-712 adapter
        # batching, rebuilt at the device boundary)
        t0 = frame.tensors[0]
        on_device = hasattr(t0, "devices")
        img = t0 if on_device else np.asarray(t0)  # HWC

        def _norm(batch):
            if self.input_norm is None:
                return batch
            mean, std = self.input_norm
            if hasattr(batch, "devices"):
                import jax.numpy as jnp

                return (jnp.asarray(batch).astype(jnp.float32) - mean) / std
            return (np.asarray(batch, np.float32) - mean) / std

        if self.frames_per_tensor == 1:
            return frame.with_tensors((_norm(img[None, ...]),))
        self._batch.append(img)
        if len(self._batch) == 1:
            self._batch_pts = frame.pts
        if len(self._batch) < self.frames_per_tensor:
            return None
        if any(hasattr(t, "devices") for t in self._batch):
            import jax.numpy as jnp

            batch = jnp.stack(self._batch, axis=0)
        else:
            batch = np.stack(self._batch, axis=0)
        batch = _norm(batch)
        self._batch.clear()
        dur = (
            frame.duration * self.frames_per_tensor
            if frame.duration is not None
            else None
        )
        return Frame((batch,), pts=self._batch_pts, duration=dur, meta=dict(frame.meta))

    def _convert_octet(self, frame: Frame) -> Frame:
        data = np.asarray(frame.tensors[0], dtype=np.uint8).tobytes()
        out_spec: TensorsSpec = self.out_specs[0]
        tensors = []
        offset = 0
        for s in out_spec:
            n = s.byte_size
            if len(data) - offset < n:
                raise ValueError(
                    f"{self.name}: octet frame too small ({len(data)} bytes, "
                    f"need {offset + n})"
                )
            a = np.frombuffer(data[offset : offset + n], dtype=s.dtype.np_dtype)
            tensors.append(a.reshape(s.shape))
            offset += n
        return frame.with_tensors(tensors)

    def stop(self) -> None:
        self._batch.clear()
