"""tensor_chaos: fault injection for pipelines under test.

The reference validates failure handling with golden "expect fail" sweeps
(§5.3) — build-time failures only. This element injects RUNTIME faults
into a live stream so the fault-tolerance layer (pipeline/faults.py,
docs/fault-tolerance.md) can be driven end-to-end: frame corruption
(shape-truncated tensors a strict downstream backend rejects), latency
spikes, bounded hangs (stall-watchdog food), and raised exceptions (which
this element's OWN ``on-error`` policy — or the default stop — handles).

A passthrough otherwise: specs and frames flow unchanged. Deterministic
by construction (``seed`` + counters), so chaos runs reproduce.
"""

from __future__ import annotations

import random
import time
from typing import List, Union

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    FAULT_PROPS,
    ElementError,
    HostElement,
    PropSpec,
    Spec,
    install_error_pad,
)
from nnstreamer_tpu.tensors.frame import Frame

_RAISES = {
    "element": ElementError,
    "value": ValueError,
    "runtime": RuntimeError,
}


@registry.element("tensor_chaos")
class TensorChaos(HostElement):
    """Passthrough chaos injector (docs/fault-tolerance.md).

    Props: ``fail-rate`` (probability an input raises), ``fail-every-n``
    (every Nth frame raises), ``corrupt-every-n`` (every Nth frame's
    tensors are shape-truncated and tagged ``chaos_corrupted`` meta),
    ``delay-ms``/``delay-every-n`` (latency injection), ``hang-on-frame``/
    ``hang-ms`` (one bounded hang, for stall-watchdog tests),
    ``raise-type`` (element|value|runtime), ``device-fault-kind``/
    ``device-fault-every-n`` (typed device-plane faults for the
    resilience layer, docs/resilience.md), ``seed``. Combine with
    ``on-error`` to exercise this element's own policy, or place it
    upstream of a strict backend (``framework=faulty
    custom=strict_shapes:true``) to drive the downstream policy."""

    FACTORY_NAME = "tensor_chaos"
    # passthrough 1:1 (even corrupted frames are delivered): sanitizer
    # frame accounting applies, which is exactly what chaos runs exercise
    SAN_ONE_TO_ONE = True

    PROPERTIES = {
        "fail-rate": PropSpec(
            "float", 0.0, desc="probability an input frame raises"
        ),
        "fail-every-n": PropSpec(
            "int", 0, desc="every Nth frame raises (0 = never)"
        ),
        "corrupt-every-n": PropSpec(
            "int", 0, desc="every Nth frame emits shape-truncated tensors"
        ),
        "delay-ms": PropSpec("float", 0.0, desc="injected per-frame delay"),
        "delay-every-n": PropSpec(
            "int", 1, desc="apply delay-ms every Nth frame"
        ),
        "hang-on-frame": PropSpec(
            "int", 0, desc="frame number that hangs once (0 = never)"
        ),
        "hang-ms": PropSpec(
            "float", 0.0, desc="bounded hang duration for hang-on-frame"
        ),
        "raise-type": PropSpec(
            "enum", "element", ("element", "value", "runtime"),
            desc="exception class injected failures raise",
        ),
        # device-plane chaos (pipeline/device_faults.py): raise a TYPED
        # device fault so the resilience layer — classifier, replica
        # failover, NACK/release accounting — is drivable from any
        # pipeline position without a faulty backend
        "device-fault-kind": PropSpec(
            "enum", "", ("", "oom", "compile", "device_lost", "transient"),
            desc="device fault class device-fault-every-n injects "
            "(docs/resilience.md)",
        ),
        "device-fault-every-n": PropSpec(
            "int", 0,
            desc="every Nth frame raises the typed device fault (0 = never)",
        ),
        "seed": PropSpec("int", 0, desc="RNG seed (reproducible chaos)"),
        **FAULT_PROPS,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.fail_rate = float(self.get_property("fail-rate", 0.0))
        self.fail_every_n = int(self.get_property("fail-every-n", 0))
        self.corrupt_every_n = int(self.get_property("corrupt-every-n", 0))
        self.delay_ms = float(self.get_property("delay-ms", 0.0))
        self.delay_every_n = max(1, int(self.get_property("delay-every-n", 1)))
        self.hang_on_frame = int(self.get_property("hang-on-frame", 0))
        self.hang_ms = float(self.get_property("hang-ms", 0.0))
        raise_type = str(self.get_property("raise-type", "element")).lower()
        if raise_type not in _RAISES:
            raise ValueError(
                f"{self.name}: raise-type={raise_type!r} not one of "
                f"{'/'.join(_RAISES)}"
            )
        self._exc = _RAISES[raise_type]
        self.device_fault_kind = str(
            self.get_property("device-fault-kind", "") or ""
        ).lower()
        self.device_fault_every_n = int(
            self.get_property("device-fault-every-n", 0)
        )
        if self.device_fault_every_n and not self.device_fault_kind:
            raise ValueError(
                f"{self.name}: device-fault-every-n needs device-fault-kind"
            )
        self._rng = random.Random(int(self.get_property("seed", 0)))
        self._n = 0
        self._hung = False
        install_error_pad(self)

    def _device_exc(self):
        from nnstreamer_tpu.pipeline import device_faults as df

        return {
            "oom": df.DeviceOOMError,
            "compile": df.DeviceCompileError,
            "device_lost": df.DeviceLostError,
            "transient": df.DeviceFaultError,
        }[self.device_fault_kind]

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        return [spec]

    def process(self, frame: Frame) -> Union[Frame, None]:
        self._n += 1
        n = self._n
        if (
            not self._hung
            and self.hang_on_frame
            and n == self.hang_on_frame
            and self.hang_ms > 0
        ):
            # BOUNDED hang (sliced sleep): long enough for the stall
            # watchdog to fire, short enough that teardown's thread
            # joins still succeed
            self._hung = True
            deadline = time.monotonic() + self.hang_ms / 1000.0
            while time.monotonic() < deadline:
                time.sleep(0.025)
        if self.delay_ms > 0 and n % self.delay_every_n == 0:
            time.sleep(self.delay_ms / 1000.0)
        if self.device_fault_every_n and n % self.device_fault_every_n == 0:
            raise self._device_exc()(
                f"{self.name}: injected {self.device_fault_kind} device "
                f"fault on frame {n}"
            )
        if self.fail_every_n and n % self.fail_every_n == 0:
            raise self._exc(f"{self.name}: injected failure on frame {n}")
        if self.fail_rate and self._rng.random() < self.fail_rate:
            raise self._exc(f"{self.name}: injected random failure (frame {n})")
        if self.corrupt_every_n and n % self.corrupt_every_n == 0:
            # shape truncation: flatten and drop the last element — a
            # strict consumer (faulty strict_shapes, a static jit) rejects
            # it, an inspecting DLQ consumer sees what arrived
            import numpy as np

            corrupted = [
                np.asarray(t).reshape(-1)[:-1] for t in frame.tensors
            ]
            return frame.with_tensors(corrupted).with_meta(
                chaos_corrupted=True
            )
        return frame
