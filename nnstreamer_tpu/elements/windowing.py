"""Temporal elements: tensor_aggregator (sliding-window batching) and
tensor_rate (framerate conversion + throttling).

Reference: gsttensor_aggregator.c (frames-in/out/flush over GstAdapter,
semantics gsttensor_aggregator.md) and gsttensor_rate.c (dup/drop rate
conversion + upstream QoS throttle :27-36). In this runtime backpressure
from bounded queues replaces upstream QoS events; `throttle=true` instead
rate-limits emission.

The aggregator is the micro-batching lever for TPU: place it before
tensor_filter to trade latency for MXU utilization (batch along frames-dim,
which for NHWC tensors is the leading axis).
"""

from __future__ import annotations

import time
from typing import List, Optional, Union

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import HostElement, NegotiationError, PropSpec, Spec
from nnstreamer_tpu.tensors.frame import Frame, SECOND
from nnstreamer_tpu.tensors.spec import TensorSpec, TensorsSpec
from fractions import Fraction


@registry.element("tensor_aggregator")
class TensorAggregator(HostElement):
    """Sliding-window frame aggregation.

    Props (reference parity): frames-in (frames per incoming buffer,
    default 1), frames-out (frames per outgoing buffer), frames-flush
    (window advance, default frames-out → tumbling; < frames-out →
    overlapping sliding window), frames-dim (reference innermost-first dim
    index to concat along), concat (reference gsttensor_aggregator.c:221-226;
    false → don't merge along frames-dim, stack the window on a new leading
    axis instead).
    """

    FACTORY_NAME = "tensor_aggregator"

    PROPERTIES = {
        "frames-in": PropSpec("int", 1),
        "frames-out": PropSpec("int", 1),
        "frames-flush": PropSpec("int", 0, desc="0 = frames-out (tumbling)"),
        "frames-dim": PropSpec(
            "int", None, desc="innermost-first dim index to concat along"
        ),
        "concat": PropSpec("bool", True),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.frames_in = int(self.get_property("frames-in", 1))
        self.frames_out = int(self.get_property("frames-out", 1))
        self.frames_flush = int(self.get_property("frames-flush", 0)) or self.frames_out
        self.ref_dim = self.get_property("frames-dim")
        self.concat = str(self.get_property("concat", "true")).lower() not in (
            "false", "0", "no",
        )
        if self.frames_in <= 0 or self.frames_out <= 0 or self.frames_flush <= 0:
            raise ValueError(f"{self.name}: frames-* must be positive")
        self._window: List[Frame] = []
        self._axis: int = 0

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        rank = spec[0].rank
        self._axis = (
            rank - 1 - int(self.ref_dim) if self.ref_dim is not None else 0
        )
        if not (0 <= self._axis < rank):
            raise NegotiationError(f"{self.name}: frames-dim out of range")
        if self.frames_out % self.frames_in != 0:
            raise NegotiationError(
                f"{self.name}: frames-out {self.frames_out} not a multiple of "
                f"frames-in {self.frames_in}"
            )
        if self.frames_flush % self.frames_in != 0:
            raise NegotiationError(
                f"{self.name}: frames-flush {self.frames_flush} not a multiple "
                f"of frames-in {self.frames_in}"
            )
        factor = self.frames_out // self.frames_in
        outs = []
        for t in spec:
            if t.rank != rank:
                raise NegotiationError(f"{self.name}: mixed ranks unsupported")
            if self.concat:
                shape = list(t.shape)
                shape[self._axis] = shape[self._axis] * factor
            else:
                shape = [factor] + list(t.shape)
            outs.append(TensorSpec(tuple(shape), t.dtype))
        rate = spec.rate * Fraction(self.frames_in, self.frames_flush) if spec.rate else None
        return [TensorsSpec(tuple(outs), spec.format, rate)]

    def process(self, frame: Frame) -> Optional[Frame]:
        import jax.numpy as jnp

        self._window.append(frame)
        need = self.frames_out // self.frames_in
        if len(self._window) < need:
            return None
        group = self._window[:need]
        tensors = []
        for ti in range(group[0].num_tensors):
            parts = [f.tensors[ti] for f in group]
            tensors.append(
                jnp.concatenate(parts, axis=self._axis)
                if self.concat
                else jnp.stack(parts, axis=0)
            )
        first = group[0]
        out = Frame(
            tuple(tensors),
            pts=first.pts,
            duration=(
                first.duration * need if first.duration is not None else None
            ),
            meta=dict(first.meta),
        )
        advance = max(1, self.frames_flush // self.frames_in)
        del self._window[:advance]
        return out

    def stop(self) -> None:
        self._window.clear()


class RateQoS:
    """Shared drop-ahead hint published by tensor_rate, consulted by
    upstream producers (the reference's upstream QoS event,
    gsttensor_rate.c:452, pulled instead of pushed).

    ``next_ts`` only ever increases, so a stale read is conservative: a
    frame judged droppable against an old (smaller) next_ts is also
    dropped by the current one — no lock needed."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self.next_ts: Optional[int] = None
        self.skipped_upstream = 0  # producers increment when they skip

    def would_drop(self, pts: Optional[int], duration: Optional[int]) -> bool:
        nt = self.next_ts
        if not self.enabled or nt is None or pts is None:
            return False
        if duration is None:
            return pts < nt
        return pts + duration <= nt


@registry.element("tensor_rate")
class TensorRate(HostElement):
    """Framerate conversion by PTS-based dup/drop, plus optional wall-clock
    throttling (the compute-saving use of reference tensor_rate).

    Props: framerate="15/1" (target), throttle=true|false (sleep to cap
    real-time emission rate), qos=true|false (default true: publish the
    next-needed timestamp upstream so producers skip frames this element
    would drop — the reference's upstream QoS events,
    gsttensor_rate.c:27-36,452).
    """

    FACTORY_NAME = "tensor_rate"

    PROPERTIES = {
        "framerate": PropSpec("fraction", None, desc="target rate"),
        "throttle": PropSpec("bool", False),
        "qos": PropSpec("bool", True),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        fr = self.get_property("framerate")
        self.target: Optional[Fraction] = Fraction(str(fr)) if fr else None
        self.throttle = str(self.get_property("throttle", "false")).lower() in (
            "1", "true", "yes",
        )
        self.qos = RateQoS(
            enabled=str(self.get_property("qos", "true")).lower()
            in ("1", "true", "yes")
        )
        self._next_ts: Optional[int] = None
        self._last_emit_wall = 0.0
        self.dup = 0
        self.drop = 0

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        if self.target is None:
            raise NegotiationError(f"{self.name}: tensor_rate needs framerate=")
        return [spec.with_rate(self.target)]

    def _throttle_wait(self) -> None:
        if not self.throttle or self.target is None:
            return
        min_gap = float(1 / self.target)
        now = time.monotonic()
        wait = self._last_emit_wall + min_gap - now
        if wait > 0:
            time.sleep(wait)
        self._last_emit_wall = time.monotonic()

    def process(self, frame: Frame) -> Union[Frame, List[Frame], None]:
        if frame.pts is None or self.target is None:
            self._throttle_wait()
            return frame
        out_dur = int(SECOND / self.target)
        if self._next_ts is None:
            self._next_ts = frame.pts
        out: List[Frame] = []
        in_end = frame.pts + (frame.duration or 0)
        # emit one output per target slot covered by this input frame
        while self._next_ts < in_end or (frame.duration is None and self._next_ts <= frame.pts):
            out.append(frame.with_pts(self._next_ts, out_dur))
            self._next_ts += out_dur
            if frame.duration is None:
                break
        self.qos.next_ts = self._next_ts  # publish drop-ahead hint upstream
        if not out:
            self.drop += 1
            return None
        if len(out) > 1:
            self.dup += len(out) - 1
        for _ in out:
            self._throttle_wait()
        return out

    def drop_stats(self) -> dict:
        """Frames removed from the stream, by reason (Executor.totals).
        Includes frames an UPSTREAM producer skipped on this element's
        QoS hint — they were produced (counted) but will never arrive
        here, so without this reason the pipeline balance would report
        a phantom leak."""
        return {
            "rate-drop": self.drop,
            "rate-qos-skip": self.qos.skipped_upstream,
        }

    def create_stats(self) -> dict:
        """Frames this element added to the stream (PTS dup)."""
        return {"rate-dup": self.dup}

    def stop(self) -> None:
        self._next_ts = None
