"""Element model: the composable stages of a pipeline.

TPU-native redesign of GStreamer elements (reference L3, SURVEY.md §1).
Where GStreamer elements negotiate caps pad-to-pad at PAUSED and then run
chain functions per buffer, here:

- ``negotiate(in_specs) -> out_specs`` runs once at pipeline build time over
  the whole graph (topological order), producing fully static specs;
- execution is classified so the pipeline compiler can FUSE maximal chains
  of pure-tensor elements into single jitted XLA programs:

  * :class:`TensorOp` — 1→1, pure tensor function; contributes a traceable
    jax fn (tensor_transform modes, jax-backed tensor_filter, tensor-math
    decoders). Fusable.
  * :class:`HostElement` — 1→1 but host-bound (stateful backends, python
    callbacks, network). Fusion barrier.
  * :class:`Source` / :class:`Sink` — stream endpoints.
  * :class:`Routing` — N→M elements with their own buffering/sync logic
    (mux, demux, tee, aggregator, rate, if, ...).

Media (non-tensor) links carry :class:`MediaSpec`; converters translate
between MediaSpec and TensorsSpec edges, mirroring the reference's
video/x-raw ↔ other/tensors boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

_log = get_logger("elements")


@dataclass(frozen=True)
class MediaSpec:
    """Spec of a raw-media link (reference caps video/x-raw, audio/x-raw,
    text/x-raw, application/octet-stream)."""

    media_type: str  # "video" | "audio" | "text" | "octet"
    # video
    width: Optional[int] = None
    height: Optional[int] = None
    format: str = "RGB"  # RGB | BGR | RGBA | BGRx | GRAY8
    # audio
    channels: Optional[int] = None
    sample_rate: Optional[int] = None
    sample_format: str = "S16LE"
    rate: Optional[Fraction] = None  # frames per second

    @property
    def channels_per_pixel(self) -> int:
        return {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRx": 4, "GRAY8": 1}[self.format]


Spec = Union[TensorsSpec, MediaSpec]


class NegotiationError(ValueError):
    """Spec mismatch at pipeline build (reference: caps negotiation failure)."""


class ElementError(RuntimeError):
    pass


@dataclass(frozen=True)
class PropSpec:
    """Declared schema of one element property — the GObject GParamSpec
    analogue (the reference installs param specs per element so
    gst-inspect and gst-validate can check properties before running;
    here nns-lint consumes the same table).

    type: "str" | "int" | "float" | "bool" | "fraction" | "enum".
    choices: allowed values when type == "enum" (case-insensitive).
    """

    type: str = "str"
    default: Any = None
    choices: Tuple[str, ...] = ()
    desc: str = ""


# Wildcard key: an element whose PROPERTIES contains PROPS_ANY accepts
# arbitrary extra properties (capsfilter carries raw caps fields).
PROPS_ANY = "*"


# -- fault-tolerance property surface (pipeline/faults.py) ------------------
# Declared here (not in pipeline.faults) so element modules can spread the
# schema without importing the pipeline package at class-definition time.

ON_ERROR_CHOICES = ("stop", "drop", "retry", "route")

#: PropSpec table spread into the PROPERTIES of every element that
#: supports per-frame error policies; pipeline/faults.py resolves the
#: values (element property over [executor] config default).
FAULT_PROPS: Dict[str, PropSpec] = {
    "on-error": PropSpec(
        "enum", None, ON_ERROR_CHOICES,
        desc="per-frame error policy (default stop; see "
        "docs/fault-tolerance.md)",
    ),
    "retry-max": PropSpec(
        "int", None, desc="retry attempts before degrading (default 3)"
    ),
    "retry-backoff-ms": PropSpec(
        "float", None,
        desc="base retry backoff, doubled per attempt, jittered "
        "(default 10.0)",
    ),
}

#: device-resilience property surface (pipeline/device_faults.py,
#: docs/resilience.md): spread into tensor_filter's PROPERTIES; the
#: resolver merges element values over the [executor] defaults.
DEVICE_PROPS: Dict[str, PropSpec] = {
    "oom-policy": PropSpec(
        "enum", None, ("degrade", "stop"),
        desc="on device OOM: degrade (shrink the batch bucket, remember "
        "the safe ceiling, re-probe after a cooldown) or stop "
        "(default degrade; docs/resilience.md)",
    ),
    "device-fallback": PropSpec(
        "bool", None,
        desc="serve from the host/eager path when the compiled device "
        "program fails (compile failure, repeated device faults); "
        "probes the device path for recovery (default true)",
    ),
}


#: resident-streaming property surface (pipeline/transfer.py,
#: docs/streaming.md): spread into tensor_filter's PROPERTIES; the
#: executor resolves element value over the [executor] ring_depth
#: config default.
STREAM_PROPS: Dict[str, PropSpec] = {
    "ring-depth": PropSpec(
        "int", None,
        desc="in-flight frames per device node: H2D of frame N+1 and "
        "D2H of frame N-1 overlap compute of frame N (default "
        "[executor] ring_depth = 2; 1 = synchronous dispatch-and-"
        "deliver; docs/streaming.md). On a plane= filter this is the "
        "stream's async in-flight WINDOW ring instead (default "
        "[plane] inflight = 1 — blocking submits; "
        "docs/serving-plane.md)",
    ),
    "chain-mode": PropSpec(
        "enum", None, ("auto", "off"),
        desc="whole-chain compilation for the chain this filter belongs "
        "to: auto compiles an eligible multi-segment chain into ONE "
        "resident program dispatched per unrolled window, off keeps "
        "the per-node parity path (default [executor] chain_mode = "
        "auto; docs/chain-analysis.md \"Compiled chains\")",
    ),
}


def install_error_pad(elem: "Element") -> None:
    """Expose the dead-letter error pad on ``elem`` when its ``on-error``
    property says ``route`` — or ``retry``, whose exhausted frames
    degrade to the error pad when one is linked (unlinked is fine for
    retry: exhaustion then drops; only ``route`` with an unlinked pad is
    a wiring mistake, nns-lint NNS-W107). Called from the __init__ of
    every element class that DECLARES the fault PropSpecs (after the
    base __init__ has consumed the property dict). The pad appears at
    index N_SRCS (src_1 for 1-src elements); negotiation appends a
    flexible spec for it (fix_negotiation) and the compiler keeps the
    element out of fused segments so per-frame routing is possible."""
    raw = elem.get_property("on-error")
    if raw is None:
        return
    mode = str(raw).strip().lower()
    if mode not in ON_ERROR_CHOICES:
        raise ValueError(
            f"{elem.name}: on-error={raw!r} not one of "
            f"{'/'.join(ON_ERROR_CHOICES)}"
        )
    if mode not in ("route", "retry"):
        return
    if type(elem).N_SRCS != 1:
        raise ValueError(
            f"{elem.name}: on-error={mode} needs exactly one src pad "
            f"(got N_SRCS={type(elem).N_SRCS})"
        )
    # instance attribute shadows the class attribute: only THIS element
    # grows the extra pad
    elem.N_SRCS = 2
    elem.error_pad = 1
    elem.error_pad_required = mode == "route"


class Element:
    """Base element. Subclasses set N_SINKS/N_SRCS (None = request pads,
    decided at link time) and implement negotiate()."""

    FACTORY_NAME = "element"
    N_SINKS: Optional[int] = 1
    N_SRCS: Optional[int] = 1

    # Set True on elements whose negotiate() allocates shared/global
    # state (e.g. the LLM continuous-batcher registers a server in a
    # module table): the static analyzer (nns-lint) must not dry-run
    # their negotiation on clones.
    LINT_SKIP_NEGOTIATE = False

    # Strict 1:1 cardinality declaration for the runtime sanitizer
    # (pipeline/sanitize.py): True means every offered frame is either
    # delivered, dropped (with a counted reason), or routed — never
    # absorbed, split, or merged — so the EOS frame-accounting invariant
    # offered == delivered + dropped + routed is enforceable per node.
    # Fused segments are implicitly strict (TensorOps are 1→1 by
    # contract); host-path elements opt in per class.
    SAN_ONE_TO_ONE: bool = False

    # Dead-letter error pad index (pipeline/faults.py): None = no error
    # pad; elements whose ``on-error=route|retry`` property exposed one
    # carry the extra src pad index here (install_error_pad sets it, the
    # executor routes error frames to it). error_pad_required is True
    # only for ``route``, where leaving the pad unlinked is a silent-drop
    # wiring mistake (nns-lint NNS-W107); a retry element's pad is an
    # optional overflow for exhausted frames.
    error_pad: Optional[int] = None
    error_pad_required: bool = False

    # Device-resident handoff capability (docs/streaming.md). The
    # executor negotiates per link from the consumer side: fused
    # segments (and anything not known to read tensor bytes on host)
    # receive device arrays untouched — adjacent segments chain in
    # device memory; host-path TensorOp nodes count as host readers
    # and get ONE coalesced async D2H per frame at delivery instead of
    # a synchronous per-tensor fetch. WANTS_HOST opts any other
    # element into that prefetched-host delivery.
    WANTS_HOST: bool = False
    # Pure plumbing (queue, capsfilter): host-path elements that never
    # read tensor bytes, so device arrays ride through untouched and a
    # device-resident handoff chains ACROSS them (the executor's
    # placement negotiation treats them as device-capable consumers).
    DEVICE_PASSTHROUGH: bool = False

    # Per-class property schema (merged over the MRO by property_schema()).
    # Subclasses add their own entries; nns-lint validates launch-string
    # properties against the merged table and the style gate's self-check
    # requires every constructor-read property to appear here.
    PROPERTIES: Dict[str, PropSpec] = {
        "name": PropSpec("str", None, desc="element instance name"),
        "queue-size": PropSpec(
            "int", 64, desc="input queue depth for this element's pads"
        ),
        "silent": PropSpec("bool", True, desc="suppress per-frame logging"),
    }

    _instance_counters: Dict[str, int] = {}

    @classmethod
    def property_schema(cls) -> Dict[str, "PropSpec"]:
        """Merged property schema over the class MRO (subclass wins)."""
        schema: Dict[str, PropSpec] = {}
        for klass in reversed(cls.__mro__):
            own = klass.__dict__.get("PROPERTIES")
            if own:
                schema.update(own)
        return schema

    @classmethod
    def accepts_any_property(cls) -> bool:
        return PROPS_ANY in cls.property_schema()

    def __init__(self, name: Optional[str] = None, **props: Any) -> None:
        if name is None:
            # deterministic per-factory numbering (gst element0, element1, ...)
            n = Element._instance_counters.get(self.FACTORY_NAME, 0)
            Element._instance_counters[self.FACTORY_NAME] = n + 1
            name = f"{self.FACTORY_NAME}{n}"
        self.name = name
        self.props: Dict[str, Any] = {}
        self.in_specs: List[Spec] = []
        self.out_specs: List[Spec] = []
        # queue size for this element's input pads (the reference's
        # queue-element analogue; see executor). 64 deep: a short queue
        # parks both neighbor threads at its edges every few frames, and
        # the context-switch ping-pong — not the per-frame work — then
        # dominates the host budget (GStreamer's queue defaults to 200
        # buffers for the same reason). Frames are array *handles*;
        # in-flight device work is exactly the dispatch-ahead pipelining
        # the executor exists for.
        self.queue_size = int(props.pop("queue-size", props.pop("queue_size", 64)))
        self.silent = _parse_bool(props.pop("silent", True))
        # downstream QoS publishers (tensor_rate upstream-throttle analogue,
        # gsttensor_rate.c:27-36,452): producers consult these and skip
        # frames the downstream limiter would drop anyway
        self.qos_sources: List[Any] = []
        for k, v in props.items():
            self.set_property(k, v)

    # -- properties (GObject property analogue) ---------------------------
    def set_property(self, key: str, value: Any) -> None:
        self.props[key.replace("_", "-")] = value

    def get_property(self, key: str, default: Any = None) -> Any:
        return self.props.get(key.replace("_", "-"), default)

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        """Given upstream specs (one per sink pad), return src-pad specs.
        Raise NegotiationError on mismatch. Called once at build."""
        raise NotImplementedError

    def fix_negotiation(self, in_specs: List[Spec]) -> List[Spec]:
        self.in_specs = list(in_specs)
        outs = list(self.negotiate(list(in_specs)))
        if self.error_pad is not None and len(outs) == self.error_pad:
            # the dead-letter pad (on-error=route): error frames carry the
            # element's ORIGINAL input tensors + error meta, so the pad's
            # spec is flexible — any sink accepts it
            outs.append(TensorsSpec(format=TensorFormat.FLEXIBLE))
        self.out_specs = outs
        return self.out_specs

    # -- QoS ----------------------------------------------------------------
    def add_qos_source(self, qos: Any) -> None:
        if qos not in self.qos_sources:
            self.qos_sources.append(qos)

    def qos_would_drop(self, frame: Any) -> bool:
        """True if a downstream rate limiter will certainly drop this frame
        — the producer can skip the work entirely (the reference's upstream
        QoS event path; here the hint is pulled, not pushed)."""
        if not self.qos_sources:
            return False
        pts = getattr(frame, "pts", None)
        dur = getattr(frame, "duration", None)
        return any(q.would_drop(pts, dur) for q in self.qos_sources)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Transition to streaming (open devices/models). Idempotent."""

    def stop(self) -> None:
        """Release streaming resources. Idempotent."""

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


def _parse_bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


class TensorOp(Element):
    """1→1 pure tensor element: contributes a traceable fn over the frame's
    tensor tuple. These fuse with neighbors into one XLA program."""

    N_SINKS = 1
    N_SRCS = 1

    # Micro-batching (pipeline/batching.py): stats are assigned at plan
    # time — for fused segments shared per segment — and read by the
    # filter's read-only avg-batch-size/pad-waste-pct/batch-wait-ms
    # props; batch_config is the plan-time resolved BatchConfig for
    # host-path (non-traceable) ops.
    batch_stats: Optional[Any] = None
    batch_config: Optional[Any] = None

    # Plan-time resolved FaultPolicy (pipeline/faults.py) for host-path
    # ops; fused segments carry their own on FusedSegment.
    fault_policy: Optional[Any] = None

    # Plan-time resolved device-resilience policy dict
    # (pipeline/device_faults.py resolve_device_policy); fused segments
    # carry their own on FusedSegment.
    device_policy: Optional[Any] = None

    # Plan-time resolved in-flight ring depth for host-path ops
    # (pipeline/transfer.py); fused segments carry their own on
    # FusedSegment. Host nodes stay synchronous (1) unless the element
    # sets ring-depth explicitly.
    ring_depth: int = 1

    # Bumped whenever the op's make_fn() result changes without a shape
    # change (model hot swap via reload_model): part of FusedSegment's
    # compiled-program cache key, so a same-shape reload cannot keep
    # serving the stale program.
    fn_version: int = 0

    def make_fn(self) -> Callable[[Tuple[Any, ...]], Tuple[Any, ...]]:
        """Return the pure fn (tensors) -> tensors for the negotiated specs.
        Called after negotiation; must be traceable by jax when
        is_traceable() is True."""
        raise NotImplementedError

    def is_traceable(self) -> bool:
        """False → run as a host node (fusion barrier) instead of fusing
        (e.g. tensor_filter with a host-library backend)."""
        return True

    def is_identity(self) -> bool:
        """True → this op's fn is the identity over its tensors (the
        passthrough backend): a segment of only-identity ops skips the
        jitted program entirely (FusedSegment short-circuit,
        docs/streaming.md)."""
        return False

    def is_batch_capable(self) -> bool:
        """True → the host path may collect a micro-batch and call
        host_process_batch (tensor_filter with a ``batchable`` backend).
        Traceable ops batch through the fused segment instead."""
        return False

    def host_process_batch(self, frames: List[Frame]) -> List[Frame]:
        """Host-path batched execution (only called when
        is_batch_capable()); default chains per-frame host_process."""
        out: List[Frame] = []
        for frame in frames:
            got = self.host_process(frame)
            if got is None:
                continue
            out.extend(got if isinstance(got, list) else [got])
        return out

    def host_process(self, frame: Frame) -> Union[Frame, List[Frame], None]:
        """Host-path execution for non-traceable TensorOps. May return
        None (frame absorbed, e.g. a batching element mid-window) or a
        list (fan-out), mirroring HostElement.process."""
        out = self.make_fn()(frame.tensors)
        return self.transform_meta(frame.with_tensors(out))

    def flush(self) -> List[Frame]:
        """Called at EOS on the host path; emit any buffered frames."""
        return []

    def transform_meta(self, frame: Frame) -> Frame:
        """Optional per-frame metadata/timestamp adjustment applied outside
        the fused program (default: passthrough)."""
        return frame


class HostElement(Element):
    """1→1 host-bound element (fusion barrier)."""

    N_SINKS = 1
    N_SRCS = 1

    def process(self, frame: Frame) -> Union[Frame, List[Frame], None]:
        """Process one frame; return 0..n output frames."""
        raise NotImplementedError

    def flush(self) -> List[Frame]:
        """Called at EOS; emit any buffered frames."""
        return []


class Source(Element):
    """Stream source: drives the pipeline from its own thread."""

    N_SINKS = 0
    N_SRCS = 1

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        return [self.output_spec()]

    def output_spec(self) -> Spec:
        raise NotImplementedError

    def generate(self):
        """Return the next Frame, EOS_FRAME when exhausted, or None for
        "no data yet" (the executor re-polls, checking its stop event, so a
        blocking source must use a bounded wait and return None)."""
        raise NotImplementedError


class Sink(Element):
    """Stream sink.

    ``sync-window`` (default 1): how many frames the sink may trail the
    device stream. 1 = render immediately (per-frame device sync, the
    reference's synchronous sink path). N>1 = the executor starts async
    device→host copies and renders each frame N frames later, so one sync
    round-trip is amortized over the window — the pattern bench.py
    measures. Ordering and EOS-flush semantics are unchanged.
    """

    N_SINKS = 1
    N_SRCS = 0

    PROPERTIES = {
        "sync-window": PropSpec(
            "int", 1, desc="frames the sink may trail the device stream"
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.sync_window = max(1, int(self.get_property("sync-window", 1)))

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        return []

    def render(self, frame: Frame) -> None:
        raise NotImplementedError

    def on_eos(self) -> None:
        """EOS notification (reference tensor_sink 'eos' signal)."""


class Routing(Element):
    """N→M element owning its buffering/sync semantics (mux, demux, tee,
    aggregator, if, rate, ...). The executor feeds it per-pad and collects
    (src_pad, frame) emissions."""

    N_SINKS: Optional[int] = None
    N_SRCS: Optional[int] = None

    def set_pad_counts(self, n_sinks: int, n_srcs: int) -> None:
        """Called at build time once actual link counts are known (request
        pads)."""
        self._n_sinks = n_sinks
        self._n_srcs = n_srcs

    def receive(self, pad: int, frame: Frame) -> List[Tuple[int, Frame]]:
        """Handle one input frame on `pad`; return list of (src_pad, frame)
        to emit now."""
        raise NotImplementedError

    def eos(self, pad: int) -> List[Tuple[int, Frame]]:
        """Handle EOS on `pad`; return final emissions. The executor
        forwards EOS downstream once all sink pads saw EOS."""
        return []
