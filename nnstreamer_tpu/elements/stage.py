"""tensor_stage: dedicated device-upload stage (double-buffered H2D).

The streaming-ingress cliff (r2 TPU capture: 89.7 fps H2D vs 2467 fps
device-resident) is per-transfer latency paid INLINE with compute
dispatch: when the filter node itself uploads, frame N+1's host→device
copy waits for frame N's dispatch turn. This element moves the upload
into its own executor node — its thread issues ``jax.device_put`` for
frame N+1 while the downstream filter node is still dispatching compute
on frame N, and the executor's SPSC channel between them is the double
(in general, ``queue-size``-deep) buffer. jax transfers are async, so
the stage thread never blocks on the wire either; the device orders the
copy before the dependent compute.

Role-match: the ingress half of gsttensor_converter.c:1046-1270 without
its per-frame memcpy — the reference stages into GstBuffer memory on
host; here frames stage straight into HBM.

Props: ``device`` (jax device index, default the backend default),
``stamp`` (bool: record ``meta["staged_at"]`` perf-counter timestamps —
the overlap unit test's evidence surface).
"""

from __future__ import annotations

import time
from typing import List

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import HostElement, PropSpec, Spec, _parse_bool
from nnstreamer_tpu.tensors.frame import Frame


@registry.element("tensor_stage")
class TensorStage(HostElement):
    """Uploads each frame's tensors to the device, spec-passthrough."""

    PROPERTIES = {
        "stamp": PropSpec("bool", False, desc="record staged_at meta"),
        "device": PropSpec("int", None, desc="jax.devices() index"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.stamp = _parse_bool(self.get_property("stamp", False))
        self._device = None

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        return list(in_specs)  # placement changes, the spec doesn't

    def start(self) -> None:
        import jax

        idx = self.get_property("device")
        if idx is not None:
            devs = jax.devices()
            i = int(idx)
            if not (0 <= i < len(devs)):
                raise ValueError(
                    f"{self.name}: device:{i} out of range ({len(devs)})"
                )
            self._device = devs[i]

    def process(self, frame: Frame) -> Frame:
        out = frame.to_device(self._device)
        if self.stamp:
            # perf stamp AFTER the puts are issued (they are async; the
            # stamp marks when this node handed the frame downstream,
            # which the overlap test compares against consumer times)
            out = out.with_meta(staged_at=time.perf_counter())
        return out
