"""Source elements: deterministic test sources, app push source, file source.

Reference parity: videotestsrc/audiotestsrc (GStreamer base elements the
reference's SSAT golden tests drive, SURVEY.md §4), appsrc, filesrc, and
tensor-native sources. Deterministic patterns make golden-file tests
reproducible, exactly like videotestsrc patterns do for the reference.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Iterable, Iterator, List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import MediaSpec, PropSpec, Source, Spec
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame, SECOND
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


def _frame_pts(index: int, rate: Optional[Fraction]):
    if not rate:
        return None, None
    dur = int(SECOND / rate)
    return index * dur, dur


@registry.element("videotestsrc")
@registry.element("testsrc")
class VideoTestSrc(Source):
    """Deterministic video source.

    Props: width, height, format (RGB/BGR/RGBA/GRAY8), num-frames (-1 =
    endless), framerate ("30/1"), pattern:
    - ``smpte``/``gradient``: per-frame shifted gradient (default)
    - ``solid``: constant fill (``foreground-color``)
    - ``random``: seeded rng (``seed``)
    - ``counter``: every pixel = frame index % 256 (golden-test friendly)

    ``is-live=true`` paces generation at ``framerate`` (a camera's
    clock discipline — GStreamer videotestsrc is-live); ``device=true``
    births frames device-resident; ``stamp-wall=true`` records
    generation wall-clock for sink-side e2e latency.
    """

    FACTORY_NAME = "videotestsrc"

    PROPERTIES = {
        "width": PropSpec("int", 320),
        "height": PropSpec("int", 240),
        "format": PropSpec(
            "enum", "RGB", ("RGB", "BGR", "RGBA", "BGRx", "GRAY8")
        ),
        "num-frames": PropSpec("int", 10, desc="-1 = endless"),
        "num-buffers": PropSpec("int", 10, desc="alias of num-frames"),
        "pattern": PropSpec(
            "enum", "gradient",
            ("smpte", "gradient", "solid", "random", "counter"),
        ),
        "framerate": PropSpec("fraction", "30/1"),
        "seed": PropSpec("int", 0, desc="rng seed for pattern=random"),
        "foreground-color": PropSpec("int", 128, desc="pattern=solid fill"),
        "device": PropSpec("bool", False, desc="frames born device-resident"),
        "stamp-wall": PropSpec("bool", False, desc="record generation wall-clock"),
        "is-live": PropSpec("bool", False, desc="pace generation at framerate"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.width = int(self.get_property("width", 320))
        self.height = int(self.get_property("height", 240))
        self.format = str(self.get_property("format", "RGB"))
        self.num_frames = int(
            self.get_property("num-frames", self.get_property("num-buffers", 10))
        )
        self.pattern = str(self.get_property("pattern", "gradient")).lower()
        self.rate = Fraction(str(self.get_property("framerate", "30/1")))
        self.seed = int(self.get_property("seed", 0))
        # device=true: frames are born device-resident (pattern math runs
        # as one tiny async device op per frame), so a fused downstream
        # segment never pays a host→device copy — the TPU-native answer
        # to "the test source must not be the bottleneck at 1000 fps".
        # `random` keeps host generation (+ upload) — rng streams are a
        # host concept here.
        from nnstreamer_tpu.elements.base import _parse_bool

        self.device = _parse_bool(self.get_property("device", False))
        # stamp-wall=true: record the generation wall-clock in frame meta
        # so sinks can report true end-to-end frame latency (BASELINE's
        # "p50 e2e frame latency tracked per config")
        self.stamp_wall = _parse_bool(self.get_property("stamp-wall", False))
        # is-live=true: PACE generation at `framerate` (a real camera's
        # behavior — GStreamer's videotestsrc is-live). Free-running
        # sources flood the queues, so a wall-stamped latency under
        # them measures BACKLOG, not service time; the honest p50-e2e
        # configuration is a paced source below the pipeline's
        # sustainable rate. Role-match: gstreamer's live-source clock
        # discipline (the reference inherits it from GStreamer).
        self.is_live = _parse_bool(self.get_property("is-live", False))
        self._t_live0 = None
        self._i = 0
        self._rng = np.random.default_rng(self.seed)
        self._base = None      # host pattern base (uint8, wraps mod 256)
        self._dev_base = None  # device-resident base / cached solid frame
        self._dev_fn = None

    def output_spec(self) -> Spec:
        return MediaSpec(
            "video",
            width=self.width,
            height=self.height,
            format=self.format,
            rate=self.rate,
        )

    def start(self) -> None:
        self._i = 0
        self._t_live0 = None
        self._rng = np.random.default_rng(self.seed)
        c = MediaSpec("video", format=self.format).channels_per_pixel
        h, w = self.height, self.width
        if self.pattern in ("smpte", "gradient"):
            # uint8 addition wraps mod 256, so (base + i) reproduces the
            # per-frame shifted gradient with ONE vectorized add instead
            # of a meshgrid rebuild per frame
            yy, xx = np.meshgrid(
                np.arange(h, dtype=np.uint16),
                np.arange(w, dtype=np.uint16),
                indexing="ij",
            )
            base = (xx + yy)[..., None] + np.arange(c, dtype=np.uint16) * 37
            self._base = (base % 256).astype(np.uint8)
        elif self.pattern == "solid":
            color = int(self.get_property("foreground-color", 128))
            self._base = np.full((h, w, c), color, np.uint8)
        elif self.pattern in ("counter", "random"):
            self._base = None
        else:
            raise ValueError(f"unknown pattern {self.pattern!r}")
        if self.device:
            import jax
            import jax.numpy as jnp

            if self._base is not None:
                self._dev_base = jnp.asarray(self._base)
            if self.pattern in ("smpte", "gradient"):
                self._dev_fn = jax.jit(lambda b, s: b + s)
            elif self.pattern == "counter":
                self._dev_fn = jax.jit(
                    lambda s: jnp.full((h, w, c), s, jnp.uint8)
                )

    def generate(self):
        if 0 <= self.num_frames <= self._i:
            return EOS_FRAME
        c = MediaSpec("video", format=self.format).channels_per_pixel
        h, w = self.height, self.width
        shift = np.uint8(self._i % 256)
        if self.pattern in ("smpte", "gradient"):
            img = (
                self._dev_fn(self._dev_base, shift)
                if self.device
                else self._base + shift
            )
        elif self.pattern == "solid":
            img = self._dev_base if self.device else self._base
        elif self.pattern == "random":
            img = self._rng.integers(0, 256, (h, w, c), dtype=np.uint8)
            if self.device:
                import jax.numpy as jnp

                img = jnp.asarray(img)
        elif self.pattern == "counter":
            img = (
                self._dev_fn(shift)
                if self.device
                else np.full((h, w, c), self._i % 256, np.uint8)
            )
        else:
            raise ValueError(f"unknown pattern {self.pattern!r}")
        pts, dur = _frame_pts(self._i, self.rate)
        if self.is_live and self.rate:
            # hold the configured cadence without drift: frame i is due
            # at t0 + i/rate on the monotonic clock
            import time

            if self._t_live0 is None:
                self._t_live0 = time.perf_counter()
            due = self._t_live0 + self._i / float(self.rate)
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        self._i += 1
        meta = {"media_type": "video"}
        if self.stamp_wall:
            import time

            meta["wall_t0"] = time.perf_counter()
        return Frame((img,), pts=pts, duration=dur, meta=meta)


@registry.element("audiotestsrc")
class AudioTestSrc(Source):
    """Deterministic audio source: sine wave chunks of `samples-per-buffer`
    S16LE samples, `channels` interleaved."""

    FACTORY_NAME = "audiotestsrc"

    PROPERTIES = {
        "rate": PropSpec("int", 16000, desc="sample rate (Hz)"),
        "channels": PropSpec("int", 1),
        "samples-per-buffer": PropSpec("int", 1024),
        "num-buffers": PropSpec("int", 10),
        "freq": PropSpec("float", 440.0, desc="sine frequency (Hz)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.sample_rate = int(self.get_property("rate", 16000))
        self.channels = int(self.get_property("channels", 1))
        self.spb = int(self.get_property("samples-per-buffer", 1024))
        self.num_buffers = int(self.get_property("num-buffers", 10))
        self.freq = float(self.get_property("freq", 440.0))
        self._i = 0

    def output_spec(self) -> Spec:
        return MediaSpec(
            "audio",
            channels=self.channels,
            sample_rate=self.sample_rate,
            sample_format="S16LE",
        )

    def start(self) -> None:
        self._i = 0

    def generate(self):
        if 0 <= self.num_buffers <= self._i:
            return EOS_FRAME
        t0 = self._i * self.spb
        t = (np.arange(self.spb) + t0) / self.sample_rate
        wave = np.sin(2 * np.pi * self.freq * t) * 0.5
        samples = (wave * 32767).astype(np.int16)
        chunk = np.repeat(samples[:, None], self.channels, axis=1)
        pts = int(t0 * SECOND / self.sample_rate)
        dur = int(self.spb * SECOND / self.sample_rate)
        self._i += 1
        return Frame((chunk,), pts=pts, duration=dur, meta={"media_type": "audio"})


@registry.element("appsrc")
class AppSrc(Source):
    """Push frames (or raw arrays) from application code.

    Use ``appsrc(iterable=...)`` for pull-from-iterator, or call
    ``push(frame)`` + ``end_of_stream()`` from any thread.
    """

    FACTORY_NAME = "appsrc"

    PROPERTIES = {
        "dimensions": PropSpec("str", None, desc="output spec dims"),
        "types": PropSpec("str", "float32"),
    }

    def __init__(self, name=None, iterable: Optional[Iterable] = None,
                 spec: Optional[Spec] = None, **props):
        super().__init__(name, **props)
        self._iter: Optional[Iterator] = iter(iterable) if iterable is not None else None
        self._spec = spec
        import queue as _q

        self._queue: "_q.Queue" = _q.Queue(maxsize=16)

    def output_spec(self) -> Spec:
        if self._spec is not None:
            return self._spec
        dims = self.get_property("dimensions")
        if dims:
            return TensorsSpec.from_strings(dims, self.get_property("types", "float32"))
        raise ValueError(f"{self.name}: appsrc needs spec= or dimensions= property")

    def push(self, frame, timeout: Optional[float] = None) -> None:
        if not isinstance(frame, Frame):
            frame = Frame(tuple(frame) if isinstance(frame, (tuple, list)) else (frame,))
        self._queue.put(frame, timeout=timeout)

    def end_of_stream(self) -> None:
        self._queue.put(EOS_FRAME)

    def generate(self):
        if self._iter is not None:
            try:
                item = next(self._iter)
            except StopIteration:
                return EOS_FRAME
            if not isinstance(item, Frame):
                item = Frame(tuple(item) if isinstance(item, (tuple, list)) else (item,))
            return item
        import queue as _q

        try:
            # bounded wait so the executor's stop event stays responsive
            return self._queue.get(timeout=0.1)
        except _q.Empty:
            return None


@registry.element("filesrc")
class FileSrc(Source):
    """Read a file as one octet buffer (or fixed ``blocksize`` chunks),
    feeding tensor_converter's application/octet-stream path."""

    FACTORY_NAME = "filesrc"

    PROPERTIES = {
        "location": PropSpec("str", "", desc="file path to read"),
        "blocksize": PropSpec("int", 0, desc="0 = whole file in one buffer"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.location = str(self.get_property("location", ""))
        self.blocksize = int(self.get_property("blocksize", 0))
        self._file = None
        self._done = False

    def output_spec(self) -> Spec:
        return MediaSpec("octet")

    def start(self) -> None:
        if not self.location:
            raise ValueError(f"{self.name}: filesrc needs location=")
        self._file = open(self.location, "rb")
        self._done = False

    def stop(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def generate(self):
        if self._done:
            return EOS_FRAME
        if self.blocksize > 0:
            data = self._file.read(self.blocksize)
            if not data:
                self._done = True
                return EOS_FRAME
        else:
            data = self._file.read()
            self._done = True
            if not data:
                return EOS_FRAME
        arr = np.frombuffer(data, dtype=np.uint8)
        return Frame((arr,), meta={"media_type": "octet"})


@registry.element("tensorsrc")
class TensorSrc(Source):
    """Pure tensor source: deterministic tensors straight in `other/tensors`
    (no converter needed). Props: dimensions, types, pattern
    (zeros/ones/counter/random), num-frames, framerate."""

    FACTORY_NAME = "tensorsrc"

    PROPERTIES = {
        "dimensions": PropSpec("str", "1"),
        "types": PropSpec("str", "float32"),
        "pattern": PropSpec(
            "enum", "counter", ("zeros", "ones", "counter", "random")
        ),
        "num-frames": PropSpec("int", 10),
        "framerate": PropSpec("fraction", None),
        "seed": PropSpec("int", 0),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.spec = TensorsSpec.from_strings(
            str(self.get_property("dimensions", "1")),
            str(self.get_property("types", "float32")),
            rate=self.get_property("framerate"),
        )
        self.num_frames = int(self.get_property("num-frames", 10))
        self.pattern = str(self.get_property("pattern", "counter")).lower()
        self.seed = int(self.get_property("seed", 0))
        self._i = 0
        self._rng = np.random.default_rng(self.seed)

    def output_spec(self) -> Spec:
        return self.spec

    def start(self) -> None:
        self._i = 0
        self._rng = np.random.default_rng(self.seed)

    def generate(self):
        if 0 <= self.num_frames <= self._i:
            return EOS_FRAME
        tensors = []
        for t in self.spec:
            if self.pattern == "zeros":
                a = np.zeros(t.shape, t.dtype.np_dtype)
            elif self.pattern == "ones":
                a = np.ones(t.shape, t.dtype.np_dtype)
            elif self.pattern == "counter":
                a = np.full(t.shape, self._i, dtype=np.float64).astype(t.dtype.np_dtype)
            elif self.pattern == "random":
                a = self._rng.random(t.shape).astype(t.dtype.np_dtype)
            else:
                raise ValueError(f"unknown pattern {self.pattern!r}")
            tensors.append(a)
        pts, dur = _frame_pts(self._i, self.spec.rate)
        self._i += 1
        return Frame(tuple(tensors), pts=pts, duration=dur)
