"""tensor_sparse_enc / tensor_sparse_dec: static ↔ sparse stream format.

Reference: gsttensor_sparseenc.c / gsttensor_sparsedec.c /
gsttensor_sparseutil.c — COO wire compression for sparse tensors (header +
nnz values + uint32 flat indices). Encode/decode run on host (it is a wire
format for files/network, not a compute format; dense static tensors feed
XLA), mirroring the reference.
"""

from __future__ import annotations

from typing import List

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import HostElement, NegotiationError, PropSpec, Spec
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.sparse import sparse_decode, sparse_encode
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec


@registry.element("tensor_sparse_enc")
class TensorSparseEnc(HostElement):
    FACTORY_NAME = "tensor_sparse_enc"

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec) or spec.format is not TensorFormat.STATIC:
            raise NegotiationError(f"{self.name}: needs static tensor input")
        return [TensorsSpec(format=TensorFormat.SPARSE, rate=spec.rate)]

    def process(self, frame: Frame) -> Frame:
        frame = frame.to_host()
        encoded = tuple(
            np.frombuffer(sparse_encode(np.asarray(t)), dtype=np.uint8)
            for t in frame.tensors
        )
        return frame.with_tensors(encoded)


@registry.element("tensor_sparse_dec")
class TensorSparseDec(HostElement):
    FACTORY_NAME = "tensor_sparse_dec"

    PROPERTIES = {
        "dimensions": PropSpec("str", None, desc="declared dense out dims"),
        "types": PropSpec("str", "float32"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.dims = self.get_property("dimensions")
        self.types = self.get_property("types", "float32")

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec) or spec.format is not TensorFormat.SPARSE:
            raise NegotiationError(f"{self.name}: needs sparse input")
        if self.dims:
            out = TensorsSpec.from_strings(str(self.dims), str(self.types))
            return [out.with_rate(spec.rate)]
        # sparse chunks are self-describing; without declared dims the
        # output is flexible (per-frame shapes)
        return [TensorsSpec(format=TensorFormat.FLEXIBLE, rate=spec.rate)]

    def process(self, frame: Frame) -> Frame:
        tensors = []
        for t in frame.tensors:
            dense, _ = sparse_decode(np.asarray(t, dtype=np.uint8).tobytes())
            tensors.append(dense)
        return frame.with_tensors(tensors)
