"""Real media ingress: encoded video files, still images, V4L2 cameras.

Reference: a gst-launch pipeline starts at ``v4l2src`` or ``filesrc !
decodebin`` and ``tensor_converter`` ingests decoded video/x-raw frames
with stride handling (gst/nnstreamer/elements/gsttensor_converter.c:
1046-1270). This framework's analogue decodes on host via OpenCV's
ffmpeg-backed VideoCapture (gated like the reference's meson options) and
emits tight RGB/BGR HWC uint8 frames into the normal video path — the
converter/filter chain downstream is identical to the synthetic-source
case, so a camera pipeline and a videotestsrc pipeline share every
compiled program.

Elements:

- ``videofilesrc location=clip.mp4``: decode a video file (any
  container/codec the image's OpenCV+ffmpeg build supports), or a still
  image (png/jpg/bmp — emitted once, or repeatedly with num-frames=N).
  Props: format=RGB|BGR|GRAY8 (default RGB), loop=true (rewind at EOF),
  framerate override, num-frames cap.
- ``v4l2src device=/dev/video0``: live camera capture through the same
  OpenCV backend. Props: device (path or index), width/height/framerate
  requests, format, num-frames.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from fractions import Fraction
from typing import Callable, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    ElementError,
    MediaSpec,
    PropSpec,
    Source,
    Spec,
    _parse_bool,
)
from nnstreamer_tpu.elements.sources import _frame_pts
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".webp", ".tif", ".tiff")


def _require_cv2():
    try:
        import cv2

        return cv2
    except ImportError as exc:
        raise ElementError(
            "opencv (cv2) unavailable; media file/camera sources are gated "
            "(like the reference's meson-gated decodebin path)"
        ) from exc


def _to_format(cv2, bgr: np.ndarray, fmt: str) -> np.ndarray:
    """BGR decode buffer → requested format, tight layout (the stride-
    handling contract: whatever the decoder's layout, the emitted tensor
    is contiguous — the converter never sees padded rows)."""
    if fmt == "BGR":
        out = bgr
    elif fmt == "RGB":
        out = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
    elif fmt == "GRAY8":
        out = cv2.cvtColor(bgr, cv2.COLOR_BGR2GRAY)[..., None]
    else:
        raise ElementError(f"unsupported format {fmt!r} (RGB/BGR/GRAY8)")
    return np.ascontiguousarray(out)


_EOF = object()


class _DecodeAhead:
    """Decode-ahead thread + bounded frame queue.

    Synchronous decode on the source thread serializes decode with the
    pipeline's per-frame host work — at target rates the decoder must
    run WHILE the previous frame uploads/infers, the role the kernel's
    buffer queue plays for the reference's v4l2src (its converter is
    handed already-queued buffers, gsttensor_converter.c:1046-1270).
    A single dedicated thread pulls frames from ``read_fn`` into a
    bounded FIFO; the source's generate() pops. Order and PTS are
    preserved by construction: one decoder thread + one FIFO means
    frames leave in decode order, and the consumer stamps PTS from its
    own monotone counter — overlap can neither reorder nor re-stamp.

    ``depth`` bounds decoded-but-unconsumed frames (memory AND, for a
    live camera, the staleness window)."""

    def __init__(self, read_fn: Callable[[], Optional[np.ndarray]],
                 depth: int = 8) -> None:
        self._read = read_fn  # returns a decoded frame or None at EOF
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=max(1, depth))
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="decode-ahead"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            img = self._read()
            item = _EOF if img is None else img
            while True:
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue_mod.Full:
                    if self._stop_evt.is_set():
                        return
            if item is _EOF:
                return

    def get(self, timeout: float = 0.1):
        """Next decoded frame; _EOF at end of stream; None when the
        decoder hasn't produced one yet (caller re-polls — the Source
        contract's no-data-yet value)."""
        try:
            return self._q.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def stop(self) -> bool:
        """Stop the decode thread. Returns True when it actually
        joined — False means it is still blocked inside the decoder
        (e.g. a wedged camera read), and the CALLER MUST NOT release
        the underlying capture handle (a native read racing release()
        is a use-after-free inside the decoder; leaking the handle to
        the daemon thread is the safe failure)."""
        self._stop_evt.set()
        if self._thread is None:
            return True
        # unblock a put() stuck on a full queue, then join
        try:
            while True:
                self._q.get_nowait()
        except queue_mod.Empty:
            pass
        self._thread.join(timeout=5.0)
        joined = not self._thread.is_alive()
        if joined:
            self._thread = None
        return joined


@registry.element("videofilesrc")
class VideoFileSrc(Source):
    """Decode an encoded video (or still image) file into video frames.

    Decoding runs on a decode-ahead thread (prop ``decode-ahead``, the
    queue depth, default 8; 0 = synchronous decode on the source
    thread), overlapping decode with downstream upload/inference."""

    FACTORY_NAME = "videofilesrc"

    PROPERTIES = {
        "location": PropSpec("str", "", desc="video/image file path"),
        "format": PropSpec("enum", "RGB", ("RGB", "BGR", "RGBA", "GRAY8")),
        "loop": PropSpec("bool", False),
        "num-frames": PropSpec("int", -1, desc="-1 = whole file"),
        "decode-ahead": PropSpec("int", 8, desc="0 = synchronous decode"),
        "framerate": PropSpec("fraction", None, desc="override file rate"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.location = str(self.get_property("location", ""))
        self.format = str(self.get_property("format", "RGB")).upper()
        self.loop = _parse_bool(self.get_property("loop", False))
        self.num_frames = int(self.get_property("num-frames", -1))
        self.decode_ahead = int(self.get_property("decode-ahead", 8))
        self._rate_override = self.get_property("framerate")
        if not self.location:
            raise ValueError(f"{self.name}: videofilesrc needs location=")
        self._cap = None
        self._ahead: Optional[_DecodeAhead] = None
        self._image: Optional[np.ndarray] = None
        self._i = 0
        # probe at build time so negotiation has real width/height/rate
        # (the reference's decodebin caps become known the same way)
        self._probe()

    def _is_image(self) -> bool:
        return self.location.lower().endswith(_IMAGE_EXTS)

    def _probe(self) -> None:
        cv2 = _require_cv2()
        if self._is_image():
            bgr = cv2.imread(self.location, cv2.IMREAD_COLOR)
            if bgr is None:
                raise ElementError(
                    f"{self.name}: cannot decode image {self.location!r}"
                )
            self._image = _to_format(cv2, bgr, self.format)
            h, w = self._image.shape[:2]
            self._size = (w, h)
            self._rate = (
                Fraction(str(self._rate_override))
                if self._rate_override
                else None
            )
            if self.num_frames < 0:
                self.num_frames = 1
            return
        cap = cv2.VideoCapture(self.location)
        if not cap.isOpened():
            raise ElementError(
                f"{self.name}: cannot open video {self.location!r}"
            )
        w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
        h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
        fps = cap.get(cv2.CAP_PROP_FPS) or 0.0
        cap.release()
        if w <= 0 or h <= 0:
            raise ElementError(
                f"{self.name}: {self.location!r} reports no frame size"
            )
        self._size = (w, h)
        if self._rate_override:
            self._rate = Fraction(str(self._rate_override))
        else:
            self._rate = (
                Fraction(fps).limit_denominator(1000) if fps > 0 else None
            )

    def output_spec(self) -> Spec:
        w, h = self._size
        return MediaSpec(
            "video", width=w, height=h, format=self.format, rate=self._rate
        )

    def start(self) -> None:
        self._i = 0
        if self._image is None:
            cv2 = _require_cv2()
            self._cap = cv2.VideoCapture(self.location)
            if not self._cap.isOpened():
                raise ElementError(
                    f"{self.name}: cannot open video {self.location!r}"
                )
            if self.decode_ahead > 0:
                cap = self._cap  # bind THIS handle into the thread: if a
                # wedged stop() later orphans it, the orphan keeps
                # reading its own capture and never touches a fresh one
                self._ahead = _DecodeAhead(
                    lambda: self._read_one(cap), depth=self.decode_ahead
                )
                self._ahead.start()

    def stop(self) -> None:
        joined = True
        if self._ahead is not None:
            joined = self._ahead.stop()
            self._ahead = None
        if self._cap is not None:
            if joined:
                self._cap.release()
            # else: the decode thread is still inside read() on its
            # bound handle — leave the native handle to the orphan
            # (release() racing a native read is a use-after-free).
            # Either way drop OUR reference so a later start() opens a
            # fresh capture instead of sharing the wedged one (two
            # native readers on one OpenCV handle is the same race
            # stop() just avoided).
            self._cap = None

    def _read_one(self, cap=None) -> Optional[np.ndarray]:
        """Decode the next frame (loop-rewinding at EOF); runs on the
        decode-ahead thread when enabled (with its bound handle), else
        the source thread (on self._cap)."""
        cv2 = _require_cv2()
        if cap is None:
            cap = self._cap
        ret, bgr = cap.read()
        if not ret:
            if self.loop:
                cap.set(cv2.CAP_PROP_POS_FRAMES, 0)
                ret, bgr = cap.read()
            if not ret:
                return None
        return _to_format(cv2, bgr, self.format)

    def generate(self):
        if 0 <= self.num_frames <= self._i:
            return EOS_FRAME
        if self._image is not None:
            img = self._image
        elif self._ahead is not None:
            img = self._ahead.get()
            if img is None:
                return None  # decoder busy: no data yet, re-poll
            if img is _EOF:
                return EOS_FRAME
        else:
            img = self._read_one()
            if img is None:
                return EOS_FRAME
        pts, dur = _frame_pts(self._i, self._rate)
        self._i += 1
        return Frame((img,), pts=pts, duration=dur, meta={"media_type": "video"})


@registry.element("v4l2src")
class V4l2Src(Source):
    """Live camera capture (V4L2 device or camera index) via OpenCV.

    Capture runs on a decode-ahead thread (prop ``decode-ahead``, queue
    depth, default 4 — small: for a LIVE source the queue depth is also
    the staleness window; 0 = synchronous capture)."""

    FACTORY_NAME = "v4l2src"

    PROPERTIES = {
        "device": PropSpec("str", 0, desc="V4L2 node or camera index"),
        "format": PropSpec("enum", "RGB", ("RGB", "BGR", "RGBA", "GRAY8")),
        "num-frames": PropSpec("int", -1),
        "width": PropSpec("int", 0, desc="0 = camera default"),
        "height": PropSpec("int", 0, desc="0 = camera default"),
        "decode-ahead": PropSpec("int", 4, desc="0 = synchronous capture"),
        "framerate": PropSpec("fraction", None),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        dev = self.get_property("device", 0)
        try:
            self.device = int(dev)
        except (TypeError, ValueError):
            self.device = str(dev)
        self.format = str(self.get_property("format", "RGB")).upper()
        self.num_frames = int(self.get_property("num-frames", -1))
        self.req_width = int(self.get_property("width", 0))
        self.req_height = int(self.get_property("height", 0))
        self.decode_ahead = int(self.get_property("decode-ahead", 4))
        self._rate_override = self.get_property("framerate")
        self._cap = None
        self._ahead: Optional[_DecodeAhead] = None
        self._i = 0
        self._probe()

    def _open_cap(self):
        """Open the device and (re)apply the requested capture geometry —
        a released camera reverts to driver defaults, so every reopen
        must re-set the props or frames stop matching the negotiated
        spec."""
        cv2 = _require_cv2()
        cap = cv2.VideoCapture(self.device)
        if not cap.isOpened():
            raise ElementError(
                f"{self.name}: cannot open camera {self.device!r}"
            )
        if self.req_width:
            cap.set(cv2.CAP_PROP_FRAME_WIDTH, self.req_width)
        if self.req_height:
            cap.set(cv2.CAP_PROP_FRAME_HEIGHT, self.req_height)
        return cap

    def _probe(self) -> None:
        cv2 = _require_cv2()
        cap = self._open_cap()
        w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
        h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
        fps = cap.get(cv2.CAP_PROP_FPS) or 0.0
        self._cap = cap  # keep the claim: cameras are exclusive devices
        if w <= 0 or h <= 0:
            cap.release()
            self._cap = None
            raise ElementError(
                f"{self.name}: camera {self.device!r} reports no frame size"
            )
        self._size = (w, h)
        if self._rate_override:
            self._rate = Fraction(str(self._rate_override))
        else:
            self._rate = (
                Fraction(fps).limit_denominator(1000) if fps > 0 else None
            )

    def output_spec(self) -> Spec:
        w, h = self._size
        return MediaSpec(
            "video", width=w, height=h, format=self.format, rate=self._rate
        )

    def start(self) -> None:
        self._i = 0
        if self._cap is None:
            self._cap = self._open_cap()
        if self.decode_ahead > 0 and self._ahead is None:
            cap = self._cap  # bound handle: an orphaned thread keeps it
            self._ahead = _DecodeAhead(
                lambda: self._read_one(cap), depth=self.decode_ahead
            )
            self._ahead.start()

    def stop(self) -> None:
        joined = True
        if self._ahead is not None:
            joined = self._ahead.stop()
            self._ahead = None
        if self._cap is not None:
            if joined:
                self._cap.release()
            # else: wedged camera read in flight — the orphan thread
            # keeps its bound handle (leak, don't race). Drop our
            # reference regardless so a restart opens a fresh capture
            # rather than sharing the wedged one.
            self._cap = None

    def _read_one(self, cap=None) -> Optional[np.ndarray]:
        cv2 = _require_cv2()
        if cap is None:
            cap = self._cap
        ret, bgr = cap.read()
        if not ret:
            return None
        return _to_format(cv2, bgr, self.format)

    def generate(self):
        if 0 <= self.num_frames <= self._i:
            return EOS_FRAME
        if self._ahead is not None:
            img = self._ahead.get()
            if img is None:
                return None  # capture in flight: no data yet, re-poll
            if img is _EOF:
                return EOS_FRAME
        else:
            img = self._read_one()
            if img is None:
                return EOS_FRAME
        pts, dur = _frame_pts(self._i, self._rate)
        self._i += 1
        return Frame((img,), pts=pts, duration=dur, meta={"media_type": "video"})
