"""tensor_llm_{serversink,serversrc}: continuous-batching LLM serving as
pipeline elements.

The reference serves one model to many clients at *frame* granularity:
tensor_query_serversrc emits client-tagged requests, the pipeline
processes them one at a time, serversink routes replies by client_id
(gst/nnstreamer/tensor_query/tensor_query_serversrc.c:379-427). An LLM
server multiplexes at *token* granularity instead — requests decode
concurrently in one slot batch (models/serving.ContinuousBatcher) and
finish out of order.

That asynchrony maps onto the same pairing pattern the reference uses for
repo and query elements: two elements share a server object through a
global ``id`` table —

    tensor_query_serversrc id=7 ! tensor_llm_serversink id=0 model=...
    tensor_llm_serversrc id=0 ! tensor_query_serversink id=7

- ``tensor_llm_serversink`` (a Sink) submits each incoming prompt frame
  (int32 token tensor; per-frame ``max_new_tokens`` meta overrides the
  element default). When the batch is full it pumps the batcher until a
  slot frees — admission backpressure.
- ``tensor_llm_serversrc`` (a Source, its own executor thread → decode
  makes progress even when no new prompts arrive) steps the batcher and
  emits one frame per *completed* request: tokens [1, n], with the
  request frame's meta (client_id!) preserved, so a downstream
  query-serversink routes each generation back to its requester.

EOS: the sink's flush marks end-of-submissions; the src drains every
pending request, then ends its stream.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    ElementError,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

_table: Dict[str, "_LlmServer"] = {}
_table_lock = threading.Lock()


def _get_server(srv_id: str, create_kw: Optional[dict] = None):
    with _table_lock:
        srv = _table.get(srv_id)
        if srv is not None and create_kw is not None and srv.eos:
            # stale server from a previous (stopped/drained) pipeline
            # run reusing this id: replace rather than resurrect — its
            # props may differ and its eos flag would end the new
            # stream. Its plane ref (if any) is NOT released here: the
            # stale server's own src may still be draining pending
            # generations through it — release rides that src's
            # _drop_server, which always releases the server it held.
            srv = None
        if srv is None:
            if create_kw is None:
                raise ElementError(
                    f"tensor_llm_server id={srv_id}: no serversink created "
                    "the server yet (the sink owns the model props)"
                )
            srv = _table[srv_id] = _LlmServer(**create_kw)
        return srv


def drain_server(srv_id: str, migrate_to: Optional[str] = None) -> Dict:
    """Operator surface (fleet tooling, tests): gracefully drain the
    id-keyed LLM server — new submits NACK ``draining``, chunked
    prefills settle, in-flight generations live-migrate to the peer (or
    resume locally when the peer refuses). Returns the drain summary
    (docs/llm-serving.md "Migration & recovery")."""
    with _table_lock:
        srv = _table.get(str(srv_id))
    if srv is None:
        raise ElementError(
            f"tensor_llm_server id={srv_id}: no server registered"
        )
    return srv.drain(migrate_to)


# meta keys that are meaningless outside the submitting process — the
# same hop-local set edge/serialize.py strips at the wire (client_id is
# the SOURCE server's transport pairing tag; the adopting server's own
# edge layer re-tags replies)
_SPAN_META_SKIP = frozenset({
    "client_id", "wall_t0", "admit_t", "_nns_srv", "_nns_budget_released",
})


def _span_meta(meta: dict) -> dict:
    """The JSON-scalar, cross-process-meaningful subset of a request's
    frame meta — what rides ``RequestSpan.meta`` (and the span
    checkpoint files) so the adopting or resuming server emits the
    finished generation with its identity (``frame_id``!) intact."""
    out = {}
    for k, v in meta.items():
        if k in _SPAN_META_SKIP:
            continue
        if v is None or isinstance(v, (str, int, float, bool)):
            out[k] = v
    return out


def _drop_server(srv_id: str, srv) -> None:
    """Remove the table entry — but only if it is still ``srv``: another
    pipeline may have reused the id with a fresh server, and a src that
    stopped before ever acquiring its server (srv None) must not evict a
    live entry another pipeline registered under the same id. A
    plane-attached server also drops its plane ref (last sharer out
    closes the shared batcher) — unconditionally on ``srv``, not just
    when the table entry still matched: a stale server replaced by a
    fresh one under the same id would otherwise leak its ref forever.
    release_plane is idempotent, so the drained-then-stopped src's two
    calls release once."""
    with _table_lock:
        if srv is not None and _table.get(srv_id) is srv:
            _table.pop(srv_id, None)
    if srv is not None:
        srv.release_plane()


def _build_batcher(model: str, options: Dict[str, str], n_slots: int,
                   max_len: int, prompt_len: int, speculate: int,
                   speculate_model: str, kv_layout: str, block_size: int,
                   kv_blocks: int, cache_dtype: str, prefill_chunks: int,
                   kv_attn: str, attn_impl: str = "xla"):
    """Open the zoo model (+ optional draft) and build the
    ContinuousBatcher — shared by the private-server path and the
    LlmPlane opener (serving_plane/llm.py), so through-plane serving
    runs the EXACT construction a solo serversink would."""
    from nnstreamer_tpu.models import zoo
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    if not model.startswith("zoo:"):
        raise ElementError(
            f"tensor_llm_serversink: model must be zoo:<name>, got "
            f"{model!r}"
        )
    m = zoo.get(model[len("zoo:"):], **options)
    n_heads = int(options.get("n_heads", 8))
    draft_kw = {}
    if speculate_model:
        # speculate-model=zoo:<name>: a draft model proposes the
        # speculate=k chunks instead of prompt-lookup. Its config
        # rides in the same custom dict under draft_-prefixed keys
        # (draft_d_model, draft_n_layers, draft_n_heads, ...); the
        # vocab must match the target's.
        if not speculate_model.startswith("zoo:"):
            raise ElementError(
                f"tensor_llm_serversink: speculate-model must be "
                f"zoo:<name>, got {speculate_model!r}"
            )
        d_opts = {
            k[len("draft_"):]: v for k, v in options.items()
            if k.startswith("draft_")
        }
        if "vocab" in options and "vocab" not in d_opts:
            d_opts["vocab"] = options["vocab"]
        dm = zoo.get(speculate_model[len("zoo:"):], **d_opts)
        draft_kw = dict(
            draft_params=dm.params,
            draft_n_heads=int(d_opts.get("n_heads", 8)),
        )
    kv_kw = {}
    if kv_layout != "slot":
        # paged KV (nnstreamer_tpu/kv/, docs/llm-serving.md):
        # block-table cache with prefix sharing, chunked prefill
        # and preemption-by-eviction; incompatible with a draft
        # model for now (ContinuousBatcher validates)
        kv_kw = dict(
            kv_layout=kv_layout, block_size=block_size,
            kv_blocks=kv_blocks or None,
            prefill_chunks=prefill_chunks,
            kv_attn=kv_attn or "auto",
        )
    return ContinuousBatcher(
        m.params, n_heads, n_slots=n_slots, max_len=max_len,
        prompt_len=prompt_len, cache_dtype=cache_dtype,
        attn_impl=attn_impl or "xla",
        **kv_kw, **draft_kw,
    )


class _LlmServer:
    """Shared state between the sink (submit) and src (pump/emit)."""

    def __init__(self, model: str, options: Dict[str, str], n_slots: int,
                 max_len: int, prompt_len: int, default_new: int,
                 stream: bool = False, speculate: int = 0,
                 speculate_model: str = "", pump_tokens: int = 1,
                 kv_layout: str = "slot", block_size: int = 16,
                 kv_blocks: int = 0, cache_dtype: str = "auto",
                 prefill_chunks: int = 1, kv_attn: str = "auto",
                 attn_impl: str = "xla",
                 plane: str = "", plane_weight: float = 1.0,
                 srv_id: str = "0", migrate_to: str = "",
                 checkpoint_every_tokens: int = 0,
                 checkpoint_dir: str = "",
                 role: str = "", decode_peers: str = ""):
        role = str(role or "")
        decode_peers = str(decode_peers or "")
        if role not in ("", "prefill", "decode"):
            raise ElementError(
                f"tensor_llm_serversink: role={role!r} must be "
                "prefill or decode"
            )
        if decode_peers and role != "prefill":
            raise ElementError(
                "tensor_llm_serversink: decode-peers needs role=prefill "
                "(only the prefill role ships spans to decode peers)"
            )
        if role:
            # disaggregated serving moves block-table KV spans between
            # roles (docs/llm-serving.md "Disaggregated serving") —
            # meaningless for the contiguous slot cache, refused on a
            # shared plane like migrate-to/checkpoint-*
            if kv_layout != "paged":
                raise ElementError(
                    "tensor_llm_serversink: role=prefill/decode needs "
                    "kv-layout=paged (handoffs are block-table spans)"
                )
            if plane:
                from nnstreamer_tpu.serving_plane.llm import LlmPlaneError

                raise LlmPlaneError(
                    f"llm plane {plane!r}: role= refused — plane-shared "
                    "batchers cannot extract or adopt request spans; "
                    "serve the role with a private kv-layout=paged "
                    "batcher instead"
                )
        if (migrate_to or checkpoint_dir or checkpoint_every_tokens):
            # migration + crash recovery (docs/llm-serving.md
            # "Migration & recovery") move block-table KV spans — they
            # have no meaning for the contiguous slot cache
            if kv_layout != "paged":
                raise ElementError(
                    "tensor_llm_serversink: migrate-to / "
                    "checkpoint-every-tokens / checkpoint-dir need "
                    "kv-layout=paged (spans are block-table slices)"
                )
            if plane:
                # typed plane refusal, raised BEFORE acquiring a plane
                # ref (nothing to release on this failure path)
                from nnstreamer_tpu.serving_plane.llm import LlmPlaneError

                raise LlmPlaneError(
                    f"llm plane {plane!r}: migrate-to/checkpoint-* "
                    "refused — plane-shared batchers cannot migrate "
                    "or checkpoint requests; serve with a private "
                    "kv-layout=paged batcher instead"
                )
        self.role = role
        self._disagg = None  # DisaggController (prefill role with peers)
        self._disagg_done: Dict[int, list] = {}  # decode role: rid→tokens
        if role == "prefill" and decode_peers:
            # built BEFORE the batcher so a malformed decode-peers spec
            # fails loudly without paying the model load
            from nnstreamer_tpu.serving_plane.disagg import DisaggController

            try:
                self._disagg = DisaggController(
                    decode_peers,
                    llm_id=int(srv_id) if str(srv_id).isdigit() else 0,
                )
            except ValueError as exc:
                raise ElementError(
                    f"tensor_llm_serversink: {exc}"
                ) from exc
        if speculate_model and speculate != -1 and speculate < 2:
            # a draft model exists ONLY to propose speculate=k chunks;
            # without this, every request would pay the draft prefill
            # for a proposer the plain-step pump never consults
            speculate = 4
        self.plane_name = plane
        self._plane = None   # LlmPlane once acquired
        self._stream = None  # this server's LlmStream
        if plane:
            # plane=<name> (docs/llm-serving.md): this serversink is one
            # client stream of a SHARED paged batcher — the tensor
            # plane's discipline at token granularity. The features that
            # assume a private batcher are rejected with the reason:
            if kv_layout != "paged":
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} needs "
                    "kv-layout=paged (the shared batcher is the paged "
                    "arena; slot caches are per-server by construction)"
                )
            if speculate or speculate_model:
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} cannot "
                    "combine with speculate/speculate-model (the "
                    "speculation controller state is per-server)"
                )
            if stream:
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} cannot "
                    "combine with stream=true (per-token routing "
                    "through a shared plane is not wired yet)"
                )
            from nnstreamer_tpu.serving_plane import llm as llm_plane

            sig = (
                model, tuple(sorted(options.items())), n_slots, max_len,
                prompt_len, kv_layout, block_size, kv_blocks,
                cache_dtype, prefill_chunks, kv_attn or "auto",
                attn_impl or "xla",
                max(1, int(pump_tokens)),
            )
            self._plane = llm_plane.acquire(
                plane, sig,
                opener=lambda: _build_batcher(
                    model, options, n_slots, max_len, prompt_len,
                    speculate, speculate_model, kv_layout, block_size,
                    kv_blocks, cache_dtype, prefill_chunks, kv_attn,
                    attn_impl,
                ),
                pump_tokens=pump_tokens,
            )
            try:
                self._stream = self._plane.attach(srv_id, plane_weight)
            except ValueError:
                # same id string attached elsewhere in this process:
                # disambiguate rather than refuse (ids are only unique
                # per pairing)
                self._stream = self._plane.attach(
                    f"{srv_id}@{id(self) & 0xffff:04x}", plane_weight
                )
            self.cb = self._plane.cb
        else:
            self.cb = _build_batcher(
                model, options, n_slots, max_len, prompt_len, speculate,
                speculate_model, kv_layout, block_size, kv_blocks,
                cache_dtype, prefill_chunks, kv_attn, attn_impl,
            )
        self.default_new = default_new
        self._lock = threading.Lock()
        self._pending: Dict[int, dict] = {}  # rid -> request meta
        self._out: deque = deque()
        self.eos = False
        self.stopped = False
        # token streaming: emit one frame per NEW token as it decodes,
        # then a final done frame — the SSE-style serving surface in the
        # pipeline idiom. Authoritative when set at creation (the sink's
        # stream prop); the serversrc's stream=true also flips it at
        # acquisition, which is race-free only in the single-pipeline
        # layout (all elements start before any frame flows) — paired
        # ACROSS pipelines, set it on the sink.
        self.stream = stream
        # speculate=k: pump via spec_step(k) — prompt-lookup speculation
        # batched over slots (greedy slots emit several tokens per
        # program launch when the guesses land; exact equivalence).
        # speculate=auto (-1): k adapts to the measured acceptance rate
        # (EMA) between 2 and 8 — long chunks when guesses land, minimal
        # verify width when they don't.
        self.speculate = speculate
        # pump=N: target tokens per program launch — step_pump(N) /
        # spec_pump(rounds=⌈N/k⌉). N=1 keeps the per-token step path
        # (minimum admission latency); larger N amortizes the
        # host↔device round trip N ways (ONE readback per pump), the
        # knob that matters on a tunnel-attached chip. Admissions join
        # at the next pump, so latency-sensitive servers keep N small.
        self.pump_tokens = max(1, int(pump_tokens))
        self._spec_k = 4
        self._acc_ema = 0.5
        self._spec_seen = (0, 0)  # (columns, accepted) at last adapt
        self._sent: Dict[int, int] = {}  # rid -> tokens already streamed
        # -- live migration + crash recovery (docs/llm-serving.md
        # "Migration & recovery") --------------------------------------
        self.srv_id = str(srv_id)
        self._paged = kv_layout == "paged" or plane != ""
        self.migrate_to = str(migrate_to or "")
        self.draining = False
        self._edge_srv = None  # paired serversrc id, learned at submit
        self._ckpt_every = max(0, int(checkpoint_every_tokens))
        self._ckpt_dir = str(checkpoint_dir or "")
        self._ckpt_seen: Dict[int, int] = {}  # rid -> tokens at last ckpt
        from nnstreamer_tpu.obs import metrics as _obs_metrics

        self._obs_reg = _obs_metrics.get()
        # the llm_id the migration handshake routes by: the serversink
        # id when numeric (the usual "id=0"), else 0 — the receiving
        # process falls back to its only handler anyway when exactly
        # one LLM server runs there
        self._mig_id = int(self.srv_id) if self.srv_id.isdigit() else 0
        self._mig_registered = False
        if self._plane is None and self._paged:
            # every private paged server is adoptable: being a
            # migration DESTINATION needs no props — migrate-to only
            # configures where THIS server ships its spans at drain
            from nnstreamer_tpu.edge import query as _equery

            _equery.register_migration_handler(self._mig_id, self)
            self._mig_registered = True
            if self._ckpt_dir:
                self._restore_checkpoints()

    def submit(self, frame: Frame) -> None:
        import time as _time

        if frame.meta.get("_nns_srv") is not None:
            # remember which edge serversrc feeds this server, so
            # drain() can flip its readiness flag and NACK at admission
            self._edge_srv = frame.meta.get("_nns_srv")
        if self.draining:
            self._nack_draining(frame)
            return
        prompt = np.asarray(frame.tensors[0]).reshape(-1).astype(np.int32)
        budget = int(frame.meta.get("max_new_tokens", self.default_new))
        # per-request sampling params ride in frame meta (greedy default)
        kw = dict(
            temperature=float(frame.meta.get("temperature", 0.0)),
            top_k=int(frame.meta.get("top_k", 0)),
            top_p=float(frame.meta.get("top_p", 1.0)),
        )
        if "seed" in frame.meta:
            kw["seed"] = int(frame.meta["seed"])
        if "deadline_ms" in frame.meta:
            # SLO accounting (nns-top --requests); the edge layer's
            # deadline shedding is upstream of this element
            kw["deadline_s"] = float(frame.meta["deadline_ms"]) / 1000.0
        if self._plane is not None:
            # through-plane serving: the prompt queues for weighted-fair
            # admission into the SHARED batcher (serving_plane/llm.py);
            # backpressure past the fair backlog pumps inside submit
            if self.stopped:
                raise ElementError("tensor_llm_serversink: stopped")
            self._plane.submit(
                self._stream, prompt, budget, kw, dict(frame.meta)
            )
            return
        while True:
            if self.stopped:
                raise ElementError("tensor_llm_serversink: stopped")
            rid = self.cb.submit(prompt, budget, **kw)
            if rid is not None:
                break
            # batch full: pumping here IS the backpressure — admission
            # waits until decoding frees a slot. A no-progress pump is
            # NOT an error: the src thread may have just stepped/ drained
            # concurrently (freeing slots), so loop and retry submit.
            if not self.pump():
                _time.sleep(0.005)
        with self._lock:
            self._pending[rid] = dict(frame.meta)

    def pump(self) -> bool:
        """One decode step; harvest finished requests (and, in streaming
        mode, every new token). True if anything advanced."""
        if self._plane is not None:
            # the SHARED batcher advances every stream's requests; this
            # server's finished generations land on its own plane
            # stream deque (pop reads them there)
            return self._plane.pump()
        N = self.pump_tokens
        if self.speculate == -1:
            if N > 1:
                emitted = self.cb.spec_pump(
                    rounds=max(1, -(-N // self._spec_k)), k=self._spec_k
                )
            else:
                emitted = self.cb.spec_step(k=self._spec_k)
            st = self.cb.stats()
            # normalize by proposal COLUMNS, not rounds: a round offers
            # active_slots×(k-1) proposals, so a rounds-based rate would
            # saturate on multi-slot servers and pin k at max exactly
            # when acceptance is poor
            cols, acc = st["spec_columns"], st["spec_accepted_tokens"]
            dc = cols - self._spec_seen[0]
            if dc > 0:
                rate = (acc - self._spec_seen[1]) / dc
                self._acc_ema = 0.7 * self._acc_ema + 0.3 * rate
                self._spec_k = min(
                    8, max(2, 2 + int(round(self._acc_ema * 6)))
                )
                self._spec_seen = (cols, acc)
        elif self.speculate > 1:
            if N > 1:
                emitted = self.cb.spec_pump(
                    rounds=max(1, -(-N // self.speculate)),
                    k=self.speculate,
                )
            else:
                emitted = self.cb.spec_step(k=self.speculate)
        elif N > 1:
            emitted = self.cb.step_pump(N)
        else:
            emitted = self.cb.step()
        harvested = False
        finished: List[int] = []
        with self._lock:
            if self.stream:
                # count-based catch-up off cb.partials() (one batcher
                # lock pass for all pending rids): robust to tokens
                # emitted by ANY thread's step between two pumps
                parts = self.cb.partials(list(self._pending))
                for rid, meta in self._pending.items():
                    toks = parts.get(rid)
                    if toks is None:
                        continue
                    if self.role == "decode" and meta.get("_nns_disagg"):
                        continue  # fetched whole by the prefill side
                    harvested |= self._stream_new_locked(rid, meta, toks)
            for rid in list(self._pending):
                toks = self.cb.result(rid)
                if toks is not None:
                    meta = self._pending.pop(rid)
                    park = (
                        self.role == "decode"
                        and bool(meta.get("_nns_disagg"))
                    )
                    if self.stream and not park:
                        # a concurrent pump's step may have finished the
                        # request AFTER our catch-up pass above — emit the
                        # tail tokens per-frame before the done frame so
                        # the one-frame-per-token contract holds
                        self._stream_new_locked(rid, meta, toks)
                        meta = {**meta, "stream": True, "done": True}
                    self._sent.pop(rid, None)
                    if park:
                        # a handed-off generation finished HERE, but the
                        # prefill side owns DELIVER (at-most-once rides
                        # its unchanged frame_id): park the tokens for
                        # its disagg_fetch instead of emitting
                        self._disagg_done[rid] = list(toks)
                    else:
                        self._out.append((toks, meta))
                    finished.append(rid)
                    harvested = True
        if self._ckpt_dir:
            for rid in finished:
                self._ckpt_drop(rid)
            if self._ckpt_every:
                self._checkpoint_tick()
        if self._disagg is not None and not self.stopped:
            # prefill role: offload freshly-extractable requests to the
            # decode peers and relay finished handoffs into _out
            harvested |= self._disagg.tick(self)
        return bool(emitted) or harvested

    def _stream_new_locked(self, rid: int, meta: dict, toks) -> bool:
        """Emit per-token frames for tokens not yet streamed (_lock held)."""
        n0 = self._sent.get(rid, 0)
        for i in range(n0, len(toks)):
            self._out.append((
                [toks[i]],
                {**meta, "stream": True, "done": False, "token_index": i},
            ))
        self._sent[rid] = len(toks)
        return len(toks) > n0

    # -- live migration + crash recovery (docs/llm-serving.md
    # "Migration & recovery") ------------------------------------------

    def _nack_draining(self, frame: Frame) -> None:
        """A submit reaching a draining LLM server is NACKed
        ``draining`` with the retry-after hint (the PR-15 edge-drain
        contract, now honoured when the DOWNSTREAM consumer drains
        behind a still-ready serversrc) — the fleet client re-routes
        instead of timing out behind a server that will never finish
        the request."""
        srv = frame.meta.get("_nns_srv")
        cid = frame.meta.get("client_id")
        if srv is not None and cid is not None:
            from nnstreamer_tpu.edge.query import discard_admitted

            discard_admitted(
                srv, cid, "nack", frame_id=frame.meta.get("frame_id"),
                draining=True,
            )
            return
        # no edge hop to answer through (direct pipeline submit): the
        # typed refusal is the only channel left
        raise ElementError(
            "tensor_llm_serversink: draining — not accepting new "
            "requests (resubmit to another endpoint)"
        )

    def migration_probe(self, tokens) -> int:
        """How many leading ``tokens`` this server's prefix index
        already covers (full blocks only) — the sender strips those
        blocks' payloads and ships only the unshared suffix. Answers
        ``migrate_probe`` CTRLs through the edge/query.py handler
        registry."""
        from nnstreamer_tpu.kv.migrate import SpanStateError

        if self._plane is not None:
            self._plane.refuse_migration("migrate_probe")
        if self.draining or self.stopped:
            raise SpanStateError(
                f"tensor_llm_server id={self.srv_id}: draining"
            )
        return int(self.cb.probe_prefix([int(t) for t in tokens]))

    def migration_adopt(self, span_bytes: bytes) -> int:
        """Decode + adopt an incoming KV span: the generation continues
        HERE under the returned rid — bitwise-identically for greedy
        requests — and this server's serversrc emits it with the span's
        surviving frame meta (``frame_id`` intact for reply dedup)."""
        from nnstreamer_tpu.kv import migrate as _migrate

        if self._plane is not None:
            self._plane.refuse_migration("migrate_span")
        if self.draining or self.stopped:
            raise _migrate.SpanStateError(
                f"tensor_llm_server id={self.srv_id}: draining"
            )
        span = _migrate.decode_span(span_bytes)
        rid = self.cb.adopt_request(span)
        with self._lock:
            self._pending[rid] = dict(span.meta)
        return rid

    # the disagg controller stamps surviving frame meta onto spans it
    # extracts — the same propagation filter drain()/checkpointing use
    span_meta = staticmethod(_span_meta)

    def migration_advert(self) -> Dict:
        """Piggybacked on every ``migrate_probe_ack`` (docs/
        llm-serving.md "Disaggregated serving"): one probe roundtrip
        tells the prefill side how WARM this server is (shared_tokens,
        from the probe itself) and how FULL (pool headroom, from this
        advert) — enough to pick the best decode peer without a second
        exchange."""
        out: Dict = {"role": self.role or ""}
        if self.role != "decode":
            return out
        st = self.cb.stats()
        out["free_slots"] = int(st.get("slots_free", 0) or 0)
        # cached blocks are evictable on demand, so they count as
        # headroom for an incoming span's unshared suffix
        out["free_blocks"] = (
            int(st.get("kv_blocks_free", 0) or 0)
            + int(st.get("kv_blocks_cached", 0) or 0)
        )
        return out

    def disagg_fetch(self, rid: int):
        """Answer a ``disagg_fetch`` CTRL from the prefill peer that
        handed rid off here: finished tokens (popped — exactly-once,
        the prefill side owns DELIVER), ``None`` while still decoding,
        or SpanStateError for an rid this server has never seen (the
        peer stops polling and resubmits the prompt)."""
        from nnstreamer_tpu.kv.migrate import SpanStateError

        rid = int(rid)
        with self._lock:
            toks = self._disagg_done.pop(rid, None)
            if toks is not None:
                return toks
            if rid in self._pending:
                return None
        raise SpanStateError(
            f"tensor_llm_server id={self.srv_id}: rid {rid} unknown"
        )

    def drain(self, migrate_to: Optional[str] = None) -> Dict[str, int]:
        """Graceful drain with live migration: stop admitting (new
        submits NACK ``draining``, the paired edge serversrc flips to
        SRV_DRAINING), settle every chunked prefill mid-flight (a span
        is only extractable once its request is decoding — no job left
        half-staged), then per in-flight request: extract the KV span,
        probe the peer's prefix coverage, ship the slimmed span. A
        refusing or unreachable peer falls back to local re-prefill
        resume; with no peer configured the requests simply finish in
        place. Returns ``{"migrated", "resumed", "completed", "kept"}``
        counts."""
        import time as _time

        if self._plane is not None:
            self._plane.refuse_migration("drain(migrate_to=...)")
        self.draining = True
        if self._edge_srv is not None:
            from nnstreamer_tpu.edge import query as _equery

            _equery._set_server_state(
                self._edge_srv, _equery.SRV_DRAINING
            )
        summary = {"migrated": 0, "resumed": 0, "completed": 0, "kept": 0}
        if self._paged:
            # settle chunked prefills: every queued/half-staged prefill
            # lands (its request becomes decoding — and extractable)
            # before any span leaves; completed chunks are never re-run
            while (self.cb.stats().get("kv_prefill_queue") or 0) > 0:
                if self.stopped:
                    break
                if not self.pump():
                    _time.sleep(0.002)
        target = self.migrate_to if migrate_to is None else str(migrate_to)
        with self._lock:
            rids = list(self._pending)
        if not target or not rids:
            summary["kept"] = len(rids)
            return summary
        if not self._paged:
            raise ElementError(
                "tensor_llm_serversink: drain(migrate_to=...) needs "
                "kv-layout=paged (spans are block-table slices)"
            )
        # host:port[/llm-id] — the peer's serversink id defaults to this
        # server's own (symmetric fleet configs), and a peer hosting a
        # single LLM server answers regardless (handler fallback)
        peer_id = self._mig_id
        base, sep, suffix = target.partition("/")
        if sep:
            target = base
            peer_id = int(suffix) if suffix.isdigit() else 0
        host, _, port_s = target.rpartition(":")
        if not host or not port_s.isdigit():
            raise ElementError(
                f"tensor_llm_serversink: migrate-to={target!r} must be "
                "host:port[/llm-id]"
            )
        port = int(port_s)
        from nnstreamer_tpu.edge import query as _equery
        from nnstreamer_tpu.edge.transport import TransportError
        from nnstreamer_tpu.kv import migrate as _migrate

        for rid in rids:
            try:
                span = self.cb.extract_request(rid)
            except _migrate.SpanError:
                # finished between the settle loop and now — pump's
                # harvest owns it (still a terminal outcome)
                summary["completed"] += 1
                continue
            with self._lock:
                meta = dict(self._pending.get(rid) or {})
            span.meta.update(_span_meta(meta))
            try:
                shared = _equery.probe_migration(
                    host, port, span.kv_tokens, llm_id=peer_id
                )
                wire = _migrate.encode_span(span.strip_shared(shared))
                _equery.send_migration(
                    host, port, wire, llm_id=peer_id
                )
            except (_equery.MigrationRefused, TransportError, OSError,
                    ValueError, _migrate.SpanError):
                # the request is still whole on this side — resume it
                # locally via re-prefill of the surviving context (the
                # cold fallback; generated tokens are NOT lost)
                new_rid = self.cb.resume_from_span(span)
                with self._lock:
                    self._pending[new_rid] = self._pending.pop(rid, meta)
                    n_sent = self._sent.pop(rid, None)
                    if n_sent is not None:
                        self._sent[new_rid] = n_sent
                if self._ckpt_dir:
                    self._ckpt_rename(rid, new_rid)
                summary["resumed"] += 1
            else:
                with self._lock:
                    self._pending.pop(rid, None)
                    self._sent.pop(rid, None)
                self._ckpt_drop(rid)
                summary["migrated"] += 1
        return summary

    def _checkpoint_tick(self) -> None:
        """Every checkpoint-every-tokens NEW tokens per request, write
        an atomic span checkpoint — a hard-killed server process
        resumes its in-flight generations from these files at next
        construction, without re-running completed prefill chunks."""
        with self._lock:
            rids = list(self._pending)
        if not rids or not self._paged:
            return
        parts = self.cb.partials(rids)
        for rid in rids:
            n = len(parts.get(rid) or ())
            if n - self._ckpt_seen.get(rid, 0) < self._ckpt_every:
                continue
            if self._write_checkpoint(rid):
                self._ckpt_seen[rid] = n

    def _write_checkpoint(self, rid: int) -> bool:
        import os

        from nnstreamer_tpu.kv import migrate as _migrate

        with self._lock:
            meta = dict(self._pending.get(rid) or {})
        try:
            span = self.cb.extract_request(rid, remove=False)
        except _migrate.SpanError:
            return False  # finished or mid-prefill this instant — skip
        span.meta.update(_span_meta(meta))
        path = os.path.join(self._ckpt_dir, f"req-{rid}.span")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self._ckpt_dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(_migrate.encode_span(span))
            # atomic replace: a reader (or the restore scan after a
            # crash) sees the old complete checkpoint or the new one,
            # never a torn file
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        return True

    def _ckpt_drop(self, rid: int) -> None:
        import os

        self._ckpt_seen.pop(rid, None)
        if not self._ckpt_dir:
            return
        try:
            os.remove(os.path.join(self._ckpt_dir, f"req-{rid}.span"))
        except OSError:
            pass

    def _ckpt_rename(self, old: int, new: int) -> None:
        """A request changed rid (resume fallback, restore adoption):
        move its checkpoint file along — the stale name would be
        re-adopted as a GHOST duplicate at the next restart."""
        import os

        self._ckpt_seen[new] = self._ckpt_seen.pop(old, 0)
        try:
            os.replace(
                os.path.join(self._ckpt_dir, f"req-{old}.span"),
                os.path.join(self._ckpt_dir, f"req-{new}.span"),
            )
        except OSError:
            pass

    def _restore_checkpoints(self) -> None:
        """Crash recovery: adopt every span checkpoint a previous
        (hard-killed) server process left in checkpoint-dir — the
        landed KV re-enters the arena directly, so completed prefill
        chunks are NOT re-run. Corrupt or unadoptable files are set
        aside (``.bad``) rather than retried forever."""
        import os

        from nnstreamer_tpu.kv import migrate as _migrate

        try:
            names = sorted(os.listdir(self._ckpt_dir))
        except OSError:
            return  # fresh dir: created lazily at the first checkpoint
        for name in names:
            if not name.endswith(".span"):
                continue
            path = os.path.join(self._ckpt_dir, name)
            try:
                with open(path, "rb") as f:
                    span = _migrate.decode_span(f.read())
                rid = self.cb.adopt_request(span)
            except (OSError, _migrate.SpanError):
                try:
                    os.replace(path, path + ".bad")
                except OSError:
                    pass
                continue
            with self._lock:
                self._pending[rid] = dict(span.meta)
            # keep the file (under the adopted rid's name) until the
            # request finishes or re-checkpoints: a crash right after
            # restore must not lose the generation a second time
            dest = os.path.join(self._ckpt_dir, f"req-{rid}.span")
            if dest != path:
                try:
                    os.replace(path, dest)
                except OSError:
                    pass
            self._ckpt_seen[rid] = len(span.tokens)
            if self._obs_reg is not None:
                self._obs_reg.counter(
                    "nns_request_resumes_total", kind="checkpoint"
                ).inc()

    def stats(self) -> Dict:
        """Batcher counters + the adaptive-speculation control state
        (VERDICT r4 #5: a silent proposer regression shows up here as a
        sagging acceptance rate / k pinned at 2 — visible in --stats,
        not only in wall time)."""
        if self._plane is not None:
            # shared-batcher counters + ONLY this stream's request rows
            # (per-stream SLO ledgers: sharers never report each
            # other's — serving_plane/llm.py)
            return self._plane.stats_for(self._stream)
        st = self.cb.stats()
        # per-request SLO rows for nns-top --requests (serving_requests
        # once the executor prefixes the row)
        st["requests"] = {
            str(rid): row for rid, row in self.cb.requests().items()
        }
        if self.role:
            st["disagg_role"] = self.role
        if self._disagg is not None:
            st["disagg"] = self._disagg.stats()
        if self.role == "decode":
            with self._lock:
                st["disagg_done_waiting"] = len(self._disagg_done)
        if self.speculate == -1:
            st["spec_k"] = self._spec_k
            # the EMA is the auto controller's state — in fixed-k mode
            # it never updates, and a frozen 0.5 would read "healthy"
            # during the exact regression this surface exists to catch
            # (fixed-k readers watch spec_acceptance_rate instead)
            st["spec_acceptance_ema"] = self._acc_ema
        elif self.speculate > 1:
            st["spec_k"] = self.speculate
        return st

    def pop(self):
        if self._plane is not None:
            return self._plane.pop(self._stream)
        with self._lock:
            return self._out.popleft() if self._out else None

    @property
    def drained(self) -> bool:
        if self._plane is not None:
            return self.eos and self._plane.idle_for(self._stream)
        if self._disagg is not None and not self._disagg.idle():
            return False  # handed-off generations still in flight
        with self._lock:
            return (
                self.eos and not self._pending and not self._out
                and not self._disagg_done
            )

    def release_plane(self) -> None:
        """Detach from (and drop one ref of) the shared LLM plane —
        called when this server leaves the pairing table. Idempotent
        (the src calls it at drain AND at stop) and race-guarded under
        ``_lock``; private-batcher servers only unregister their
        migration handler here."""
        if self._mig_registered:
            self._mig_registered = False
            from nnstreamer_tpu.edge import query as _equery

            _equery.unregister_migration_handler(self._mig_id, self)
        with self._lock:
            plane, self._plane = self._plane, None
        if plane is None:
            return
        from nnstreamer_tpu.serving_plane import llm as llm_plane

        if self._stream is not None:
            plane.detach(self._stream)
        llm_plane.release(self.plane_name, plane)
        self.cb = None


@registry.element("tensor_llm_serversink")
class LlmServerSink(Sink):
    """Submit prompt frames into the shared continuous batcher.

    Props: id (pairing key), model (zoo:transformer_lm), custom
    (model options, filter-style "k:v,k2:v2"), n-slots, max-len,
    prompt-len, max-new-tokens (per-request default; per-frame
    ``max_new_tokens`` meta overrides), stream (one frame per NEW
    token then a done frame), speculate (=k: pump via spec_step —
    prompt-lookup speculation batched over slots, working across
    sampling/windowed/Pallas configurations; =auto adapts k to the
    measured acceptance rate), speculate-model
    (zoo:<name>: a DRAFT model proposes the speculate=k chunks instead
    of prompt-lookup; configure it with draft_-prefixed keys in the
    custom dict, e.g. draft_d_model/draft_n_layers/draft_n_heads —
    vocab is inherited from the target; implies speculate=4 when
    speculate is unset), pump (=N: target tokens per program launch —
    step_pump(N)/spec_pump over device-scanned rounds, ONE
    device→host read per pump instead of one per token; default 1
    keeps per-token stepping for minimum admission latency),
    kv-layout/block-size/kv-blocks/prefill-chunks (paged KV cache:
    block-table arena with prefix sharing, chunked prefill and
    preemption-by-eviction — docs/llm-serving.md; defaults from the
    [llm] config section), kv-attn (paged decode formulation:
    auto/block attend the arena directly through the block tables;
    gather keeps the materialized-view debug/parity oracle — flagged
    by nns-lint NNS-W117 when it would breach the memory bound),
    cache-dtype (int8 stores the KV cache quantized), kv-memory-bound
    (declared HBM budget consumed by nns-lint NNS-W115/W117),
    migrate-to (peer host:port — drain-time live KV-span migration;
    in-flight generations continue on the peer bitwise-identically for
    greedy requests), checkpoint-every-tokens/checkpoint-dir (periodic
    atomic span checkpoints; a restarted server adopts the files and
    resumes without re-running completed prefill chunks — docs/
    llm-serving.md "Migration & recovery"; all three require
    kv-layout=paged and are refused on plane= with a typed error),
    role/decode-peers (disaggregated prefill/decode serving — a
    role=prefill server runs chunked prefill then hands each KV span
    to the warmest decode peer, a role=decode server advertises pool
    headroom in probe acks and parks finished handoffs for the
    prefill side's fetch — docs/llm-serving.md "Disaggregated
    serving"; same kv-layout=paged / no-plane constraints)."""

    FACTORY_NAME = "tensor_llm_serversink"

    # negotiate() builds the shared _LlmServer (full model load) and
    # registers it in the module-global _table — nns-lint must not do
    # that during a dry run
    LINT_SKIP_NEGOTIATE = True

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with the serversrc"),
        "model": PropSpec("str", "zoo:transformer_lm"),
        "custom": PropSpec("str", "", desc="model options 'k:v,k2:v2'"),
        "n-slots": PropSpec("int", 4),
        "max-len": PropSpec("int", 256),
        "prompt-len": PropSpec("int", 64),
        "max-new-tokens": PropSpec("int", 16),
        "stream": PropSpec("bool", False),
        "speculate": PropSpec("str", "0", desc="k, or 'auto'"),
        "speculate-model": PropSpec("str", "", desc="zoo:<draft model>"),
        "pump": PropSpec("int", 1, desc="target tokens per launch"),
        # paged KV cache (nnstreamer_tpu/kv/, docs/llm-serving.md);
        # empty strings defer to the [llm] config section
        "kv-layout": PropSpec("str", "", desc="slot | paged ([llm] default)"),
        "kv-attn": PropSpec(
            "str", "",
            desc="paged decode path: auto | block | gather ([llm] default)",
        ),
        "block-size": PropSpec("int", 0, desc="tokens per KV block (paged)"),
        "kv-blocks": PropSpec("int", 0, desc="arena blocks (paged; 0=auto)"),
        "cache-dtype": PropSpec("str", "auto", desc="auto | int8"),
        "attn-impl": PropSpec(
            "str", "",
            desc="decode attention kernel: xla | pallas ([llm] "
            "attn_impl default; a pallas request the kernel registry "
            "would degrade is flagged by nns-lint NNS-W129)",
        ),
        "prefill-chunks": PropSpec(
            "int", 0, desc="prefill buckets per pump (paged; 0=[llm])"
        ),
        "kv-memory-bound": PropSpec(
            "str", "", desc="declared KV HBM bound (lint NNS-W115)"
        ),
        # through-plane serving (serving_plane/llm.py,
        # docs/llm-serving.md): serversinks naming one plane share ONE
        # paged ContinuousBatcher — cross-stream admission rides the
        # deficit-round-robin scheduler, SLO ledgers stay per stream
        "plane": PropSpec(
            "str", "",
            desc="attach to the named process-wide LLM serving plane "
            "(shared paged batcher; requires kv-layout=paged)",
        ),
        "plane-weight": PropSpec(
            "float", 1.0,
            desc="this stream's weighted-fair admission share on the "
            "LLM plane (default 1.0)",
        ),
        # live migration + crash recovery (docs/llm-serving.md
        # "Migration & recovery"): paged private batchers only —
        # plane-shared batchers refuse these with a typed error
        "migrate-to": PropSpec(
            "str", "",
            desc="peer host:port[/llm-id] for drain-time live KV-span "
            "migration (requires kv-layout=paged)",
        ),
        "checkpoint-every-tokens": PropSpec(
            "int", 0,
            desc="write an atomic span checkpoint every N generated "
            "tokens per request (0 = off; requires kv-layout=paged)",
        ),
        "checkpoint-dir": PropSpec(
            "str", "",
            desc="span checkpoint directory — in-flight generations "
            "found here resume at startup (crash recovery)",
        ),
        # disaggregated prefill/decode serving (serving_plane/disagg.py,
        # docs/llm-serving.md "Disaggregated serving"): paged private
        # batchers only, same refusal taxonomy as migrate-to
        "role": PropSpec(
            "enum", "", ("", "prefill", "decode"),
            desc="disaggregated serving role: prefill runs chunked "
            "prefill then hands the KV span to a decode peer; decode "
            "advertises pool headroom and adopts handed-off spans "
            "(requires kv-layout=paged)",
        ),
        "decode-peers": PropSpec(
            "str", "",
            desc="comma-separated decode peers host:port[/llm-id] for "
            "role=prefill handoffs (refusal or unreachable peers fall "
            "back to local decode — tokens are never lost)",
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.srv_id = str(self.get_property("id", "0"))
        # filter-style "k:v,k2:v2" option grammar (one parser for all
        # custom= props)
        from nnstreamer_tpu.backends.base import FilterProps

        options = FilterProps(
            custom=str(self.get_property("custom", ""))
        ).custom_dict()
        from nnstreamer_tpu.elements.base import _parse_bool

        from nnstreamer_tpu.config import conf

        cfg = conf()
        kv_layout = str(self.get_property("kv-layout", "")).strip() or (
            cfg.get("llm", "kv_layout", "slot")
        )
        if (
            str(self.get_property("plane", "") or "")
            and not str(self.get_property("kv-layout", "")).strip()
            and kv_layout == "slot"
        ):
            # plane= means "the shared paged batcher" — an unset
            # kv-layout follows the plane rather than the slot default
            kv_layout = "paged"
        kv_attn = str(self.get_property("kv-attn", "")).strip() or (
            cfg.get("llm", "kv_attn", "auto")
        )
        block_size = int(self.get_property("block-size", 0)) or (
            cfg.get_int("llm", "block_size", 16)
        )
        kv_blocks = int(self.get_property("kv-blocks", 0)) or (
            cfg.get_int("llm", "kv_blocks", 0)
        )
        prefill_chunks = int(self.get_property("prefill-chunks", 0)) or (
            cfg.get_int("llm", "prefill_chunks", 1)
        )
        self._create_kw = dict(
            model=str(self.get_property("model", "zoo:transformer_lm")),
            options=options,
            n_slots=int(self.get_property("n-slots", 4)),
            max_len=int(self.get_property("max-len", 256)),
            prompt_len=int(self.get_property("prompt-len", 64)),
            default_new=int(self.get_property("max-new-tokens", 16)),
            stream=_parse_bool(self.get_property("stream", False)),
            speculate=(
                -1 if str(self.get_property("speculate", 0)) == "auto"
                else int(self.get_property("speculate", 0))
            ),
            speculate_model=str(self.get_property("speculate-model", "")),
            pump_tokens=int(self.get_property("pump", 1)),
            kv_layout=kv_layout,
            block_size=block_size,
            kv_blocks=kv_blocks,
            cache_dtype=str(self.get_property("cache-dtype", "auto")),
            prefill_chunks=prefill_chunks,
            kv_attn=kv_attn,
            attn_impl=str(self.get_property("attn-impl", "")).strip() or (
                cfg.get("llm", "attn_impl", "xla")
            ),
            plane=str(self.get_property("plane", "") or ""),
            plane_weight=float(self.get_property("plane-weight", 1.0)),
            srv_id=self.srv_id,
            migrate_to=str(self.get_property("migrate-to", "") or ""),
            checkpoint_every_tokens=int(
                self.get_property("checkpoint-every-tokens", 0)
            ),
            checkpoint_dir=str(
                self.get_property("checkpoint-dir", "") or ""
            ),
            role=str(self.get_property("role", "") or ""),
            decode_peers=str(self.get_property("decode-peers", "") or ""),
        )
        self._server: Optional[_LlmServer] = None

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        self._server = _get_server(self.srv_id, self._create_kw)
        return []

    def render(self, frame: Frame) -> None:
        self._server.submit(frame)

    def on_eos(self) -> None:
        if self._server is not None:
            self._server.eos = True

    def stop(self) -> None:
        if self._server is not None:
            self._server.eos = True
            self._server.stopped = True


@registry.element("tensor_llm_serversrc")
class LlmServerSrc(Source):
    """Emit one frame per completed generation: tokens [1, n] int32 with
    the submitting frame's meta preserved (client_id routing)."""

    FACTORY_NAME = "tensor_llm_serversrc"

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with the serversink"),
        "stream": PropSpec("bool", False),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        from nnstreamer_tpu.elements.base import _parse_bool

        self.srv_id = str(self.get_property("id", "0"))
        # stream=true: one frame per NEW token (meta: stream/done/
        # token_index + the request frame's meta incl. client_id), then a
        # final done frame carrying the full generation
        self.stream = _parse_bool(self.get_property("stream", False))
        # THIS run's server, held by object reference — the id string is
        # reusable across pipelines, so it never identifies the server
        self._server: Optional[_LlmServer] = None
        self._final_stats: Optional[Dict] = None

    def _acquired(self, srv: Optional[_LlmServer]) -> Optional[_LlmServer]:
        if srv is not None and self.stream:
            srv.stream = True
        return srv

    def start(self) -> None:
        # acquire the paired server eagerly so teardown before the first
        # generate() still releases it from the table (the sink creates
        # it at negotiate, which precedes every element's start). If the
        # id pairs across pipelines started out of order the table may
        # still be empty here — generate() keeps the lazy fallback.
        if self._server is None:
            with _table_lock:
                self._server = self._acquired(_table.get(self.srv_id))

    def stop(self) -> None:
        # pipeline teardown (drained or not) releases the server — model
        # params and KV caches must not outlive the pipeline in _table;
        # keep a final stats snapshot for post-run --stats readers
        if self._final_stats is None:
            self._final_stats = self.serving_stats()
        _drop_server(self.srv_id, self._server)

    def serving_stats(self) -> Optional[Dict]:
        """Batcher counters for the executor's --stats surface (this
        run's server only, live or final snapshot)."""
        if self._final_stats is not None:
            return self._final_stats
        if self._server is not None:
            return self._server.stats()
        return None

    def output_spec(self) -> Spec:
        # generations vary in length per request → flexible
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def generate(self):
        import time as _time

        srv = self._server
        if srv is None:
            srv = self._server = self._acquired(_get_server(self.srv_id))
        item = srv.pop()
        if item is None:
            if srv.drained:
                self._final_stats = srv.stats()
                _drop_server(self.srv_id, srv)
                return EOS_FRAME
            if not srv.pump():  # decode even while no prompts arrive
                # idle (no active slots): the executor re-polls
                # immediately, so bound the spin here
                _time.sleep(0.002)
            item = srv.pop()
            if item is None:
                return None
        toks, meta = item
        arr = np.asarray(toks, np.int32)[None, :]
        return Frame((arr,), meta=meta)
