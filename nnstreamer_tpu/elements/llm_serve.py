"""tensor_llm_{serversink,serversrc}: continuous-batching LLM serving as
pipeline elements.

The reference serves one model to many clients at *frame* granularity:
tensor_query_serversrc emits client-tagged requests, the pipeline
processes them one at a time, serversink routes replies by client_id
(gst/nnstreamer/tensor_query/tensor_query_serversrc.c:379-427). An LLM
server multiplexes at *token* granularity instead — requests decode
concurrently in one slot batch (models/serving.ContinuousBatcher) and
finish out of order.

That asynchrony maps onto the same pairing pattern the reference uses for
repo and query elements: two elements share a server object through a
global ``id`` table —

    tensor_query_serversrc id=7 ! tensor_llm_serversink id=0 model=...
    tensor_llm_serversrc id=0 ! tensor_query_serversink id=7

- ``tensor_llm_serversink`` (a Sink) submits each incoming prompt frame
  (int32 token tensor; per-frame ``max_new_tokens`` meta overrides the
  element default). When the batch is full it pumps the batcher until a
  slot frees — admission backpressure.
- ``tensor_llm_serversrc`` (a Source, its own executor thread → decode
  makes progress even when no new prompts arrive) steps the batcher and
  emits one frame per *completed* request: tokens [1, n], with the
  request frame's meta (client_id!) preserved, so a downstream
  query-serversink routes each generation back to its requester.

EOS: the sink's flush marks end-of-submissions; the src drains every
pending request, then ends its stream.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import (
    ElementError,
    NegotiationError,
    PropSpec,
    Sink,
    Source,
    Spec,
)
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

_table: Dict[str, "_LlmServer"] = {}
_table_lock = threading.Lock()


def _get_server(srv_id: str, create_kw: Optional[dict] = None):
    with _table_lock:
        srv = _table.get(srv_id)
        if srv is not None and create_kw is not None and srv.eos:
            # stale server from a previous (stopped/drained) pipeline
            # run reusing this id: replace rather than resurrect — its
            # props may differ and its eos flag would end the new
            # stream. Its plane ref (if any) is NOT released here: the
            # stale server's own src may still be draining pending
            # generations through it — release rides that src's
            # _drop_server, which always releases the server it held.
            srv = None
        if srv is None:
            if create_kw is None:
                raise ElementError(
                    f"tensor_llm_server id={srv_id}: no serversink created "
                    "the server yet (the sink owns the model props)"
                )
            srv = _table[srv_id] = _LlmServer(**create_kw)
        return srv


def _drop_server(srv_id: str, srv) -> None:
    """Remove the table entry — but only if it is still ``srv``: another
    pipeline may have reused the id with a fresh server, and a src that
    stopped before ever acquiring its server (srv None) must not evict a
    live entry another pipeline registered under the same id. A
    plane-attached server also drops its plane ref (last sharer out
    closes the shared batcher) — unconditionally on ``srv``, not just
    when the table entry still matched: a stale server replaced by a
    fresh one under the same id would otherwise leak its ref forever.
    release_plane is idempotent, so the drained-then-stopped src's two
    calls release once."""
    with _table_lock:
        if srv is not None and _table.get(srv_id) is srv:
            _table.pop(srv_id, None)
    if srv is not None:
        srv.release_plane()


def _build_batcher(model: str, options: Dict[str, str], n_slots: int,
                   max_len: int, prompt_len: int, speculate: int,
                   speculate_model: str, kv_layout: str, block_size: int,
                   kv_blocks: int, cache_dtype: str, prefill_chunks: int,
                   kv_attn: str):
    """Open the zoo model (+ optional draft) and build the
    ContinuousBatcher — shared by the private-server path and the
    LlmPlane opener (serving_plane/llm.py), so through-plane serving
    runs the EXACT construction a solo serversink would."""
    from nnstreamer_tpu.models import zoo
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    if not model.startswith("zoo:"):
        raise ElementError(
            f"tensor_llm_serversink: model must be zoo:<name>, got "
            f"{model!r}"
        )
    m = zoo.get(model[len("zoo:"):], **options)
    n_heads = int(options.get("n_heads", 8))
    draft_kw = {}
    if speculate_model:
        # speculate-model=zoo:<name>: a draft model proposes the
        # speculate=k chunks instead of prompt-lookup. Its config
        # rides in the same custom dict under draft_-prefixed keys
        # (draft_d_model, draft_n_layers, draft_n_heads, ...); the
        # vocab must match the target's.
        if not speculate_model.startswith("zoo:"):
            raise ElementError(
                f"tensor_llm_serversink: speculate-model must be "
                f"zoo:<name>, got {speculate_model!r}"
            )
        d_opts = {
            k[len("draft_"):]: v for k, v in options.items()
            if k.startswith("draft_")
        }
        if "vocab" in options and "vocab" not in d_opts:
            d_opts["vocab"] = options["vocab"]
        dm = zoo.get(speculate_model[len("zoo:"):], **d_opts)
        draft_kw = dict(
            draft_params=dm.params,
            draft_n_heads=int(d_opts.get("n_heads", 8)),
        )
    kv_kw = {}
    if kv_layout != "slot":
        # paged KV (nnstreamer_tpu/kv/, docs/llm-serving.md):
        # block-table cache with prefix sharing, chunked prefill
        # and preemption-by-eviction; incompatible with a draft
        # model for now (ContinuousBatcher validates)
        kv_kw = dict(
            kv_layout=kv_layout, block_size=block_size,
            kv_blocks=kv_blocks or None,
            prefill_chunks=prefill_chunks,
            kv_attn=kv_attn or "auto",
        )
    return ContinuousBatcher(
        m.params, n_heads, n_slots=n_slots, max_len=max_len,
        prompt_len=prompt_len, cache_dtype=cache_dtype,
        **kv_kw, **draft_kw,
    )


class _LlmServer:
    """Shared state between the sink (submit) and src (pump/emit)."""

    def __init__(self, model: str, options: Dict[str, str], n_slots: int,
                 max_len: int, prompt_len: int, default_new: int,
                 stream: bool = False, speculate: int = 0,
                 speculate_model: str = "", pump_tokens: int = 1,
                 kv_layout: str = "slot", block_size: int = 16,
                 kv_blocks: int = 0, cache_dtype: str = "auto",
                 prefill_chunks: int = 1, kv_attn: str = "auto",
                 plane: str = "", plane_weight: float = 1.0,
                 srv_id: str = "0"):
        if speculate_model and speculate != -1 and speculate < 2:
            # a draft model exists ONLY to propose speculate=k chunks;
            # without this, every request would pay the draft prefill
            # for a proposer the plain-step pump never consults
            speculate = 4
        self.plane_name = plane
        self._plane = None   # LlmPlane once acquired
        self._stream = None  # this server's LlmStream
        if plane:
            # plane=<name> (docs/llm-serving.md): this serversink is one
            # client stream of a SHARED paged batcher — the tensor
            # plane's discipline at token granularity. The features that
            # assume a private batcher are rejected with the reason:
            if kv_layout != "paged":
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} needs "
                    "kv-layout=paged (the shared batcher is the paged "
                    "arena; slot caches are per-server by construction)"
                )
            if speculate or speculate_model:
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} cannot "
                    "combine with speculate/speculate-model (the "
                    "speculation controller state is per-server)"
                )
            if stream:
                raise ElementError(
                    f"tensor_llm_serversink: plane={plane!r} cannot "
                    "combine with stream=true (per-token routing "
                    "through a shared plane is not wired yet)"
                )
            from nnstreamer_tpu.serving_plane import llm as llm_plane

            sig = (
                model, tuple(sorted(options.items())), n_slots, max_len,
                prompt_len, kv_layout, block_size, kv_blocks,
                cache_dtype, prefill_chunks, kv_attn or "auto",
                max(1, int(pump_tokens)),
            )
            self._plane = llm_plane.acquire(
                plane, sig,
                opener=lambda: _build_batcher(
                    model, options, n_slots, max_len, prompt_len,
                    speculate, speculate_model, kv_layout, block_size,
                    kv_blocks, cache_dtype, prefill_chunks, kv_attn,
                ),
                pump_tokens=pump_tokens,
            )
            try:
                self._stream = self._plane.attach(srv_id, plane_weight)
            except ValueError:
                # same id string attached elsewhere in this process:
                # disambiguate rather than refuse (ids are only unique
                # per pairing)
                self._stream = self._plane.attach(
                    f"{srv_id}@{id(self) & 0xffff:04x}", plane_weight
                )
            self.cb = self._plane.cb
        else:
            self.cb = _build_batcher(
                model, options, n_slots, max_len, prompt_len, speculate,
                speculate_model, kv_layout, block_size, kv_blocks,
                cache_dtype, prefill_chunks, kv_attn,
            )
        self.default_new = default_new
        self._lock = threading.Lock()
        self._pending: Dict[int, dict] = {}  # rid -> request meta
        self._out: deque = deque()
        self.eos = False
        self.stopped = False
        # token streaming: emit one frame per NEW token as it decodes,
        # then a final done frame — the SSE-style serving surface in the
        # pipeline idiom. Authoritative when set at creation (the sink's
        # stream prop); the serversrc's stream=true also flips it at
        # acquisition, which is race-free only in the single-pipeline
        # layout (all elements start before any frame flows) — paired
        # ACROSS pipelines, set it on the sink.
        self.stream = stream
        # speculate=k: pump via spec_step(k) — prompt-lookup speculation
        # batched over slots (greedy slots emit several tokens per
        # program launch when the guesses land; exact equivalence).
        # speculate=auto (-1): k adapts to the measured acceptance rate
        # (EMA) between 2 and 8 — long chunks when guesses land, minimal
        # verify width when they don't.
        self.speculate = speculate
        # pump=N: target tokens per program launch — step_pump(N) /
        # spec_pump(rounds=⌈N/k⌉). N=1 keeps the per-token step path
        # (minimum admission latency); larger N amortizes the
        # host↔device round trip N ways (ONE readback per pump), the
        # knob that matters on a tunnel-attached chip. Admissions join
        # at the next pump, so latency-sensitive servers keep N small.
        self.pump_tokens = max(1, int(pump_tokens))
        self._spec_k = 4
        self._acc_ema = 0.5
        self._spec_seen = (0, 0)  # (columns, accepted) at last adapt
        self._sent: Dict[int, int] = {}  # rid -> tokens already streamed

    def submit(self, frame: Frame) -> None:
        import time as _time

        prompt = np.asarray(frame.tensors[0]).reshape(-1).astype(np.int32)
        budget = int(frame.meta.get("max_new_tokens", self.default_new))
        # per-request sampling params ride in frame meta (greedy default)
        kw = dict(
            temperature=float(frame.meta.get("temperature", 0.0)),
            top_k=int(frame.meta.get("top_k", 0)),
            top_p=float(frame.meta.get("top_p", 1.0)),
        )
        if "seed" in frame.meta:
            kw["seed"] = int(frame.meta["seed"])
        if "deadline_ms" in frame.meta:
            # SLO accounting (nns-top --requests); the edge layer's
            # deadline shedding is upstream of this element
            kw["deadline_s"] = float(frame.meta["deadline_ms"]) / 1000.0
        if self._plane is not None:
            # through-plane serving: the prompt queues for weighted-fair
            # admission into the SHARED batcher (serving_plane/llm.py);
            # backpressure past the fair backlog pumps inside submit
            if self.stopped:
                raise ElementError("tensor_llm_serversink: stopped")
            self._plane.submit(
                self._stream, prompt, budget, kw, dict(frame.meta)
            )
            return
        while True:
            if self.stopped:
                raise ElementError("tensor_llm_serversink: stopped")
            rid = self.cb.submit(prompt, budget, **kw)
            if rid is not None:
                break
            # batch full: pumping here IS the backpressure — admission
            # waits until decoding frees a slot. A no-progress pump is
            # NOT an error: the src thread may have just stepped/ drained
            # concurrently (freeing slots), so loop and retry submit.
            if not self.pump():
                _time.sleep(0.005)
        with self._lock:
            self._pending[rid] = dict(frame.meta)

    def pump(self) -> bool:
        """One decode step; harvest finished requests (and, in streaming
        mode, every new token). True if anything advanced."""
        if self._plane is not None:
            # the SHARED batcher advances every stream's requests; this
            # server's finished generations land on its own plane
            # stream deque (pop reads them there)
            return self._plane.pump()
        N = self.pump_tokens
        if self.speculate == -1:
            if N > 1:
                emitted = self.cb.spec_pump(
                    rounds=max(1, -(-N // self._spec_k)), k=self._spec_k
                )
            else:
                emitted = self.cb.spec_step(k=self._spec_k)
            st = self.cb.stats()
            # normalize by proposal COLUMNS, not rounds: a round offers
            # active_slots×(k-1) proposals, so a rounds-based rate would
            # saturate on multi-slot servers and pin k at max exactly
            # when acceptance is poor
            cols, acc = st["spec_columns"], st["spec_accepted_tokens"]
            dc = cols - self._spec_seen[0]
            if dc > 0:
                rate = (acc - self._spec_seen[1]) / dc
                self._acc_ema = 0.7 * self._acc_ema + 0.3 * rate
                self._spec_k = min(
                    8, max(2, 2 + int(round(self._acc_ema * 6)))
                )
                self._spec_seen = (cols, acc)
        elif self.speculate > 1:
            if N > 1:
                emitted = self.cb.spec_pump(
                    rounds=max(1, -(-N // self.speculate)),
                    k=self.speculate,
                )
            else:
                emitted = self.cb.spec_step(k=self.speculate)
        elif N > 1:
            emitted = self.cb.step_pump(N)
        else:
            emitted = self.cb.step()
        harvested = False
        with self._lock:
            if self.stream:
                # count-based catch-up off cb.partials() (one batcher
                # lock pass for all pending rids): robust to tokens
                # emitted by ANY thread's step between two pumps
                parts = self.cb.partials(list(self._pending))
                for rid, meta in self._pending.items():
                    toks = parts.get(rid)
                    if toks is None:
                        continue
                    harvested |= self._stream_new_locked(rid, meta, toks)
            for rid in list(self._pending):
                toks = self.cb.result(rid)
                if toks is not None:
                    meta = self._pending.pop(rid)
                    if self.stream:
                        # a concurrent pump's step may have finished the
                        # request AFTER our catch-up pass above — emit the
                        # tail tokens per-frame before the done frame so
                        # the one-frame-per-token contract holds
                        self._stream_new_locked(rid, meta, toks)
                        meta = {**meta, "stream": True, "done": True}
                    self._sent.pop(rid, None)
                    self._out.append((toks, meta))
                    harvested = True
        return bool(emitted) or harvested

    def _stream_new_locked(self, rid: int, meta: dict, toks) -> bool:
        """Emit per-token frames for tokens not yet streamed (_lock held)."""
        n0 = self._sent.get(rid, 0)
        for i in range(n0, len(toks)):
            self._out.append((
                [toks[i]],
                {**meta, "stream": True, "done": False, "token_index": i},
            ))
        self._sent[rid] = len(toks)
        return len(toks) > n0

    def stats(self) -> Dict:
        """Batcher counters + the adaptive-speculation control state
        (VERDICT r4 #5: a silent proposer regression shows up here as a
        sagging acceptance rate / k pinned at 2 — visible in --stats,
        not only in wall time)."""
        if self._plane is not None:
            # shared-batcher counters + ONLY this stream's request rows
            # (per-stream SLO ledgers: sharers never report each
            # other's — serving_plane/llm.py)
            return self._plane.stats_for(self._stream)
        st = self.cb.stats()
        # per-request SLO rows for nns-top --requests (serving_requests
        # once the executor prefixes the row)
        st["requests"] = {
            str(rid): row for rid, row in self.cb.requests().items()
        }
        if self.speculate == -1:
            st["spec_k"] = self._spec_k
            # the EMA is the auto controller's state — in fixed-k mode
            # it never updates, and a frozen 0.5 would read "healthy"
            # during the exact regression this surface exists to catch
            # (fixed-k readers watch spec_acceptance_rate instead)
            st["spec_acceptance_ema"] = self._acc_ema
        elif self.speculate > 1:
            st["spec_k"] = self.speculate
        return st

    def pop(self):
        if self._plane is not None:
            return self._plane.pop(self._stream)
        with self._lock:
            return self._out.popleft() if self._out else None

    @property
    def drained(self) -> bool:
        if self._plane is not None:
            return self.eos and self._plane.idle_for(self._stream)
        with self._lock:
            return self.eos and not self._pending and not self._out

    def release_plane(self) -> None:
        """Detach from (and drop one ref of) the shared LLM plane —
        called when this server leaves the pairing table. Idempotent
        (the src calls it at drain AND at stop) and race-guarded under
        ``_lock``; no-op for private-batcher servers."""
        with self._lock:
            plane, self._plane = self._plane, None
        if plane is None:
            return
        from nnstreamer_tpu.serving_plane import llm as llm_plane

        if self._stream is not None:
            plane.detach(self._stream)
        llm_plane.release(self.plane_name, plane)
        self.cb = None


@registry.element("tensor_llm_serversink")
class LlmServerSink(Sink):
    """Submit prompt frames into the shared continuous batcher.

    Props: id (pairing key), model (zoo:transformer_lm), custom
    (model options, filter-style "k:v,k2:v2"), n-slots, max-len,
    prompt-len, max-new-tokens (per-request default; per-frame
    ``max_new_tokens`` meta overrides), stream (one frame per NEW
    token then a done frame), speculate (=k: pump via spec_step —
    prompt-lookup speculation batched over slots, working across
    sampling/windowed/Pallas configurations; =auto adapts k to the
    measured acceptance rate), speculate-model
    (zoo:<name>: a DRAFT model proposes the speculate=k chunks instead
    of prompt-lookup; configure it with draft_-prefixed keys in the
    custom dict, e.g. draft_d_model/draft_n_layers/draft_n_heads —
    vocab is inherited from the target; implies speculate=4 when
    speculate is unset), pump (=N: target tokens per program launch —
    step_pump(N)/spec_pump over device-scanned rounds, ONE
    device→host read per pump instead of one per token; default 1
    keeps per-token stepping for minimum admission latency),
    kv-layout/block-size/kv-blocks/prefill-chunks (paged KV cache:
    block-table arena with prefix sharing, chunked prefill and
    preemption-by-eviction — docs/llm-serving.md; defaults from the
    [llm] config section), kv-attn (paged decode formulation:
    auto/block attend the arena directly through the block tables;
    gather keeps the materialized-view debug/parity oracle — flagged
    by nns-lint NNS-W117 when it would breach the memory bound),
    cache-dtype (int8 stores the KV cache quantized), kv-memory-bound
    (declared HBM budget consumed by nns-lint NNS-W115/W117)."""

    FACTORY_NAME = "tensor_llm_serversink"

    # negotiate() builds the shared _LlmServer (full model load) and
    # registers it in the module-global _table — nns-lint must not do
    # that during a dry run
    LINT_SKIP_NEGOTIATE = True

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with the serversrc"),
        "model": PropSpec("str", "zoo:transformer_lm"),
        "custom": PropSpec("str", "", desc="model options 'k:v,k2:v2'"),
        "n-slots": PropSpec("int", 4),
        "max-len": PropSpec("int", 256),
        "prompt-len": PropSpec("int", 64),
        "max-new-tokens": PropSpec("int", 16),
        "stream": PropSpec("bool", False),
        "speculate": PropSpec("str", "0", desc="k, or 'auto'"),
        "speculate-model": PropSpec("str", "", desc="zoo:<draft model>"),
        "pump": PropSpec("int", 1, desc="target tokens per launch"),
        # paged KV cache (nnstreamer_tpu/kv/, docs/llm-serving.md);
        # empty strings defer to the [llm] config section
        "kv-layout": PropSpec("str", "", desc="slot | paged ([llm] default)"),
        "kv-attn": PropSpec(
            "str", "",
            desc="paged decode path: auto | block | gather ([llm] default)",
        ),
        "block-size": PropSpec("int", 0, desc="tokens per KV block (paged)"),
        "kv-blocks": PropSpec("int", 0, desc="arena blocks (paged; 0=auto)"),
        "cache-dtype": PropSpec("str", "auto", desc="auto | int8"),
        "prefill-chunks": PropSpec(
            "int", 0, desc="prefill buckets per pump (paged; 0=[llm])"
        ),
        "kv-memory-bound": PropSpec(
            "str", "", desc="declared KV HBM bound (lint NNS-W115)"
        ),
        # through-plane serving (serving_plane/llm.py,
        # docs/llm-serving.md): serversinks naming one plane share ONE
        # paged ContinuousBatcher — cross-stream admission rides the
        # deficit-round-robin scheduler, SLO ledgers stay per stream
        "plane": PropSpec(
            "str", "",
            desc="attach to the named process-wide LLM serving plane "
            "(shared paged batcher; requires kv-layout=paged)",
        ),
        "plane-weight": PropSpec(
            "float", 1.0,
            desc="this stream's weighted-fair admission share on the "
            "LLM plane (default 1.0)",
        ),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.srv_id = str(self.get_property("id", "0"))
        # filter-style "k:v,k2:v2" option grammar (one parser for all
        # custom= props)
        from nnstreamer_tpu.backends.base import FilterProps

        options = FilterProps(
            custom=str(self.get_property("custom", ""))
        ).custom_dict()
        from nnstreamer_tpu.elements.base import _parse_bool

        from nnstreamer_tpu.config import conf

        cfg = conf()
        kv_layout = str(self.get_property("kv-layout", "")).strip() or (
            cfg.get("llm", "kv_layout", "slot")
        )
        if (
            str(self.get_property("plane", "") or "")
            and not str(self.get_property("kv-layout", "")).strip()
            and kv_layout == "slot"
        ):
            # plane= means "the shared paged batcher" — an unset
            # kv-layout follows the plane rather than the slot default
            kv_layout = "paged"
        kv_attn = str(self.get_property("kv-attn", "")).strip() or (
            cfg.get("llm", "kv_attn", "auto")
        )
        block_size = int(self.get_property("block-size", 0)) or (
            cfg.get_int("llm", "block_size", 16)
        )
        kv_blocks = int(self.get_property("kv-blocks", 0)) or (
            cfg.get_int("llm", "kv_blocks", 0)
        )
        prefill_chunks = int(self.get_property("prefill-chunks", 0)) or (
            cfg.get_int("llm", "prefill_chunks", 1)
        )
        self._create_kw = dict(
            model=str(self.get_property("model", "zoo:transformer_lm")),
            options=options,
            n_slots=int(self.get_property("n-slots", 4)),
            max_len=int(self.get_property("max-len", 256)),
            prompt_len=int(self.get_property("prompt-len", 64)),
            default_new=int(self.get_property("max-new-tokens", 16)),
            stream=_parse_bool(self.get_property("stream", False)),
            speculate=(
                -1 if str(self.get_property("speculate", 0)) == "auto"
                else int(self.get_property("speculate", 0))
            ),
            speculate_model=str(self.get_property("speculate-model", "")),
            pump_tokens=int(self.get_property("pump", 1)),
            kv_layout=kv_layout,
            block_size=block_size,
            kv_blocks=kv_blocks,
            cache_dtype=str(self.get_property("cache-dtype", "auto")),
            prefill_chunks=prefill_chunks,
            kv_attn=kv_attn,
            plane=str(self.get_property("plane", "") or ""),
            plane_weight=float(self.get_property("plane-weight", 1.0)),
            srv_id=self.srv_id,
        )
        self._server: Optional[_LlmServer] = None

    def negotiate(self, in_specs: List[Spec]) -> List[Spec]:
        (spec,) = in_specs
        if not isinstance(spec, TensorsSpec):
            raise NegotiationError(f"{self.name}: needs tensor input")
        self._server = _get_server(self.srv_id, self._create_kw)
        return []

    def render(self, frame: Frame) -> None:
        self._server.submit(frame)

    def on_eos(self) -> None:
        if self._server is not None:
            self._server.eos = True

    def stop(self) -> None:
        if self._server is not None:
            self._server.eos = True
            self._server.stopped = True


@registry.element("tensor_llm_serversrc")
class LlmServerSrc(Source):
    """Emit one frame per completed generation: tokens [1, n] int32 with
    the submitting frame's meta preserved (client_id routing)."""

    FACTORY_NAME = "tensor_llm_serversrc"

    PROPERTIES = {
        "id": PropSpec("str", "0", desc="pairing key with the serversink"),
        "stream": PropSpec("bool", False),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        from nnstreamer_tpu.elements.base import _parse_bool

        self.srv_id = str(self.get_property("id", "0"))
        # stream=true: one frame per NEW token (meta: stream/done/
        # token_index + the request frame's meta incl. client_id), then a
        # final done frame carrying the full generation
        self.stream = _parse_bool(self.get_property("stream", False))
        # THIS run's server, held by object reference — the id string is
        # reusable across pipelines, so it never identifies the server
        self._server: Optional[_LlmServer] = None
        self._final_stats: Optional[Dict] = None

    def _acquired(self, srv: Optional[_LlmServer]) -> Optional[_LlmServer]:
        if srv is not None and self.stream:
            srv.stream = True
        return srv

    def start(self) -> None:
        # acquire the paired server eagerly so teardown before the first
        # generate() still releases it from the table (the sink creates
        # it at negotiate, which precedes every element's start). If the
        # id pairs across pipelines started out of order the table may
        # still be empty here — generate() keeps the lazy fallback.
        if self._server is None:
            with _table_lock:
                self._server = self._acquired(_table.get(self.srv_id))

    def stop(self) -> None:
        # pipeline teardown (drained or not) releases the server — model
        # params and KV caches must not outlive the pipeline in _table;
        # keep a final stats snapshot for post-run --stats readers
        if self._final_stats is None:
            self._final_stats = self.serving_stats()
        _drop_server(self.srv_id, self._server)

    def serving_stats(self) -> Optional[Dict]:
        """Batcher counters for the executor's --stats surface (this
        run's server only, live or final snapshot)."""
        if self._final_stats is not None:
            return self._final_stats
        if self._server is not None:
            return self._server.stats()
        return None

    def output_spec(self) -> Spec:
        # generations vary in length per request → flexible
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def generate(self):
        import time as _time

        srv = self._server
        if srv is None:
            srv = self._server = self._acquired(_get_server(self.srv_id))
        item = srv.pop()
        if item is None:
            if srv.drained:
                self._final_stats = srv.stats()
                _drop_server(self.srv_id, srv)
                return EOS_FRAME
            if not srv.pump():  # decode even while no prompts arrive
                # idle (no active slots): the executor re-polls
                # immediately, so bound the spin here
                _time.sleep(0.002)
            item = srv.pop()
            if item is None:
                return None
        toks, meta = item
        arr = np.asarray(toks, np.int32)[None, :]
        return Frame((arr,), meta=meta)
