"""Pipeline elements. Importing registers every built-in element factory
(the analogue of GST_PLUGIN_DEFINE in registerer/nnstreamer.c:88-121)."""

from nnstreamer_tpu.elements.base import (  # noqa: F401
    Element,
    HostElement,
    MediaSpec,
    NegotiationError,
    Routing,
    Sink,
    Source,
    TensorOp,
)
from nnstreamer_tpu.elements import sources  # noqa: F401
from nnstreamer_tpu.elements import converter  # noqa: F401
from nnstreamer_tpu.elements import transform  # noqa: F401
from nnstreamer_tpu.elements import filter as filter_elem  # noqa: F401
from nnstreamer_tpu.elements import decoder  # noqa: F401
from nnstreamer_tpu.elements import sink  # noqa: F401
from nnstreamer_tpu.elements import flow  # noqa: F401
from nnstreamer_tpu.elements import routing  # noqa: F401
from nnstreamer_tpu.elements import windowing  # noqa: F401
from nnstreamer_tpu.elements import control  # noqa: F401
from nnstreamer_tpu.elements import sparse_elems  # noqa: F401
from nnstreamer_tpu.elements import stage  # noqa: F401
from nnstreamer_tpu.elements import iio  # noqa: F401
from nnstreamer_tpu.elements import chaos  # noqa: F401
from nnstreamer_tpu.elements import llm_serve  # noqa: F401
from nnstreamer_tpu.elements import media  # noqa: F401
# distributed elements (conditional registration in the reference's
# registerer, nnstreamer.c:113-119 — here always available, TCP transport)
from nnstreamer_tpu.edge import pubsub  # noqa: F401
from nnstreamer_tpu.edge import mqtt_elems  # noqa: F401
from nnstreamer_tpu.edge import query  # noqa: F401
from nnstreamer_tpu.edge import grpc_bridge  # noqa: F401
