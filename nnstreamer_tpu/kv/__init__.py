"""nns-kv: paged KV-cache management for continuous-batching LLM serving.

The slot-layout :class:`~nnstreamer_tpu.models.serving.ContinuousBatcher`
allocates one contiguous ``[L, B, max_len, KV, Dh]`` cache sized for the
worst-case request: HBM for short requests is wasted, shared system
prompts re-prefill per request, and a long prefill stalls every decoding
slot. This package is the paged alternative behind
``ContinuousBatcher(kv_layout="paged")`` (docs/llm-serving.md):

- :mod:`blocks` — BlockPool: fixed-size token blocks carved from one
  device-resident arena per layer, ref-counted with copy-on-write, and a
  rolling-prefix-hash index so requests sharing a token prefix share
  physical blocks;
- :mod:`block_attn` — the DEFAULT block-native decode/verify
  formulation (``kv_attn="auto"|"block"``): attention reads ride the
  block table straight off the arena, token writes land in place in
  their owning block — no contiguous view in either direction, bitwise
  identical to the slot path (tests/test_kv_block_attn.py);
- :mod:`gather` — the admission-path block ops plus the
  gather→view→scatter decode oracle behind ``kv_attn="gather"``
  (bitwise parity with the contiguous slot path, pinned by
  tests/test_kv_paged.py; pays a transient view beside the arena —
  debugging only);
- :mod:`sched` — chunked-prefill admission jobs, watermark block
  accounting with preemption-by-eviction, and the per-request SLO
  ledger (queue/prefill/TTFT/TPOT → nns-obs).
"""

from nnstreamer_tpu.kv.blocks import BlockPool, NoBlocksError
from nnstreamer_tpu.kv.sched import PrefillJob, SLOLedger, SLORecord

__all__ = [
    "BlockPool",
    "NoBlocksError",
    "PrefillJob",
    "SLOLedger",
    "SLORecord",
]
