"""Chunked-prefill admission, watermark accounting, per-request SLOs.

Three serving-scheduler concerns the paged batcher delegates here:

- **Chunked prefill** (:class:`PrefillJob`): a paged submit() never
  prefill-stalls the decode plane. The prompt becomes a job; each
  step/pump advances the front job by at most ``prefill_chunks`` buckets
  of ``prompt_len`` tokens before decoding, so a decoding request's
  time-between-tokens is bounded by ONE chunk of someone else's prompt,
  however long that prompt is (pinned by tests/test_kv_paged.py).
- **Watermark admission + preemption-by-eviction**: a finished prefill
  only activates when the pool can cover its blocks AND one decode-
  growth block per live request (the watermark) — otherwise it waits,
  so admission can never thrash the decode plane. Decode growth itself
  preempts the youngest other request on exhaustion
  (:func:`choose_victim`): its blocks are freed (shared prefix blocks
  survive in the pool's cached tier) and it re-enters the prefill queue
  to be re-prefilled from whatever prefix still matches — never an OOM.
- **SLO ledger** (:class:`SLOLedger`): per-request queue / prefill /
  TTFT / TPOT wall stamps, surfaced through ``nns-top --requests`` and
  the ``nns_request_ttft_ms`` / ``nns_request_tpot_ms`` histograms.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class PrefillJob:
    """One admission working its way through chunked prefill.

    ``tokens`` is the FULL known context (prefix + prompt for a fresh
    request; prompt + already-generated tokens for a preempted one being
    re-prefilled — ``known_first`` then carries the pending token, so no
    re-sampling happens and the resumed stream is exactly the original).
    ``base`` is the first position not yet covered (matched prefix
    tokens start it past 0); ``cpos`` tracks chunking progress."""

    slot: int
    req: Any  # models/serving._Request
    tokens: Any  # np.ndarray int32 — full context to (re)prefill
    known_first: Optional[int] = None
    base: int = 0                 # positions < base came from the match
    cpos: int = 0                 # positions < base+cpos are staged
    stage: Any = None             # (ks, vs) staging cache, lazily built
    logits_row: Any = None        # final chunk's last-token logits
    matched_full: List[int] = field(default_factory=list)
    matched_partial: Optional[int] = None
    n_partial: int = 0
    resumed: bool = False
    # set by the sharing-degradation fallback: staging restarts WITHOUT
    # re-matching (re-adopting the same prefix would undo the degrade
    # and livelock the queue head)
    no_rematch: bool = False

    @property
    def fill(self) -> int:
        return int(self.tokens.shape[0])

    def done_staging(self) -> bool:
        return self.base + self.cpos >= self.fill


def choose_victim(slots, active, needy_slot: int) -> Optional[int]:
    """Preemption victim: the YOUNGEST (highest rid) active request
    other than the one needing room — it has the least sunk prefill/
    decode work and the best chance of a prefix hit on re-admission
    (its own prompt blocks just went into the cached tier). None when
    the needy slot is the only active one."""
    best = None
    best_rid = -1
    for s, req in enumerate(slots):
        if req is None or not active[s] or s == needy_slot:
            continue
        if req.rid > best_rid:
            best, best_rid = s, req.rid
    return best


@dataclass
class SLORecord:
    rid: int
    t_submit: float
    deadline_s: Optional[float] = None
    t_admit: Optional[float] = None      # prefill done, slot active
    t_first: Optional[float] = None      # first token materialized
    t_done: Optional[float] = None
    n_tokens: int = 0
    preemptions: int = 0
    # queued | prefilling | decoding | done | migrated (extracted and
    # re-hosted on a peer batcher — terminal HERE; the adopting side
    # opens a fresh record that finishes the request)
    state: str = "queued"

    def view(self) -> Dict[str, Any]:
        ttft = tpot = None
        if self.t_first is not None:
            ttft = (self.t_first - self.t_submit) * 1000.0
        if (self.t_done is not None and self.t_first is not None
                and self.n_tokens > 1):
            tpot = ((self.t_done - self.t_first)
                    / (self.n_tokens - 1)) * 1000.0
        queue_ms = None
        if self.t_admit is not None:
            queue_ms = (self.t_admit - self.t_submit) * 1000.0
        out = {
            "state": self.state,
            "queue_ms": queue_ms,
            "ttft_ms": ttft,
            "tpot_ms": tpot,
            "tokens": self.n_tokens,
            "preemptions": self.preemptions,
        }
        if self.deadline_s is not None:
            remaining = self.deadline_s - (time.perf_counter()
                                           - self.t_submit)
            out["deadline_s"] = round(remaining, 3)
        return out


class SLOLedger:
    """Bounded per-request SLO accounting. Single-writer under the
    batcher's state lock; emits the TTFT/TPOT histograms through the
    obs registry resolved once at construction (the FaultGate
    discipline)."""

    def __init__(self, keep: int = 1024, obs_registry=None):
        self._recs: "OrderedDict[int, SLORecord]" = OrderedDict()
        self._keep = keep
        self._obs = obs_registry
        self.preemptions_total = 0

    def submit(self, rid: int, deadline_s: Optional[float] = None
               ) -> SLORecord:
        rec = SLORecord(rid, time.perf_counter(), deadline_s=deadline_s)
        self._recs[rid] = rec
        while len(self._recs) > self._keep:
            self._recs.popitem(last=False)
        return rec

    def _get(self, rid: int) -> Optional[SLORecord]:
        return self._recs.get(rid)

    def prefilling(self, rid: int) -> None:
        rec = self._get(rid)
        if rec is not None and rec.state == "queued":
            rec.state = "prefilling"

    def admitted(self, rid: int) -> None:
        rec = self._get(rid)
        if rec is not None:
            rec.t_admit = time.perf_counter()
            rec.state = "decoding"

    def first_token(self, rid: int) -> None:
        rec = self._get(rid)
        if rec is not None and rec.t_first is None:
            rec.t_first = time.perf_counter()
            if self._obs is not None:
                self._obs.histogram("nns_request_ttft_ms").observe(
                    max((rec.t_first - rec.t_submit) * 1000.0, 1e-6)
                )

    def record(self, rid: int) -> Optional[SLORecord]:
        """The live record for ``rid`` (migration reads the deadline and
        preemption count to ship with the span), or None if evicted."""
        return self._get(rid)

    def migrated(self, rid: int) -> None:
        """The request was extracted and re-hosted elsewhere: terminal
        for THIS ledger (the peer's record carries it to done)."""
        rec = self._get(rid)
        if rec is not None:
            rec.t_done = time.perf_counter()
            rec.state = "migrated"

    def preempted(self, rid: int) -> None:
        rec = self._get(rid)
        self.preemptions_total += 1
        if rec is not None:
            rec.preemptions += 1
            rec.state = "queued"

    def finished(self, rid: int, n_tokens: int) -> None:
        rec = self._get(rid)
        if rec is None:
            return
        rec.t_done = time.perf_counter()
        rec.n_tokens = n_tokens
        rec.state = "done"
        if rec.t_first is None:  # one-token requests: first IS done
            rec.t_first = rec.t_done
        if self._obs is not None and n_tokens > 1:
            tpot = (rec.t_done - rec.t_first) / (n_tokens - 1) * 1000.0
            self._obs.histogram("nns_request_tpot_ms").observe(
                max(tpot, 1e-6)
            )

    def view(self, extra: Optional[Dict[int, Dict]] = None
             ) -> Dict[int, Dict[str, Any]]:
        out = {}
        for rid, rec in self._recs.items():
            row = rec.view()
            if extra and rid in extra:
                row.update(extra[rid])
            out[rid] = row
        return out

    def snapshot(self) -> dict:
        return {
            "preemptions_total": self.preemptions_total,
            "records": [
                {
                    "rid": r.rid,
                    "t_submit": r.t_submit,
                    "deadline_s": r.deadline_s,
                    "t_admit": r.t_admit,
                    "t_first": r.t_first,
                    "t_done": r.t_done,
                    "n_tokens": r.n_tokens,
                    "preemptions": r.preemptions,
                    "state": r.state,
                }
                for r in self._recs.values()
            ],
        }

    def restore(self, snap: dict) -> None:
        self.preemptions_total = int(snap.get("preemptions_total", 0))
        self._recs = OrderedDict()
        for d in snap.get("records", []):
            rec = SLORecord(
                int(d["rid"]), float(d["t_submit"]),
                deadline_s=d.get("deadline_s"),
            )
            rec.t_admit = d.get("t_admit")
            rec.t_first = d.get("t_first")
            rec.t_done = d.get("t_done")
            rec.n_tokens = int(d.get("n_tokens", 0))
            rec.preemptions = int(d.get("preemptions", 0))
            rec.state = str(d.get("state", "queued"))
            self._recs[rec.rid] = rec
