"""Live KV-span serialization: one request's paged cache state on the wire.

A :class:`RequestSpan` is everything a second paged
``ContinuousBatcher`` needs to continue a generation mid-decode exactly
where the source left off (docs/llm-serving.md "Migration & recovery"):

- the request row itself — prompt, generated-token tail, sampling
  params, the base PRNG key and ``fill0`` (sampling keys by
  (seed, position), so the resumed stream is bitwise the original);
- the raw per-block K/V payloads, sliced straight off the arena leaves
  — NOT through ``read_block``, whose int8 path dequantizes: shipping
  the quantized bytes + scales verbatim is what keeps an int8 migration
  bitwise — each block CRC32-checked individually;
- the rolling-CRC prefix hashes (kv/blocks.roll_hash) at every full
  block boundary, so a destination can prove which prefix blocks it
  already holds and the source can strip those payloads
  (:meth:`RequestSpan.strip_shared`) — a warm migration ships only the
  unshared suffix;
- the SLO row (remaining deadline, preemption count) so the request's
  service record survives the hop.

Wire format: ``NNSSPAN1`` magic, a uint32-length JSON header (geometry,
request row, per-block CRC records), then the concatenated raw leaf
bytes of every non-stripped block. Byte counts feed :data:`tally` (the
``pipeline/transfer.TransferTally`` idiom) and the
``nns_kv_span_bytes_total`` counter, so warm-vs-cold savings are
observable, not folklore.
"""

from __future__ import annotations

import json
import struct
import threading
import zlib
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

SPAN_VERSION = 1
_MAGIC = b"NNSSPAN1"
_LEN = struct.Struct("<I")


class SpanError(RuntimeError):
    """Base of the migration failure taxonomy — every refusal a peer or
    codec can produce is a subclass, so fleet callers catch one type and
    fall back to re-prefill (the PR-10 eviction-resume path)."""


class SpanFormatError(SpanError):
    """Malformed span bytes, or a geometry mismatch between the span and
    the adopting batcher (block size, arena leaf shapes, cache dtype)."""


class SpanCorruptError(SpanError):
    """A block payload failed its CRC32 — the span must not be adopted
    (a corrupt block would silently poison the continued generation)."""


class SpanPayloadMissingError(SpanError):
    """A stripped block's K/V is not covered by the destination's prefix
    index — the sender stripped more than the receiver shares."""


class SpanStateError(SpanError):
    """The request is not in an extractable state (unknown rid, still
    queued/prefilling — settle the prefill queue first, or finished)."""


class SpanCapacityError(SpanError):
    """The destination cannot host the span right now: no free slot, no
    free blocks, or the span would overflow ``max_len``. Retryable —
    the source keeps the request and falls back to local resume."""


class SpanTally:
    """Process-local byte accounting for encoded/decoded spans — the
    ``pipeline/transfer.TransferTally`` idiom at migration granularity,
    so tests assert warm < cold in bytes, not vibes. Thread-safe; the
    module-global :data:`tally` is shared by every batcher in the
    process."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts = {"out": 0, "in": 0}
        self._bytes = {"out": 0, "in": 0}

    def count(self, direction: str, nbytes: int) -> None:
        with self._lock:
            self._counts[direction] += 1
            self._bytes[direction] += int(nbytes)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "spans_out": self._counts["out"],
                "spans_in": self._counts["in"],
                "bytes_out": self._bytes["out"],
                "bytes_in": self._bytes["in"],
            }

    def reset(self) -> None:
        with self._lock:
            self._counts = {"out": 0, "in": 0}
            self._bytes = {"out": 0, "in": 0}


tally = SpanTally()


def _emit_span_bytes(direction: str, nbytes: int) -> None:
    """Mirror a span encode/decode into the obs registry (resolved per
    event — migrations are rare control-plane work, not a hot path)."""
    from nnstreamer_tpu.obs import metrics as _metrics

    reg = _metrics.get()
    if reg is not None:
        reg.counter(
            "nns_kv_span_bytes_total", direction=direction
        ).inc(int(nbytes))


@dataclass
class BlockRecord:
    """One KV block of a span: ``n_tokens`` valid positions, the CRC32
    of its raw leaf bytes, and the payload itself — one ``bytes`` per
    arena leaf, or None when stripped (the destination's prefix index
    already holds this block's content)."""

    n_tokens: int
    crc: int
    payload: Optional[List[bytes]] = None


@dataclass
class RequestSpan:
    """A single request's migratable state (see module docstring)."""

    block_size: int
    # per-block leaf templates: (dtype name, per-block shape) for each
    # arena leaf in jax tree-leaves order — fp caches carry 2 leaves
    # (k, v), int8 caches 4 (k8, k_scale, v8, v_scale)
    leaves: List[Tuple[str, Tuple[int, ...]]]
    cache_dtype: str
    rid: int
    prompt: np.ndarray
    tokens: List[int]
    fill0: int
    budget: int
    temperature: float
    top_k: int
    top_p: float
    stop_token: Optional[int]
    key: np.ndarray  # base PRNG key, uint32 [2]
    deadline_s: Optional[float]  # REMAINING deadline at extraction
    preemptions: int
    prefix_hashes: List[int]  # rolling CRC at each full block boundary
    blocks: List[BlockRecord]
    meta: Dict[str, Any] = field(default_factory=dict)
    version: int = SPAN_VERSION

    @property
    def n_kv(self) -> int:
        """Positions with K/V on the source: the pending token
        ``tokens[-1]`` has not been written yet (the batcher invariant
        ``pos = fill0 + len(tokens) - 1``)."""
        return self.fill0 + len(self.tokens) - 1

    @property
    def kv_tokens(self) -> np.ndarray:
        """The token stream covered by K/V (prompt + generated, minus
        the pending token) — what the destination matches against its
        prefix index and registers after adoption."""
        stream = np.concatenate([
            np.asarray(self.prompt, np.int32),
            np.asarray(self.tokens, np.int32),
        ])
        return stream[: self.n_kv]

    def payload_bytes(self) -> int:
        """Raw K/V bytes this span would ship (stripped blocks cost 0)."""
        return sum(
            sum(len(b) for b in rec.payload)
            for rec in self.blocks if rec.payload is not None
        )

    def strip_shared(self, n_shared_tokens: int) -> "RequestSpan":
        """A copy with payloads dropped for every FULL block entirely
        covered by the destination's ``probe_prefix`` answer — the warm-
        migration diet. CRCs and hashes stay, so the receiver still
        verifies what it adopts locally. Partial blocks never strip:
        the destination shares full blocks only (no CoW over the wire)."""
        bs = self.block_size
        out = []
        for i, rec in enumerate(self.blocks):
            covered = (i + 1) * bs <= int(n_shared_tokens)
            if covered and rec.n_tokens == bs:
                out.append(BlockRecord(rec.n_tokens, rec.crc, None))
            else:
                out.append(rec)
        return replace(self, blocks=out)


def _leaf_nbytes(dtype: str, shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def block_crc(payload: List[bytes]) -> int:
    """CRC32 over a block's concatenated leaf bytes."""
    crc = 0
    for part in payload:
        crc = zlib.crc32(part, crc)
    return crc & 0xFFFFFFFF


def encode_span(span: RequestSpan) -> bytes:
    """Span → wire bytes (magic + JSON header + raw block payloads)."""
    header = {
        "version": span.version,
        "block_size": span.block_size,
        "leaves": [[dt, list(sh)] for dt, sh in span.leaves],
        "cache_dtype": span.cache_dtype,
        "rid": span.rid,
        "prompt": np.asarray(span.prompt, np.int32).tolist(),
        "tokens": [int(t) for t in span.tokens],
        "fill0": span.fill0,
        "budget": span.budget,
        "temperature": span.temperature,
        "top_k": span.top_k,
        "top_p": span.top_p,
        "stop_token": span.stop_token,
        "key": np.asarray(span.key, np.uint32).tolist(),
        "deadline_s": span.deadline_s,
        "preemptions": span.preemptions,
        "prefix_hashes": [int(h) for h in span.prefix_hashes],
        "meta": span.meta,
        "blocks": [
            {
                "n": rec.n_tokens,
                "crc": rec.crc,
                "stripped": rec.payload is None,
            }
            for rec in span.blocks
        ],
    }
    enc = json.dumps(header, separators=(",", ":")).encode()
    parts = [_MAGIC, _LEN.pack(len(enc)), enc]
    for rec in span.blocks:
        if rec.payload is not None:
            parts.extend(rec.payload)
    out = b"".join(parts)
    tally.count("out", len(out))
    _emit_span_bytes("out", len(out))
    return out


def decode_span(data: bytes) -> RequestSpan:
    """Wire bytes → span, CRC-verifying every shipped block. Raises
    :class:`SpanFormatError` on malformed input, :class:`SpanCorruptError`
    on a payload whose CRC32 does not match its header record."""
    if len(data) < len(_MAGIC) + _LEN.size or not data.startswith(_MAGIC):
        raise SpanFormatError("not a KV span (bad magic)")
    off = len(_MAGIC)
    (hlen,) = _LEN.unpack_from(data, off)
    off += _LEN.size
    if len(data) < off + hlen:
        raise SpanFormatError("KV span header truncated")
    try:
        h = json.loads(data[off: off + hlen])
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise SpanFormatError(f"KV span header not valid JSON: {exc}") \
            from exc
    off += hlen
    if int(h.get("version", 0)) != SPAN_VERSION:
        raise SpanFormatError(
            f"unsupported KV span version {h.get('version')!r}"
        )
    leaves = [(str(dt), tuple(int(d) for d in sh))
              for dt, sh in h["leaves"]]
    lens = [_leaf_nbytes(dt, sh) for dt, sh in leaves]
    records: List[BlockRecord] = []
    for rec in h["blocks"]:
        if rec["stripped"]:
            records.append(BlockRecord(int(rec["n"]), int(rec["crc"])))
            continue
        payload = []
        for n in lens:
            if len(data) < off + n:
                raise SpanFormatError("KV span payload truncated")
            payload.append(data[off: off + n])
            off += n
        got = block_crc(payload)
        if got != int(rec["crc"]):
            raise SpanCorruptError(
                f"KV block payload CRC mismatch: block {len(records)} "
                f"expected {int(rec['crc']):#010x} got {got:#010x}"
            )
        records.append(BlockRecord(int(rec["n"]), int(rec["crc"]), payload))
    if off != len(data):
        raise SpanFormatError(
            f"KV span has {len(data) - off} trailing bytes"
        )
    span = RequestSpan(
        block_size=int(h["block_size"]),
        leaves=leaves,
        cache_dtype=str(h["cache_dtype"]),
        rid=int(h["rid"]),
        prompt=np.asarray(h["prompt"], np.int32),
        tokens=[int(t) for t in h["tokens"]],
        fill0=int(h["fill0"]),
        budget=int(h["budget"]),
        temperature=float(h["temperature"]),
        top_k=int(h["top_k"]),
        top_p=float(h["top_p"]),
        stop_token=(None if h["stop_token"] is None
                    else int(h["stop_token"])),
        key=np.asarray(h["key"], np.uint32),
        deadline_s=(None if h["deadline_s"] is None
                    else float(h["deadline_s"])),
        preemptions=int(h["preemptions"]),
        prefix_hashes=[int(x) for x in h["prefix_hashes"]],
        blocks=records,
        meta=dict(h.get("meta", {})),
    )
    if not span.tokens:
        raise SpanFormatError("KV span has no generated tokens")
    if len(span.blocks) != -(-span.n_kv // span.block_size):
        raise SpanFormatError(
            f"KV span block count {len(span.blocks)} does not cover "
            f"{span.n_kv} positions at block_size {span.block_size}"
        )
    tally.count("in", len(data))
    _emit_span_bytes("in", len(data))
    return span
