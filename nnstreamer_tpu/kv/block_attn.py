"""Block-native paged attention: decode/verify straight off the arena.

The gather formulation (:mod:`nnstreamer_tpu.kv.gather`) runs the paged
step as ``gather_cache`` → contiguous view → slot-layout step →
``scatter_window``: correct and bitwise-pinned, but every decode pump
materializes the full ``[L, B, max_len, ...]`` view as a donated scan
carry BESIDE the arena (a transient HBM doubling) and pays a
whole-arena scatter per step — exactly the intermediate
materialization a streaming dataflow must not pay (StreamTensor,
PAPERS.md). This module is the block-native replacement the batcher
selects by default (``ContinuousBatcher(kv_attn="auto"|"block")``):

- the attention READ takes each layer's blocks through the block table
  *inside* that layer's body (:func:`_take_layer`, one per-layer
  transient instead of an L-deep carried view) and runs the IDENTICAL
  masked-softmax expressions the gathered view ran — so block-native
  streams stay bitwise identical to the gather oracle (and hence to the
  slot layout), pinned by tests/test_kv_block_attn.py /
  tests/test_kv_paged.py;
- the WRITE is :func:`write_fresh_window`: the freshly computed K/V of
  the pending token (or verify chunk) lands in its owning arena
  block(s) with ONE scatter per leaf on the donated arena — the
  width-1 dynamic block update that replaces ``scatter_window`` on the
  decode path. Inactive lanes route to scratch block 0 carrying its
  init values (zero payload, unit scales), so scratch stays pristine
  and shared / copy-on-write blocks are never touched: the write
  window lies in blocks the request owns privately (the pool's CoW
  discipline);
- :func:`paged_attention_ref` is the per-block ONLINE-softmax jnp
  reference of the Pallas block-table kernel
  (:mod:`nnstreamer_tpu.ops.pallas.paged_attention`): one take per
  logical block, the flash recurrence across blocks, scratch and
  beyond-fill columns masked to exact zeros, the pending token's own
  column folded last (it is the highest live position, so the
  reduction order matches position order). :func:`block_attention`
  dispatches ``impl="auto"|"jnp"|"pallas"`` like the PR-12 kernels —
  the kernel on a real TPU backend, the reference elsewhere.

The admission-path ops (``write_block`` / ``read_block`` /
``copy_block`` and chunked-prefill staging) are shared with the gather
formulation and stay in :mod:`nnstreamer_tpu.kv.gather`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import dequantize_kv, quantize_kv

NEG_INF = -1e30


def _write_view(c, new, pos, gate):
    """[B, w, ...] chunk into the per-slot view at per-slot ``pos``,
    gated on active — the EXACT write expression of the slot layout's
    step/verify bodies (the bitwise-parity pin rides on this); the
    decode step is just the w=1 case."""
    written = jax.vmap(
        lambda cb, nb, p: jax.lax.dynamic_update_slice(cb, nb, (p, 0, 0))
    )(c, new.astype(c.dtype), pos)
    return jnp.where(gate, written, c)


def _write_view_scale(sc, new, pos, gate):
    written = jax.vmap(
        lambda sb, nb, p: jax.lax.dynamic_update_slice(sb, nb, (p, 0))
    )(sc, new, pos)
    return jnp.where(gate[..., 0], written, sc)


def _take_layer(layer, tables):
    """One layer's arena leaf ``[N, bs, ...]`` → the contiguous per-slot
    view ``[B, nb*bs, ...]`` through ``tables`` [B, nb] — the read half
    of ``kv.gather.gather_cache`` for a single layer, materialized
    transiently inside the layer body instead of carried (and scattered
    back) across the whole program."""
    b, nb = tables.shape
    t = jnp.take(layer, tables, axis=0)  # [B, nb, bs, ...]
    return t.reshape((b, nb * layer.shape[1]) + layer.shape[2:])


def write_fresh_window(arena, tables, fresh, pos, width: int, active,
                       quantized: bool):
    """Land freshly computed K/V straight into its owning arena blocks.

    ``fresh`` holds the per-layer stacked chunk values —
    ``(k, v)`` [L, B, width, KV, Dh] (fp) or ``(k8, ks, v8, vs)``
    (int8 payloads + [L, B, width, KV] scales) — exactly what the layer
    bodies computed and wrote into their attention views. Token column
    ``c`` of lane ``b`` goes to arena block ``tables[b, (pos+c)//bs]``
    at row ``(pos+c) % bs``: ONE scatter per arena leaf, in place under
    donation. Inactive lanes (and out-of-range columns) are routed to
    scratch block 0 and write its init values (zero payload, unit
    scales), so scratch stays pristine; active lanes' windows lie in
    privately-owned blocks (copy-on-write discipline), so shared blocks
    are untouched by construction."""
    first = arena[0][0] if quantized else arena[0]
    bs = first.shape[2]
    nb = tables.shape[1]
    p = pos[:, None] + jnp.arange(int(width), dtype=jnp.int32)[None, :]
    lb = p // bs                                    # [B, w] logical block
    off = (p % bs).reshape(-1)
    valid = active[:, None] & (lb < nb)
    phys = jnp.take_along_axis(tables, jnp.clip(lb, 0, nb - 1), axis=1)
    phys = jnp.where(valid, phys, 0).reshape(-1)
    valid = valid.reshape(-1)

    def put(a, rows, fill=0):
        # rows [L, B, w, ...] → [L, B*w, ...]; duplicate targets exist
        # only among routed-to-scratch lanes, and they all write the
        # identical fill value — deterministic whatever the scatter order
        rows = rows.reshape((rows.shape[0], -1) + rows.shape[3:])
        keep = valid.reshape((1, -1) + (1,) * (rows.ndim - 2))
        rows = jnp.where(keep, rows.astype(a.dtype),
                         jnp.asarray(fill, a.dtype))
        return a.at[:, phys, off].set(rows)

    if quantized:
        k8, ks, v8, vs = fresh
        (ka, ksc), (va, vsc) = arena
        return (
            (put(ka, k8), put(ksc, ks, 1.0)),
            (put(va, v8), put(vsc, vs, 1.0)),
        )
    k, v = fresh
    ka, va = arena
    return (put(ka, k), put(va, v))


def batched_decode_step_block(
    params,
    tok,
    pos,
    active,
    arena,
    tables,
    n_heads: int,
    compute_dtype=jnp.float32,
    attn_fn=None,
):
    """One decode step for a slot batch, directly against the block
    arena — the block-native sibling of
    ``models/serving.batched_decode_step``.

    tok/pos/active [B] as in the slot step; ``arena`` is the kv.gather
    arena tree (leaves [L, N, bs, ...]), ``tables`` [B, nb] int32 →
    (logits [B, V] f32, arena', pos'). Per layer, the attention view is
    taken through the tables and the pending token's K/V is written into
    it with the EXACT expressions the gathered path used — bitwise
    parity with the gather oracle by construction — while the arena
    write itself is deferred to one :func:`write_fresh_window` scatter
    after the layer scan (in place under donation; no ``scatter_window``,
    no carried view). ``attn_fn(q, k_entry, v_entry, tables, pos,
    (fresh_k, fresh_v)) -> [B,1,H,Dh]`` overrides the inline read with a
    block-table kernel (ops/pallas/paged_attention.py) that never
    materializes the view at all."""
    quantized = isinstance(arena[0], tuple)
    first = arena[0][0] if quantized else arena[0]
    bs_blk = first.shape[2]
    max_len = tables.shape[1] * bs_blk
    x = tfm.embed_lookup(params["embed"], tok, compute_dtype)[:, None, :]
    gate = active[:, None, None, None]

    def write(c, new):
        return _write_view(c, new, pos, gate)

    def write_scale(sc, new):
        return _write_view_scale(sc, new, pos, gate)

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ka, ksc, va, vsc = layer
        else:
            blk, ka, va = layer
        bsz, _, d = x.shape
        q, k, v = tfm.block_qkv(x, blk, n_heads, pos[:, None])
        if quantized:
            k8, ks = quantize_kv(k)
            v8, vs = quantize_kv(v)
            fresh = (k8, ks, v8, vs)
            if attn_fn is None:
                ck = dequantize_kv(
                    write(_take_layer(ka, tables), k8),
                    write_scale(_take_layer(ksc, tables), ks),
                )
                cv = dequantize_kv(
                    write(_take_layer(va, tables), v8),
                    write_scale(_take_layer(vsc, tables), vs),
                )
                o = None
            else:
                o = attn_fn(
                    q, (ka, ksc), (va, vsc), tables, pos,
                    (dequantize_kv(k8, ks), dequantize_kv(v8, vs)),
                )
        else:
            fresh = (k, v)
            if attn_fn is None:
                ck = write(_take_layer(ka, tables), k)
                cv = write(_take_layer(va, tables), v)
                o = None
            else:
                o = attn_fn(q, ka, va, tables, pos, (k, v))
        if o is None:
            mask = jnp.arange(max_len)[None, :] <= pos[:, None]
            o = tfm.cache_attention(q, ck, cv, mask[:, None, :])
        o = o.astype(x.dtype).reshape(bsz, 1, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, fresh

    if quantized:
        (ka, ksc), (va, vsc) = arena
        xs = (params["blocks"], ka, ksc, va, vsc)
    else:
        xs = (params["blocks"],) + tuple(arena)
    x, fresh_layers = jax.lax.scan(body, x, xs)
    arena = write_fresh_window(
        arena, tables, fresh_layers, pos, 1, active, quantized
    )
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)[:, 0]
    return logits, arena, pos + active.astype(jnp.int32)


def batched_verify_step_block(
    params,
    toks,
    pos,
    active,
    arena,
    tables,
    n_heads: int,
    compute_dtype=jnp.float32,
):
    """Score per-slot k-token candidate chunks in one forward against
    the block arena — the block-native sibling of
    ``models/serving.batched_verify_step`` (same chunk-write-then-mask
    invariant: rejected positions are overwritten by a later round
    before any mask can reach them). toks [B, k] → (logits [B, k, V]
    f32, arena'). Attention reads ride the per-layer take; the chunk's
    K/V lands via one :func:`write_fresh_window` scatter (≤ k columns,
    each in its privately-owned block). Caller guarantees pos + k ≤
    max_len for active lanes, exactly as for the slot verify."""
    quantized = isinstance(arena[0], tuple)
    first = arena[0][0] if quantized else arena[0]
    bs_blk = first.shape[2]
    max_len = tables.shape[1] * bs_blk
    b, k = toks.shape
    x = tfm.embed_lookup(params["embed"], toks, compute_dtype)  # [B,k,D]
    positions = pos[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    gate = active[:, None, None, None]

    def write_chunk(c, new):
        return _write_view(c, new, pos, gate)

    def write_scale_chunk(sc, new):
        return _write_view_scale(sc, new, pos, gate)

    mask = (
        jnp.arange(max_len)[None, None, :] <= positions[:, :, None]
    )  # [B, k, max_len]

    def body(carry, layer):
        x = carry
        if quantized:
            blk, ka, ksc, va, vsc = layer
        else:
            blk, ka, va = layer
        bsz = x.shape[0]
        q, kk, v = tfm.block_qkv(x, blk, n_heads, positions)
        if quantized:
            k8, ks = quantize_kv(kk)
            v8, vs = quantize_kv(v)
            fresh = (k8, ks, v8, vs)
            ck = dequantize_kv(
                write_chunk(_take_layer(ka, tables), k8),
                write_scale_chunk(_take_layer(ksc, tables), ks),
            )
            cv = dequantize_kv(
                write_chunk(_take_layer(va, tables), v8),
                write_scale_chunk(_take_layer(vsc, tables), vs),
            )
        else:
            fresh = (kk, v)
            ck = write_chunk(_take_layer(ka, tables), kk)
            cv = write_chunk(_take_layer(va, tables), v)
        o = tfm.cache_attention(q, ck, cv, mask)
        o = o.astype(x.dtype).reshape(bsz, k, -1)
        x = x + o @ tfm.wt(blk["wo"], x.dtype)
        x = tfm.block_ffn(x, blk)
        return x, fresh

    if quantized:
        (ka, ksc), (va, vsc) = arena
        xs = (params["blocks"], ka, ksc, va, vsc)
    else:
        xs = (params["blocks"],) + tuple(arena)
    x, fresh_layers = jax.lax.scan(body, x, xs)
    arena = write_fresh_window(
        arena, tables, fresh_layers, pos, k, active, quantized
    )
    x = tfm.rmsnorm(x, params["ln_f"])
    logits = (x @ tfm.wt(params["head"], x.dtype)).astype(jnp.float32)
    return logits, arena


def paged_attention_ref(q, ck, cv, tables, pos, fresh_kv,
                        k_scale=None, v_scale=None,
                        scale: Optional[float] = None):
    """jnp online-softmax reference of the Pallas block-table kernel.

    q [B,1,H,Dh]; ck/cv [N, bs, KV, Dh] arena leaves (int8 with
    ``k_scale``/``v_scale`` [N, bs, KV]); tables [B, nb]; pos [B] is
    the HISTORY length (positions 0..pos-1 live in blocks);
    ``fresh_kv = (fk, fv)`` [B,1,KV,Dh] is the pending token's K/V,
    folded LAST (it is position pos, the highest live column, so the
    per-block reduction order equals position order). One take per
    logical block, the flash recurrence across blocks; scratch-mapped
    and beyond-fill columns get softmax weight EXACTLY zero (and their
    V rows are zeroed before the weighted sum), so arbitrary scratch
    content can never leak into the output."""
    b, _, h, hd = q.shape
    n_kv = ck.shape[2]
    g = h // n_kv
    bs = ck.shape[1]
    nb = tables.shape[1]
    sc = scale if scale is not None else 1.0 / (hd ** 0.5)
    fk, fv = fresh_kv
    # GQA folding as in tfm.cache_attention: query heads group over the
    # compact KV heads, no repeat_kv expansion
    q5 = q.astype(jnp.float32)[:, 0].reshape(b, n_kv, g, hd)
    m = jnp.full((b, n_kv, g), NEG_INF, jnp.float32)
    l = jnp.zeros((b, n_kv, g), jnp.float32)
    acc = jnp.zeros((b, n_kv, g, hd), jnp.float32)
    hist = jnp.minimum(pos, nb * bs)
    for kb in range(nb):
        phys = tables[:, kb]
        kblk = jnp.take(ck, phys, axis=0).astype(jnp.float32)  # [B,bs,KV,hd]
        vblk = jnp.take(cv, phys, axis=0).astype(jnp.float32)
        if k_scale is not None:
            kblk = kblk * jnp.take(k_scale, phys, axis=0)[..., None]
            vblk = vblk * jnp.take(v_scale, phys, axis=0)[..., None]
        s = jnp.einsum("bkgd,bskd->bkgs", q5, kblk) * sc  # [B,KV,g,bs]
        cols = kb * bs + jnp.arange(bs, dtype=jnp.int32)
        live = cols[None, :] < hist[:, None]               # [B, bs]
        s = jnp.where(live[:, None, None, :], s, NEG_INF)
        vblk = jnp.where(live[:, :, None, None], vblk, 0.0)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_new))
        p = jnp.where(
            m_new[..., None] <= NEG_INF, 0.0, jnp.exp(s - m_new[..., None])
        )
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bkgs,bskd->bkgd", p, vblk)
        m = m_new
    fkf = fk.astype(jnp.float32)[:, 0]  # [B, KV, hd]
    fvf = fv.astype(jnp.float32)[:, 0]
    s1 = jnp.einsum("bkgd,bkd->bkg", q5, fkf) * sc
    m_new = jnp.maximum(m, s1)
    alpha = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - m_new))
    p1 = jnp.exp(s1 - m_new)  # the fresh column is always live
    l = l * alpha + p1
    acc = acc * alpha[..., None] + p1[..., None] * fvf[:, :, None, :]
    l2 = l[..., None]
    o = jnp.where(l2 > 0, acc / jnp.maximum(l2, 1e-30), 0.0)
    return o.reshape(b, 1, h, hd)


def block_attention(q, cache_k, cache_v, tables, pos, fresh_kv,
                    impl: str = "auto", interpret: Optional[bool] = None):
    """Block-table decode attention with PR-12-style impl dispatch:
    ``impl="auto"`` runs the Pallas kernel
    (ops/pallas/paged_attention.py) on a real TPU backend and
    :func:`paged_attention_ref` elsewhere; ``"pallas"`` forces the
    kernel (interpret-mode off-TPU), ``"jnp"`` forces the reference.
    ``cache_k``/``cache_v`` are arena layer leaves — ``[N, bs, KV, Dh]``
    float, or ``(int8 payload, [N, bs, KV] scales)`` tuples."""
    if impl not in ("auto", "jnp", "pallas"):
        raise ValueError(f"block_attention impl {impl!r} not auto/jnp/pallas")
    from nnstreamer_tpu.ops.dispatch import record as _record_dispatch
    from nnstreamer_tpu.ops.pallas._compat import pallas_ok

    use_pallas = impl == "pallas" or (
        impl == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas:
        # registry dtype gate: an unsupported arena dtype degrades to
        # the jnp reference with a logged reason
        payload = cache_k[0] if isinstance(cache_k, tuple) else cache_k
        use_pallas, _ = pallas_ok("paged_decode_attention", payload.dtype)
    _record_dispatch("block_attention", "pallas" if use_pallas else "jnp")
    if use_pallas:
        from nnstreamer_tpu.ops.pallas.paged_attention import (
            make_paged_attention,
        )

        return make_paged_attention(interpret=interpret)(
            q, cache_k, cache_v, tables, pos, fresh_kv
        )
    if isinstance(cache_k, tuple):
        (k8, ks), (v8, vs) = cache_k, cache_v
        return paged_attention_ref(
            q, k8, v8, tables, pos, fresh_kv, k_scale=ks, v_scale=vs
        )
    return paged_attention_ref(q, cache_k, cache_v, tables, pos, fresh_kv)
