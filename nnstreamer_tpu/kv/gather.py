"""Jitted block-table gather/scatter: the paged cache's device read/write.

The paged step/pump/spec programs run the SAME attention math as the
contiguous slot layout (models/serving.batched_decode_step and friends)
— the only difference is where the cache bytes live:

- :func:`gather_cache` materializes, inside the program, a per-slot
  contiguous view ``[L, B, max_len, ...]`` from the block arena
  ``[L, N, bs, ...]`` through the block table ``[B, max_len//bs]``.
  Logical token position ``p`` lands at view column ``p`` exactly as in
  the slot cache, so masks, RoPE positions and reduction orders are
  identical — the bitwise-parity invariant tests/test_kv_paged.py pins.
  Unallocated table entries point at scratch block 0; their columns are
  masked (``> pos``) so they contribute exact zeros, same as the slot
  cache's never-written tail.
- :func:`scatter_window` writes the updated view's touched blocks back:
  a ``width``-token write starting at per-slot ``pos`` spans at most
  ``(width + bs - 2)//bs + 1`` blocks — a static, small unrolled loop.
  Inactive lanes are routed to scratch with their unchanged content, so
  shared (read-only) blocks are never scattered by construction: the
  write window always lies in blocks the owning request holds privately
  (the pool's copy-on-write discipline).

Host-path helpers (:func:`write_block_fn`, :func:`read_block_fn`,
:func:`copy_block_fn`) build the admission-time ops: stage→block
scatter (quantizing when the arena is int8, exactly like the slot
layout's insert_slot), block→stage gather for prefix-seeded prefill,
and the device side of copy-on-write.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models.serving import dequantize_kv, quantize_kv


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def gather_cache(arena, tables):
    """arena leaves [L, N, bs, ...] → contiguous view [L, B, nb*bs, ...]
    through ``tables`` [B, nb] int32 (works for the fp ``(k, v)`` tree
    and the int8 ``((k8, ksc), (v8, vsc))`` tree alike)."""
    b, nb = tables.shape

    def g(a):
        t = jnp.take(a, tables, axis=1)  # [L, B, nb, bs, ...]
        return t.reshape((a.shape[0], b, nb * a.shape[2]) + a.shape[3:])

    return _tree_map(g, arena)


def scatter_window(arena, tables, view, pos, width: int, active):
    """Write the ``[pos, pos+width)`` token window of the updated
    contiguous ``view`` back into the arena blocks the tables map.

    ``width`` is static (1 for a decode step, k for a verify chunk); the
    write can straddle at most ``(width + bs - 2)//bs + 1`` blocks, each
    handled by one unrolled scatter. Inactive slots (and out-of-range
    block indices) are routed to scratch block 0 carrying its own
    unchanged content — a no-op write, duplicate-index-safe because
    every duplicate writes identical bytes."""
    first = jax.tree_util.tree_leaves(arena)[0]
    blk = first.shape[2]
    b, nb = tables.shape
    nblk = (int(width) + blk - 2) // blk + 1
    base = pos // blk

    for j in range(nblk):
        lb = base + j  # [B] logical block this unroll writes
        safe = jnp.clip(lb, 0, nb - 1)
        valid = active & (lb * blk < pos + width) & (lb < nb)
        phys = jnp.take_along_axis(tables, safe[:, None], axis=1)[:, 0]
        phys = jnp.where(valid, phys, 0)
        start = safe * blk

        def put(a, v, phys=phys, valid=valid, start=start):
            # v [L, B, T, ...] → the block-wide rows [L, B, bs, ...]
            def one(vb, s):
                return jax.lax.dynamic_slice_in_dim(vb, s, blk, axis=1)

            rows = jax.vmap(one, in_axes=(1, 0), out_axes=1)(v, start)
            old = jnp.take(a, phys, axis=1)
            keep = valid.reshape((1, b) + (1,) * (old.ndim - 2))
            return a.at[:, phys].set(
                jnp.where(keep, rows.astype(a.dtype), old)
            )

        arena = _tree_map(put, arena, view)
    return arena


def make_paged_ops(quantized: bool, compute_dtype):
    """Admission-path jitted ops over one arena layout.

    Returns ``(write_block, read_block, copy_block)``:

    - ``write_block(arena, blk, ks, vs)`` — land one block of staged
      K/V (``[L, 1, bs, KV, Dh]`` compute dtype) at arena block ``blk``,
      quantizing per token per head when the arena is int8 (the same
      quantize_kv the slot layout's insert_slot applies, so paged and
      slot int8 payloads are bitwise identical);
    - ``read_block(arena, blk)`` — one block back as compute-dtype
      ``(ks, vs)`` (dequantized when int8): the prefix-seeded prefill
      stage source;
    - ``copy_block(arena, src, dst)`` — the device half of
      copy-on-write.
    """

    def write_block(arena, blk, ks, vs):
        if quantized:
            (ka, ksc), (va, vsc) = arena
            k8, ks_ = quantize_kv(ks)
            v8, vs_ = quantize_kv(vs)
            return (
                (ka.at[:, blk].set(k8[:, 0]), ksc.at[:, blk].set(ks_[:, 0])),
                (va.at[:, blk].set(v8[:, 0]), vsc.at[:, blk].set(vs_[:, 0])),
            )
        ka, va = arena
        return (
            ka.at[:, blk].set(ks[:, 0].astype(ka.dtype)),
            va.at[:, blk].set(vs[:, 0].astype(va.dtype)),
        )

    def read_block(arena, blk):
        if quantized:
            (ka, ksc), (va, vsc) = arena
            ks = dequantize_kv(ka[:, blk], ksc[:, blk])
            vs = dequantize_kv(va[:, blk], vsc[:, blk])
        else:
            ka, va = arena
            ks, vs = ka[:, blk], va[:, blk]
        return (
            ks.astype(compute_dtype)[:, None],
            vs.astype(compute_dtype)[:, None],
        )

    def copy_block(arena, src, dst):
        return _tree_map(lambda a: a.at[:, dst].set(a[:, src]), arena)

    return (
        jax.jit(write_block, donate_argnums=0),
        jax.jit(read_block),
        jax.jit(copy_block, donate_argnums=0),
    )


def init_arena(n_layers: int, n_blocks: int, block_size: int, kv: int,
               hd: int, quantized: bool, compute_dtype):
    """Zeroed arena tree (+1 scratch block at index 0), mirroring the
    slot cache's init values: int8 payloads zero with unit scales, fp
    zeros — so scratch/unwritten columns are finite and masked columns
    contribute exact zeros either way."""
    shape = (n_layers, n_blocks + 1, block_size, kv, hd)
    if quantized:
        sshape = shape[:-1]
        return (
            (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
            (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
        )
    return (
        jnp.zeros(shape, compute_dtype),
        jnp.zeros(shape, compute_dtype),
    )
