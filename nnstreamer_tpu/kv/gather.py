"""Jitted block-table gather/scatter: the paged cache's admission ops
and the gather-formulation decode oracle.

Since the block-native path landed (:mod:`nnstreamer_tpu.kv.block_attn`,
``ContinuousBatcher(kv_attn="auto"|"block")`` — the default), the
gather/scatter pair below serves the DECODE plane only as the
debug/parity oracle behind ``kv_attn="gather"``: bitwise identical
streams, but every step materializes the full contiguous view beside
the arena (a transient HBM doubling — forcing it on a bounded chip is
what nns-lint NNS-W117 warns about) and pays a whole-arena scatter.
The admission-path helpers at the bottom (block write/read/copy,
arena init) are shared by BOTH formulations.

Under ``kv_attn="gather"`` the step/pump/spec programs run the SAME
attention math as the contiguous slot layout
(models/serving.batched_decode_step and friends) — the only difference
is where the cache bytes live:

- :func:`gather_cache` materializes, inside the program, a per-slot
  contiguous view ``[L, B, max_len, ...]`` from the block arena
  ``[L, N, bs, ...]`` through the block table ``[B, max_len//bs]``.
  Logical token position ``p`` lands at view column ``p`` exactly as in
  the slot cache, so masks, RoPE positions and reduction orders are
  identical — the bitwise-parity invariant tests/test_kv_paged.py pins.
  Unallocated table entries point at scratch block 0; their columns are
  masked (``> pos``) so they contribute exact zeros, same as the slot
  cache's never-written tail.
- :func:`scatter_window` writes the updated view's touched blocks back:
  a ``width``-token write starting at per-slot ``pos`` spans at most
  ``(width + bs - 2)//bs + 1`` blocks — a static, small unrolled loop.
  Inactive lanes are routed to scratch with their unchanged content, so
  shared (read-only) blocks are never scattered by construction: the
  write window always lies in blocks the owning request holds privately
  (the pool's copy-on-write discipline).

Host-path helpers (:func:`write_block_fn`, :func:`read_block_fn`,
:func:`copy_block_fn`) build the admission-time ops: stage→block
scatter (quantizing when the arena is int8, exactly like the slot
layout's insert_slot), block→stage gather for prefix-seeded prefill,
and the device side of copy-on-write.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models.serving import dequantize_kv, quantize_kv


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def gather_cache(arena, tables):
    """arena leaves [L, N, bs, ...] → contiguous view [L, B, nb*bs, ...]
    through ``tables`` [B, nb] int32 (works for the fp ``(k, v)`` tree
    and the int8 ``((k8, ksc), (v8, vsc))`` tree alike)."""
    b, nb = tables.shape

    def g(a):
        t = jnp.take(a, tables, axis=1)  # [L, B, nb, bs, ...]
        return t.reshape((a.shape[0], b, nb * a.shape[2]) + a.shape[3:])

    return _tree_map(g, arena)


def scatter_window(arena, tables, view, pos, width: int, active):
    """Write the ``[pos, pos+width)`` token window of the updated
    contiguous ``view`` back into the arena blocks the tables map.

    ``width`` is static (1 for a decode step, k for a verify chunk); the
    write can straddle at most ``(width + bs - 2)//bs + 1`` blocks, each
    handled by one unrolled scatter. Inactive slots (and out-of-range
    block indices) are routed to scratch block 0 carrying its own
    unchanged content — a no-op write, duplicate-index-safe because
    every duplicate writes identical bytes."""
    first = jax.tree_util.tree_leaves(arena)[0]
    blk = first.shape[2]
    b, nb = tables.shape
    nblk = (int(width) + blk - 2) // blk + 1
    base = pos // blk

    for j in range(nblk):
        lb = base + j  # [B] logical block this unroll writes
        safe = jnp.clip(lb, 0, nb - 1)
        valid = active & (lb * blk < pos + width) & (lb < nb)
        phys = jnp.take_along_axis(tables, safe[:, None], axis=1)[:, 0]
        phys = jnp.where(valid, phys, 0)
        start = safe * blk

        def put(a, v, phys=phys, valid=valid, start=start):
            # v [L, B, T, ...] → the block-wide rows [L, B, bs, ...]
            def one(vb, s):
                return jax.lax.dynamic_slice_in_dim(vb, s, blk, axis=1)

            rows = jax.vmap(one, in_axes=(1, 0), out_axes=1)(v, start)
            old = jnp.take(a, phys, axis=1)
            keep = valid.reshape((1, b) + (1,) * (old.ndim - 2))
            return a.at[:, phys].set(
                jnp.where(keep, rows.astype(a.dtype), old)
            )

        arena = _tree_map(put, arena, view)
    return arena


def make_paged_ops(quantized: bool, compute_dtype):
    """Admission-path jitted ops over one arena layout.

    Returns ``(write_block, read_block, copy_block)``:

    - ``write_block(arena, blk, ks, vs)`` — land one block of staged
      K/V (``[L, 1, bs, KV, Dh]`` compute dtype) at arena block ``blk``,
      quantizing per token per head when the arena is int8 (the same
      quantize_kv the slot layout's insert_slot applies, so paged and
      slot int8 payloads are bitwise identical);
    - ``read_block(arena, blk)`` — one block back as compute-dtype
      ``(ks, vs)`` (dequantized when int8): the prefix-seeded prefill
      stage source;
    - ``copy_block(arena, src, dst)`` — the device half of
      copy-on-write.
    """

    def write_block(arena, blk, ks, vs):
        if quantized:
            (ka, ksc), (va, vsc) = arena
            k8, ks_ = quantize_kv(ks)
            v8, vs_ = quantize_kv(vs)
            return (
                (ka.at[:, blk].set(k8[:, 0]), ksc.at[:, blk].set(ks_[:, 0])),
                (va.at[:, blk].set(v8[:, 0]), vsc.at[:, blk].set(vs_[:, 0])),
            )
        ka, va = arena
        return (
            ka.at[:, blk].set(ks[:, 0].astype(ka.dtype)),
            va.at[:, blk].set(vs[:, 0].astype(va.dtype)),
        )

    def read_block(arena, blk):
        if quantized:
            (ka, ksc), (va, vsc) = arena
            ks = dequantize_kv(ka[:, blk], ksc[:, blk])
            vs = dequantize_kv(va[:, blk], vsc[:, blk])
        else:
            ka, va = arena
            ks, vs = ka[:, blk], va[:, blk]
        return (
            ks.astype(compute_dtype)[:, None],
            vs.astype(compute_dtype)[:, None],
        )

    def copy_block(arena, src, dst):
        return _tree_map(lambda a: a.at[:, dst].set(a[:, src]), arena)

    return (
        jax.jit(write_block, donate_argnums=0),
        jax.jit(read_block),
        jax.jit(copy_block, donate_argnums=0),
    )


def make_staging_ops(quantized: bool, compute_dtype):
    """Coalesced admission staging: ONE program per direction instead
    of one :func:`make_paged_ops` call per block.

    Returns ``(seed_stage, land_stage)`` over a chunked-prefill stage;
    the stage's block count rides in ``ids.shape[0]`` (the caller
    passes one id slot per stage block — a bucket-wide fast-path stage
    and the full chunked stage each compile once):

    - ``seed_stage(arena, stage, ids, n_seed)`` — read arena blocks
      ``ids[:n_seed]`` (dequantized when int8) into the stage's leading
      columns in one launch: the prefix-seeded prefill source
      (replaces a ``read_block`` + two dynamic-update launches per
      matched block);
    - ``land_stage(arena, stage, ids, valid)`` — write every stage
      block ``i`` with ``valid[i]`` to arena block ``ids[i]``
      (quantizing when int8 — per token per head, so slicing per block
      first would change nothing) in one launch; invalid lanes route
      to scratch block 0 carrying its init values (zero payload, unit
      scales), so scratch stays pristine. Replaces a ``write_block``
      launch per landed block.

    Values are bitwise the per-block ops' — only the dispatch count
    changes (the paged admission path used to cost ~2 launches per
    block of prompt, a real tax on the `bench.py --pipeline llm`
    equal-occupancy cell)."""

    def seed_stage(arena, stage, ids, n_seed):
        S = ids.shape[0]
        if quantized:
            (ka, ksc), (va, vsc) = arena

            def taken(pay, sc):
                t = jnp.take(pay, ids, axis=1)   # [L, S, bs, KV, Dh]
                s = jnp.take(sc, ids, axis=1)    # [L, S, bs, KV]
                return dequantize_kv(t, s)
            tk, tv = taken(ka, ksc), taken(va, vsc)
        else:
            ka, va = arena
            tk = jnp.take(ka, ids, axis=1)
            tv = jnp.take(va, ids, axis=1)
        bs = tk.shape[2]

        def place(t, sleaf):
            flat = t.reshape(
                (t.shape[0], 1, S * bs) + t.shape[3:]
            ).astype(sleaf.dtype)
            cols = jnp.arange(S * bs, dtype=jnp.int32)
            keep = (cols < n_seed * bs).reshape(
                (1, 1, S * bs) + (1,) * (sleaf.ndim - 3)
            )
            return jnp.where(keep, flat, sleaf)

        return place(tk, stage[0]), place(tv, stage[1])

    def land_stage(arena, stage, ids, valid):
        S = ids.shape[0]
        ks, vs = stage  # [L, 1, S*bs, KV, Dh] compute dtype

        def rows_of(s):
            return s.reshape((s.shape[0], S, -1) + s.shape[3:])

        def put(a, rows, fill=0):
            keep = valid.reshape((1, S) + (1,) * (rows.ndim - 2))
            rows = jnp.where(keep, rows.astype(a.dtype),
                             jnp.asarray(fill, a.dtype))
            return a.at[:, ids].set(rows)

        if quantized:
            (ka, ksc), (va, vsc) = arena
            k8, ksn = quantize_kv(ks)
            v8, vsn = quantize_kv(vs)
            return (
                (put(ka, rows_of(k8)), put(ksc, rows_of(ksn), 1.0)),
                (put(va, rows_of(v8)), put(vsc, rows_of(vsn), 1.0)),
            )
        ka, va = arena
        return (put(ka, rows_of(ks)), put(va, rows_of(vs)))

    return (
        jax.jit(seed_stage, donate_argnums=1),
        jax.jit(land_stage, donate_argnums=0),
    )


def init_arena(n_layers: int, n_blocks: int, block_size: int, kv: int,
               hd: int, quantized: bool, compute_dtype):
    """Zeroed arena tree (+1 scratch block at index 0), mirroring the
    slot cache's init values: int8 payloads zero with unit scales, fp
    zeros — so scratch/unwritten columns are finite and masked columns
    contribute exact zeros either way."""
    shape = (n_layers, n_blocks + 1, block_size, kv, hd)
    if quantized:
        sshape = shape[:-1]
        return (
            (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
            (jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.float32)),
        )
    return (
        jnp.zeros(shape, compute_dtype),
        jnp.zeros(shape, compute_dtype),
    )
