"""BlockPool: ref-counted fixed-size KV blocks in one device arena.

The paged layout stores every request's K/V in ``block_size``-token
blocks carved from a single device-resident arena per layer —
``[L, n_blocks+1, block_size, KV, Dh]`` (block 0 is a reserved scratch
block: unallocated block-table entries and inactive write lanes point at
it, so gathers stay static-shaped and scatters never need a branch).

Host-side the pool tracks, per block: a reference count (how many live
requests map it), whether it is registered in the **prefix index**, and
two reclaim tiers — ``free`` (unreferenced, unindexed) and ``cached``
(unreferenced but still indexed: its content can still be adopted by a
future request with the same prompt prefix, so it is reclaimed LRU-last,
vLLM-style automatic prefix caching).

Prefix sharing: every admitted prompt registers its block-aligned
prefixes under a rolling hash (CRC32 chained block by block, token
content stored for collision-proof verification). A later request whose
prompt starts with the same tokens adopts the matched physical blocks —
full blocks by refcount (read-only share), a final partial block by
**copy-on-write** (:meth:`BlockPool.cow`): the adopter gets a fresh
private copy it may extend, the registered original stays pristine.

The pool is host bookkeeping only; the jitted device ops (gather /
scatter / block write / copy) live in :mod:`nnstreamer_tpu.kv.gather`.
Callers (the batcher) serialize access under their own state lock.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class NoBlocksError(RuntimeError):
    """The pool has no free or reclaimable block left. The batcher's
    answer is preemption-by-eviction (free a victim request's blocks and
    re-prefill it later from whatever prefix survived), never an OOM."""


class PoolCapacityError(ValueError):
    """A snapshot needs more blocks than this pool has (``kv_blocks``
    shrank across a restart). Raised BEFORE any state mutates, so the
    restoring batcher's arena stays intact; ``evictable`` names what the
    snapshot could shed to fit — cached-tier prefix blocks (reclaimable
    without touching a live request) and registered-prefix pins."""

    def __init__(self, msg: str, needed: int, have: int,
                 evictable=None) -> None:
        super().__init__(msg)
        self.needed = int(needed)
        self.have = int(have)
        self.evictable = list(evictable or [])


def roll_hash(prev: int, tokens: np.ndarray) -> int:
    """Rolling block hash: CRC32 of the block's token bytes chained on
    the previous boundary's hash — one int per block boundary, cheap to
    extend, verified against stored tokens on every match (a collision
    can never adopt wrong K/V)."""
    return zlib.crc32(np.ascontiguousarray(tokens, np.int32).tobytes(),
                      prev & 0xFFFFFFFF)


@dataclass
class _IndexEntry:
    """One registered prefix boundary: ``block`` holds the K/V of
    ``tokens`` (len ≤ block_size; < block_size marks a partial entry
    adoptable only via copy-on-write)."""

    block: int
    tokens: np.ndarray
    parent: int  # rolling hash at the previous boundary
    partial: bool = False


@dataclass
class _Match:
    """Longest indexed prefix of a prompt: ``full`` blocks adoptable by
    refcount, plus an optional partial boundary block (CoW)."""

    n_tokens: int = 0
    full: List[int] = field(default_factory=list)
    partial_block: Optional[int] = None
    n_partial: int = 0


class BlockPool:
    """Host accounting for ``n_blocks`` usable blocks (+ scratch 0).

    ``obs_registry`` (optional MetricsRegistry) receives the
    ``nns_kv_blocks_in_use`` gauge and ``nns_kv_prefix_hits_total``
    counter; resolved once by the batcher at construction like every
    other emitter."""

    def __init__(self, n_blocks: int, block_size: int, obs_registry=None):
        if n_blocks < 1:
            raise ValueError("BlockPool needs at least one usable block")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        # block ids 1..n_blocks are usable; 0 is the scratch block
        self._refcount = np.zeros(self.n_blocks + 1, np.int32)
        self._free: deque = deque(range(1, self.n_blocks + 1))
        # refcount-0 blocks still serving the prefix index, LRU order
        # (oldest reclaimed first); value unused
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        self._index: Dict[int, _IndexEntry] = {}
        self._partials: Dict[int, List[int]] = {}  # parent hash → hashes
        self._block_hashes: Dict[int, List[int]] = {}  # block → its keys
        self.prefix_hits = 0      # blocks adopted instead of re-prefilled
        self.prefix_hit_tokens = 0
        self.cow_copies = 0
        self._obs = obs_registry

    # -- capacity ----------------------------------------------------------
    def available(self) -> int:
        """Blocks allocatable right now (free + reclaimable cached)."""
        return len(self._free) + len(self._cached)

    def in_use(self) -> int:
        return self.n_blocks - self.available()

    def _emit_in_use(self) -> None:
        if self._obs is not None:
            self._obs.gauge("nns_kv_blocks_in_use").set(float(self.in_use()))

    # -- alloc / free ------------------------------------------------------
    def alloc(self, n: int = 1) -> List[int]:
        """Claim ``n`` blocks (refcount 1 each). Reclaims cached prefix
        blocks LRU-first when the free list runs dry; raises
        :class:`NoBlocksError` (after returning nothing) when even those
        are exhausted — all-or-nothing, so a failed multi-block claim
        never leaks."""
        got: List[int] = []
        try:
            for _ in range(int(n)):
                if self._free:
                    b = self._free.popleft()
                elif self._cached:
                    b, _ = self._cached.popitem(last=False)
                    self._unindex_block(b)
                else:
                    raise NoBlocksError(
                        f"kv pool exhausted: {self.n_blocks} blocks all "
                        "referenced (preempt a request or grow kv_blocks)"
                    )
                self._refcount[b] = 1
                got.append(b)
        except NoBlocksError:
            for b in got:
                self._refcount[b] = 0
                self._free.appendleft(b)
            raise
        self._emit_in_use()
        return got

    def adopt(self, block: int) -> None:
        """Share an indexed block read-only (prefix hit): bump its
        refcount, pulling it out of the cached tier if idle."""
        if self._refcount[block] == 0:
            self._cached.pop(block, None)
        self._refcount[block] += 1
        self.prefix_hits += 1
        if self._obs is not None:
            self._obs.counter("nns_kv_prefix_hits_total").inc()
        self._emit_in_use()

    def free(self, blocks) -> None:
        """Drop one reference per block; refcount-0 blocks return to the
        free list, or to the cached LRU tier while the prefix index
        still maps them (their content stays adoptable)."""
        for b in blocks:
            if b == 0:
                continue  # scratch is never owned
            if self._refcount[b] <= 0:
                raise ValueError(f"double free of kv block {b}")
            self._refcount[b] -= 1
            if self._refcount[b] == 0:
                if self._block_hashes.get(b):
                    self._cached[b] = None
                    self._cached.move_to_end(b)
                else:
                    self._free.append(b)
        self._emit_in_use()

    def cow(self) -> int:
        """Claim a fresh block for a copy-on-write of a shared partial
        block (the device copy itself is the caller's
        :func:`~nnstreamer_tpu.kv.gather` scatter). Counted so the
        bench/tests can see sharing degrade into copies."""
        (b,) = self.alloc(1)
        self.note_cow()
        return b

    def note_cow(self) -> None:
        """Count a copy-on-write whose block came from a bulk alloc."""
        self.cow_copies += 1

    # -- prefix index ------------------------------------------------------
    def _unindex_block(self, block: int) -> None:
        for h in self._block_hashes.pop(block, []):
            e = self._index.pop(h, None)
            if e is not None and e.partial:
                sibs = self._partials.get(e.parent)
                if sibs is not None:
                    try:
                        sibs.remove(h)
                    except ValueError:
                        pass
                    if not sibs:
                        self._partials.pop(e.parent, None)

    def register(self, tokens: np.ndarray, blocks: List[int]) -> None:
        """Index a prompt's blocks under their rolling prefix hashes:
        one entry per full block boundary (read-only shareable) plus one
        for the trailing partial block, if any (CoW-shareable). Already-
        indexed boundaries (the matched prefix itself) are skipped."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        h = 0
        for i, b in enumerate(blocks):
            chunk = tokens[i * bs: (i + 1) * bs]
            if chunk.size == 0:
                break
            nh = roll_hash(h, chunk)
            partial = chunk.size < bs
            e = self._index.get(nh)
            if e is not None and not np.array_equal(e.tokens, chunk):
                # hash collision: keep the incumbent (match() verifies
                # token content, so the incumbent is never wrong for its
                # own prefix) and stop chaining — deeper entries would
                # be unreachable through a broken link anyway
                break
            if e is None:
                self._index[nh] = _IndexEntry(b, chunk.copy(), h, partial)
                self._block_hashes.setdefault(b, []).append(nh)
                if partial:
                    self._partials.setdefault(h, []).append(nh)
            if partial:
                break
            h = nh

    def match(self, tokens: np.ndarray) -> _Match:
        """Longest registered prefix of ``tokens``: walks the rolling
        hash block by block verifying token content, then tries the
        partial entries hanging off the last matched boundary. Does NOT
        take references — callers adopt()/cow() what they decide to
        use."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        m = _Match()
        h = 0
        i = 0
        while (i + 1) * bs <= tokens.shape[0]:
            chunk = tokens[i * bs: (i + 1) * bs]
            nh = roll_hash(h, chunk)
            e = self._index.get(nh)
            if e is None or e.partial or not np.array_equal(e.tokens, chunk):
                break
            m.full.append(e.block)
            m.n_tokens += bs
            h = nh
            i += 1
        best: Optional[_IndexEntry] = None
        rest = tokens[m.n_tokens:]
        for ph in self._partials.get(h, []):
            e = self._index.get(ph)
            if e is None:
                continue
            n = e.tokens.shape[0]
            if n <= rest.shape[0] and np.array_equal(e.tokens, rest[:n]):
                if best is None or n > best.tokens.shape[0]:
                    best = e
        if best is not None:
            m.partial_block = best.block
            m.n_partial = best.tokens.shape[0]
            m.n_tokens += m.n_partial
        return m

    def record_hit_tokens(self, n: int) -> None:
        self.prefix_hit_tokens += int(n)

    # -- snapshot / restore (PR-7 warm-restart discipline) ----------------
    def snapshot(self) -> dict:
        return {
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "refcount": self._refcount.tolist(),
            "free": list(self._free),
            "cached": list(self._cached),
            "index": [
                {
                    "hash": h,
                    "block": e.block,
                    "tokens": e.tokens.tolist(),
                    "parent": e.parent,
                    "partial": e.partial,
                }
                for h, e in self._index.items()
            ],
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "cow_copies": self.cow_copies,
        }

    def restore(self, snap: dict) -> None:
        if (snap["n_blocks"] != self.n_blocks
                or snap["block_size"] != self.block_size):
            raise ValueError(
                "kv pool snapshot shape mismatch: snapshot "
                f"{snap['n_blocks']}x{snap['block_size']} vs pool "
                f"{self.n_blocks}x{self.block_size}"
            )
        self._refcount = np.asarray(snap["refcount"], np.int32).copy()
        self._free = deque(snap["free"])
        self._cached = OrderedDict((b, None) for b in snap["cached"])
        self._index = {}
        self._partials = {}
        self._block_hashes = {}
        for d in snap["index"]:
            e = _IndexEntry(
                int(d["block"]), np.asarray(d["tokens"], np.int32),
                int(d["parent"]), bool(d["partial"]),
            )
            self._index[int(d["hash"])] = e
            self._block_hashes.setdefault(e.block, []).append(int(d["hash"]))
            if e.partial:
                self._partials.setdefault(e.parent, []).append(int(d["hash"]))
        self.prefix_hits = int(snap.get("prefix_hits", 0))
        self.prefix_hit_tokens = int(snap.get("prefix_hit_tokens", 0))
        self.cow_copies = int(snap.get("cow_copies", 0))
        self._emit_in_use()

    def stats(self) -> Dict[str, int]:
        return {
            "kv_blocks": self.n_blocks,
            "kv_blocks_in_use": self.in_use(),
            "kv_blocks_free": len(self._free),
            "kv_blocks_cached": len(self._cached),
            "kv_prefix_hits": self.prefix_hits,
            "kv_prefix_hit_tokens": self.prefix_hit_tokens,
            "kv_cow_copies": self.cow_copies,
        }
