"""Async executor: one streaming thread per node, bounded queues between.

The runtime analogue of GStreamer's streaming threads + queue elements
(reference parallelism construct #1, SURVEY.md §2.6): every node runs
concurrently, queues give backpressure, and frame-level pipelining across
stages is automatic. On TPU the win is larger than on CPU: a fused segment's
jitted call *dispatches* asynchronously (jax async dispatch), so while one
frame computes on device, the next frame's host-side work overlaps.

Node kinds (from the compile plan):
- SourceNode: drives generate() until EOS or stop.
- FusedNode: a FusedSegment (1..n TensorOps) → one jitted call per frame.
- HostNode: HostElement.process per frame (fusion barrier).
- RoutingNode: feeds Routing.receive/eos with per-pad frames.
- SinkNode: Sink.render per frame.

EOS: a sentinel flows through every queue. Multi-input nodes forward EOS
downstream only after ALL sink pads saw it. Errors capture into
Executor.errors and poison the pipeline (stop event) so threads unwind —
UNLESS the failing node carries an active error policy (pipeline/faults.py
``on-error=drop|retry|route``): then the FaultGate consumes the frame
(drop/dead-letter/backoff-retry) and streaming continues. A stall watchdog
([executor] watchdog_timeout_ms > 0) converts hangs — data queued, no node
progressing — into typed PipelineStallErrors with a per-node snapshot.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from nnstreamer_tpu.elements.base import (
    Element,
    HostElement,
    Routing,
    Sink,
    Source,
    TensorOp,
)
from nnstreamer_tpu import trace
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.pipeline.device_faults import (
    BucketGovernor,
    DeviceCircuit,
    classify_device_fault,
    resolve_device_policy,
)
from nnstreamer_tpu.pipeline.faults import (
    FaultGate,
    PipelineStallError,
    frame_deadline_expired,
    notify_shed,
    resolve_fault_policy,
    watchdog_timeout_ms,
)
from nnstreamer_tpu.pipeline import transfer
from nnstreamer_tpu.pipeline.graph import ExecPlan, FusedSegment, Link
from nnstreamer_tpu.pipeline.sanitize import (
    Sanitizer,
    san_chan_cls,
    sanitize_enabled,
)
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_log = get_logger("executor")


class _Stop(Exception):
    pass


_EMPTY = object()  # _Chan.get_nowait sentinel (frames are never None-like)


class _Chan:
    """Bounded SPSC channel for inter-node frames.

    Every executor link has exactly one producer node and one consumer
    node (pads fan out to distinct queues), so the synchronized
    queue.Queue — whose mutex + condvar dance costs several µs per
    put/get — is overkill: deque.append/popleft are GIL-atomic, making
    the non-blocking fast path lock-free (~1 µs per hop).

    Parking discipline: the waiter advertises itself in a _*_waiting
    flag BEFORE re-checking the deque, and the other side checks the
    flag AFTER its deque op — under the GIL this Dekker-style pairing
    means either the waiter sees the data/space or the mover sees the
    flag, so no wake is ever missed — and in steady flow (nobody
    parked) NO Event is touched at all. Wakes themselves are the
    expensive part (each one is a context switch; a wake per frame at
    a full/empty edge costs more than the frame's own host work), so
    the full edge wakes a parked producer only at the LOW-WATER mark
    (half-drained, or empty): the producer then refills in one burst,
    amortizing the switch over maxsize/2 frames. The empty edge wakes
    on the first item — a parked consumer is the frame path, and
    delaying it would add latency. All waits are bounded (50 ms) so
    any missed edge degrades to a beat, never a hang."""

    __slots__ = ("_d", "_max", "_data", "_space", "_get_waiting",
                 "_put_waiting")

    def __init__(self, maxsize: int) -> None:
        self._d: deque = deque()
        self._max = max(1, maxsize)
        self._data = threading.Event()   # set: items may be available
        self._space = threading.Event()  # set: space may be available
        self._get_waiting = False
        self._put_waiting = False

    def __len__(self) -> int:
        return len(self._d)

    def put(self, item, stop_event) -> None:
        d = self._d
        if len(d) >= self._max:
            while True:
                if stop_event.is_set():
                    raise _Stop()
                self._space.clear()
                self._put_waiting = True
                # recheck after advertising: a pop between the len
                # check and the flag set either leaves items visible
                # here or sees the flag and wakes us
                if len(d) < self._max:
                    self._put_waiting = False
                    break
                self._space.wait(0.05)
                self._put_waiting = False
                if len(d) < self._max:
                    break
        d.append(item)
        if self._get_waiting:
            self._data.set()

    def _wake_put(self, d) -> None:
        # low-water wake: burst-refill beats a switch per pop
        if self._put_waiting and (len(d) * 2 <= self._max or not d):
            self._space.set()

    def get(self, stop_event):
        d = self._d
        if not d:
            while True:
                if stop_event.is_set():
                    raise _Stop()
                self._data.clear()
                self._get_waiting = True
                if d:
                    self._get_waiting = False
                    break
                self._data.wait(0.05)
                self._get_waiting = False
                if d:
                    break
        item = d.popleft()
        self._wake_put(d)
        return item

    def get_nowait(self):
        """Pop without blocking; returns _EMPTY when nothing is queued."""
        d = self._d
        if not d:
            return _EMPTY
        item = d.popleft()
        self._wake_put(d)
        return item

    def get_until(self, deadline: float, stop_event):
        """Blocking pop bounded by a ``time.monotonic()`` deadline;
        returns None once the deadline passes with nothing queued (frames
        are never None — see module invariant). The batch collector's
        straggler wait."""
        d = self._d
        while True:
            if d:
                item = d.popleft()
                self._wake_put(d)
                return item
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if stop_event.is_set():
                raise _Stop()
            self._data.clear()
            self._get_waiting = True
            # same Dekker pairing as get(): advertise, then recheck
            if d:
                self._get_waiting = False
                continue
            self._data.wait(min(0.05, remaining))
            self._get_waiting = False

    def drain(self, limit: int) -> list:
        """Pop up to ``limit`` queued items without blocking.

        Unlike get/get_nowait this ALWAYS wakes a parked producer when
        space was freed, low-water mark or not: a batch consumer goes
        compute for a whole batch after draining, so the "it will pop
        again in a moment and hit low-water" assumption behind the
        burst-amortized wake does not hold — without the wake a full
        channel plus a partial drain leaves the producer sleeping out
        its entire 50 ms beat while space sits free."""
        d = self._d
        out = []
        while len(out) < limit and d:
            out.append(d.popleft())
        if out and self._put_waiting and len(d) < self._max:
            self._space.set()
        return out


class _MeteredChan(_Chan):
    """_Chan plus queue-wait metering (nns-obs, opt-in): ``put`` stamps
    a parallel timestamp deque, the pop paths pair stamps back off and
    feed the ``nns_queue_wait_us`` histogram. The stamp lands BEFORE the
    item so the stamp deque always runs ahead of the item deque — under
    SPSC ordering the consumer can never pop an item whose stamp is
    missing, and pairing stays exact for the whole run (a stamp-after
    design desyncs permanently on the first put/pop race). Consequence:
    the stamp records when the producer OFFERED the frame, so a
    producer blocked on a full channel books that stall as queue wait —
    the backpressure signal this histogram exists to surface. Default-
    off pipelines never construct this class, so the lock-free fast
    path stays untouched."""

    __slots__ = ("_tq", "wait_hist")

    def __init__(self, maxsize: int, wait_hist) -> None:
        super().__init__(maxsize)
        self._tq: deque = deque()
        self.wait_hist = wait_hist

    def put(self, item, stop_event) -> None:
        self._tq.append(time.perf_counter())
        try:
            super().put(item, stop_event)
        except BaseException:
            # the item never entered the channel (stop/teardown): take
            # our own stamp back so pairing stays exact. The right end
            # is ours — the consumer only pops as many stamps as items.
            try:
                self._tq.pop()
            except IndexError:
                pass
            raise

    def _observe(self, n: int = 1) -> None:
        tq = self._tq
        now = time.perf_counter()
        for _ in range(n):
            if not tq:
                break
            dt = now - tq.popleft()
            if dt >= 0.0:
                self.wait_hist.observe(dt * 1e6)

    def get(self, stop_event):
        item = super().get(stop_event)
        self._observe()
        return item

    def get_nowait(self):
        item = super().get_nowait()
        if item is not _EMPTY:
            self._observe()
        return item

    def get_until(self, deadline: float, stop_event):
        item = super().get_until(deadline, stop_event)
        if item is not None:
            self._observe()
        return item

    def drain(self, limit: int) -> list:
        out = super().drain(limit)
        if out:
            self._observe(len(out))
        return out


class _FrameRing:
    """In-flight frame window for a device node (docs/streaming.md).

    The resident streaming discipline: a node SUBMITS frame N (async
    dispatch), and only once ``depth`` frames are in flight does the
    oldest one DELIVER downstream — so H2D staging of frame N+1, compute
    of frame N, and D2H of frame N-1 all overlap on the device's stream.
    Delivery is strictly FIFO, so in-order semantics and the sanitizer's
    offered == delivered accounting hold at every depth, and a fault
    mid-ring degrades only after the older in-flight frames have drained
    in order (the ladder in _invoke_window never reorders either).

    ``to_host`` arms the D2H half: when every consumer on the out pad
    negotiated host tensors, entering the ring starts ONE coalesced
    async fetch for the frame (pipeline/transfer.py) and delivery
    materializes the — by then usually landed — host copy. Device-
    capable consumers (an adjacent fused segment) get the device arrays
    untouched: the resident handoff, zero host materialization."""

    __slots__ = ("node", "depth", "to_host", "_q")

    def __init__(self, node: "Node", depth: int, to_host: bool) -> None:
        self.node = node
        self.depth = max(1, int(depth))
        self.to_host = to_host
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    def put(self, frame, t0: Optional[float] = None) -> None:
        """Submit one output frame; delivers the oldest in-flight frame
        once the ring is full. ``t0`` (per-frame paths) defers the
        node's stat() to delivery so frames_processed counts frames
        that actually left the node."""
        fetch = None
        if self.to_host and any(
            transfer.is_device_array(t) for t in frame.tensors
        ):
            fetch = transfer.fetch_frame(frame)
        self._q.append((frame, fetch, t0))
        while len(self._q) >= self.depth:
            self._deliver_one()

    def flush(self) -> None:
        """Deliver everything in flight, oldest first (EOS, idle input,
        and pre-degradation drains)."""
        while self._q:
            self._deliver_one()

    def _deliver_one(self) -> None:
        frame, fetch, t0 = self._q.popleft()
        node = self.node
        if fetch is not None:
            try:
                frame = frame.with_tensors(fetch.finish()).mark_synced()
            except _Stop:
                raise
            except Exception as exc:
                # async dispatch means a device fault can surface HERE,
                # at materialization, long after _process_frame's try
                # blocks returned — feed it to the node's fault
                # machinery (circuit + error policy) instead of letting
                # it skip the whole ladder
                if node.dispose_fault(frame, exc):
                    return  # disposed with accounting (drop/route)
                raise
        if t0 is not None:
            node.stat(t0)
        node.push_out(0, frame)


class Node:
    def __init__(self, ex: "Executor", name: str) -> None:
        self.ex = ex
        self.name = name
        self.in_queues: List[_Chan] = []
        # out pad -> consumers [(dst node, dst pad), ...]
        self.outs: Dict[int, List[Tuple["Node", int]]] = {}
        self.thread: Optional[threading.Thread] = None
        self.frames_processed = 0
        self.proc_time_ema_ms = 0.0
        self.max_invoke_ms = 0.0  # slowest observed invoke (drain sizing)
        self._needs_notify = False  # set for multi-pad scheduler nodes
        self.fault_stats = None  # FaultStats when an error policy is active
        self.fault_gate = None   # the gate itself (watchdog backoff check)
        # deadline-aware shedding (docs/edge-serving.md): frames whose
        # client SLO expired are dropped at dequeue, before this node
        # spends device time on them; counted so the sanitizer's
        # offered == delivered + dropped + routed invariant still latches
        self.deadline_shed = 0
        self._shed_ctr = None    # nns_deadline_shed_total handle (lazy)
        # device-resilience (pipeline/device_faults.py): wired by the
        # fused/host-op service loops from the plan-time device policy;
        # None on every other node kind (and when resilience is off)
        self.device_circuit = None   # DeviceCircuit
        self.bucket_governor = None  # BucketGovernor (OOM batch ladder)
        self._device_ctrs: Dict[str, Any] = {}  # kind -> counter (lazy)
        self._deg_gauge = None       # nns_degraded_segments handle (lazy)
        # warm-restart state restored before the service loop built its
        # governor/circuit/gate (Executor.restore on a fresh executor)
        self._pending_restore: Optional[Dict[str, Any]] = None
        # in-flight frame ring (docs/streaming.md): built by the device
        # service loops; None on nodes that deliver synchronously
        self._ring: Optional[_FrameRing] = None
        # nns-obs handles (None/empty with metrics off — the default):
        # wired by Executor._build when a registry is active
        self._lat_hist = None        # per-invoke latency histogram
        self._frames_ctr = None      # frames counter
        self._depth_hists: List = []  # sampled queue depth per pad
        self._batch_hist = None      # batch-size histogram (lazy)
        # nns_fused_postproc_total handle: armed by _build only for
        # fused segments carrying pre/post-processing ops
        # (docs/on-device-ops.md), so every other node pays one None
        # check per stat
        self._postproc_ctr = None

    def add_in_queue(self, size: int) -> int:
        self.in_queues.append(self.ex.make_chan(size, self, len(self.in_queues)))
        return len(self.in_queues) - 1

    # -- data movement ----------------------------------------------------
    def push_out(self, pad: int, item) -> None:
        if self.ex.sanitizer is not None and item is not EOS_FRAME:
            self.ex.sanitizer.count_push(self.name, pad)
        # an out pad may feed several consumers (eliminated tee fan-out);
        # frames are immutable, so every consumer shares the same object
        for dst, dst_pad in self.outs[pad]:
            dst.in_queues[dst_pad].put(item, self.ex.stop_event)
            if dst._needs_notify:
                dst.notify()

    def notify(self) -> None:
        """Data arrived on one of this node's input queues. Nodes that
        block on a single queue don't need it (chan.get wakes them);
        multi-pad nodes override to wake their scheduler and set
        _needs_notify so producers know to call it."""

    def inflight(self) -> int:
        """Frames submitted but not yet delivered (the node's ring):
        drain() quiescence and the stall watchdog must see them — a
        frame parked in a ring is neither queued nor delivered."""
        ring = self._ring
        return len(ring) if ring is not None else 0

    def _out_wants_host(self) -> bool:
        """Link-level placement negotiation for pad 0 (docs/streaming.md):
        True when EVERY consumer reads tensor bytes on host — a
        host-library filter node, or an element declaring WANTS_HOST —
        so the producer pre-fetches ONE coalesced async D2H per frame,
        overlapped with the next frame's compute, instead of each
        consumer paying a synchronous per-tensor fetch. Any
        device-capable consumer (an adjacent fused segment — the
        resident handoff) keeps frames on device untouched."""
        consumers = self.outs.get(0)
        if not consumers:
            return False
        for dst, _pad in consumers:
            elem = getattr(dst, "elem", None)
            if elem is not None and getattr(type(elem), "WANTS_HOST", False):
                continue
            if isinstance(dst, TensorOpHostNode):
                probe = getattr(elem, "wants_host_input", None)
                if callable(probe) and not probe():
                    # device-capable host node (a device-pinned/placed
                    # jax filter stages its own inputs): the resident
                    # handoff chains INTO it — placement's same-chip
                    # case costs no transfer, the cross-chip case pays
                    # one device_put, never a host round-trip
                    # (docs/serving-plane.md)
                    return False
                if not getattr(type(elem), "DEVICE_PASSTHROUGH", False):
                    # host-path op that reads tensor bytes;
                    # queue/capsfilter (DEVICE_PASSTHROUGH) carry device
                    # arrays untouched, so the handoff chains across
                    continue
            return False
        return True

    def broadcast_eos(self) -> None:
        for pad in self.outs:
            try:
                self.push_out(pad, EOS_FRAME)
            except _Stop:
                pass

    def pop(self, pad: int = 0):
        return self.in_queues[pad].get(self.ex.stop_event)

    # -- thread ------------------------------------------------------------
    def start(self) -> None:
        self.thread = threading.Thread(target=self._run_safe, name=self.name, daemon=True)
        self.thread.start()

    def _run_safe(self) -> None:
        try:
            self.run()
        except _Stop:
            pass
        except Exception as exc:  # capture and poison
            _log.error("node %s failed: %s", self.name, exc)
            self.ex.record_error(exc)
            self.broadcast_eos()

    def run(self) -> None:
        raise NotImplementedError

    def _advance(self, n: int) -> None:
        """The ONE place frames_processed mutates — the node's own service
        thread is the only writer (single-writer contract; observers get
        GIL-atomic reads), and funneling the read-modify-write through a
        single method makes that structural for the nns-san race lint."""
        self.frames_processed += n

    def stat(self, t0: float) -> None:
        self._advance(1)
        tracer = trace.get()
        lat = self._lat_hist
        if tracer is None and lat is None and (self.frames_processed & 7):
            # sampled EMA (1-in-8): the per-frame timing arithmetic is a
            # measurable slice of the host budget at multi-kfps rates,
            # and an EMA over every 8th frame reads the same. With a
            # tracer or a metrics registry attached every frame records
            # (completeness matters more than throughput when profiling).
            return
        now = time.perf_counter()
        dt = (now - t0) * 1000.0
        a = 0.2
        self.proc_time_ema_ms = (1 - a) * self.proc_time_ema_ms + a * dt
        if dt > self.max_invoke_ms:
            self.max_invoke_ms = dt
        if lat is not None:
            lat.observe((now - t0) * 1e6)
            self._frames_ctr.inc()
            if self._postproc_ctr is not None:
                self._postproc_ctr.inc()
            if not (self.frames_processed & 15):
                # sampled queue-depth: every 16th frame, one len() read
                # per pad (backpressure visibility without per-put cost)
                for h, q in zip(self._depth_hists, self.in_queues):
                    h.observe(len(q))
        if tracer is not None:
            tracer.complete(
                self.name, type(self).__name__, t0, now - t0,
                {"frame": self.frames_processed},
            )

    def shed_if_expired(self, item) -> bool:
        """Deadline-aware shedding at dequeue (the executor ingress):
        a frame whose client SLO already expired is dropped BEFORE it
        consumes this node's (device) time; the edge layer NACKs the
        client so the request still gets a terminal outcome. The check
        is one meta lookup for frames without a deadline — the common
        case stays effectively free."""
        meta = getattr(item, "meta", None)
        if not meta or "deadline_ms" not in meta:
            return False
        if not frame_deadline_expired(meta):
            return False
        self.deadline_shed += 1
        if self._shed_ctr is None and self.ex.metrics is not None:
            self._shed_ctr = self.ex.metrics.counter(
                "nns_deadline_shed_total", element=self.name
            )
        if self._shed_ctr is not None:
            self._shed_ctr.inc()
        notify_shed(item, self.name)
        return True

    # -- device resilience (pipeline/device_faults.py) --------------------
    def _device_fault(self, exc: Exception) -> Optional[str]:
        """Classify ``exc``; for device-plane faults record the
        nns_device_faults_total counter + a trace event and return the
        kind, else None (ordinary element errors stay with the per-frame
        policies). Cold path: one event per fault, never per frame."""
        kind = classify_device_fault(exc)
        if kind is None:
            return None
        if self.ex.metrics is not None:
            ctr = self._device_ctrs.get(kind)
            if ctr is None:
                ctr = self.ex.metrics.counter(
                    "nns_device_faults_total", element=self.name, kind=kind
                )
                self._device_ctrs[kind] = ctr
            ctr.inc()
        tracer = trace.get()
        if tracer is not None:
            tracer.fault(self.name, f"device-{kind}", exc)
        return kind

    def dispose_fault(self, frame, exc: Exception) -> bool:
        """Handle a fault that surfaced OUTSIDE an invoke's try block —
        async dispatch errors materialize at ring delivery (the
        coalesced fetch), and H2D staging can fail before the invoke:
        classify + count it (device circuit included), then dispose of
        the frame through the per-frame error policy with full
        accounting. False → no disposal policy (stop): the caller
        re-raises, PR-3 semantics. The frame cannot be re-invoked at
        this point, so ``retry`` degrades to route-or-drop exactly like
        an exhausted retry budget."""
        kind = self._device_fault(exc)
        circ = self.device_circuit
        if kind is not None and circ is not None and circ.record_fault(kind):
            self._update_degraded_gauge()
        gate = self.fault_gate
        if gate is None or gate.policy.on_error == "stop":
            return False
        gate.stats.errors += 1
        gate._dispose(frame, exc, 0)
        return True

    def _update_degraded_gauge(self) -> None:
        """Refresh nns_degraded_segments for this node (0/1): degraded
        means the circuit is open (serving eager) or the OOM governor
        holds the batch ceiling below the full ladder. Called on state
        TRANSITIONS only (fault/recovery events), never per frame."""
        if self.ex.metrics is None:
            return
        if self._deg_gauge is None:
            self._deg_gauge = self.ex.metrics.gauge(
                "nns_degraded_segments", element=self.name
            )
        circ, gov = self.device_circuit, self.bucket_governor
        self._deg_gauge.set(
            1 if (
                (circ is not None and circ.open)
                or (gov is not None and gov.degraded)
            ) else 0
        )

    def device_snapshot(self) -> Dict[str, Any]:
        """Warm-restart payload for this node (Executor.snapshot)."""
        d: Dict[str, Any] = {
            "frames": self.frames_processed,
            "deadline_shed": self.deadline_shed,
        }
        if self.bucket_governor is not None:
            d["governor"] = self.bucket_governor.snapshot()
        if self.device_circuit is not None:
            d["circuit"] = self.device_circuit.snapshot()
        fs = self.fault_stats
        if fs is not None:
            d["faults"] = {
                "errors": fs.errors, "dropped": fs.dropped,
                "routed": fs.routed,
                "routed_unlinked": fs.routed_unlinked,
                "retries": fs.retries,
                "retry_exhausted": fs.retry_exhausted,
            }
        return d

    def restore_state(self, d: Dict[str, Any]) -> None:
        """Apply a device_snapshot(): counters land immediately;
        governor/circuit/fault-stats parts are stashed and applied by
        the service loop once it has built those objects (they do not
        exist before run())."""
        self.frames_processed = int(d.get("frames", self.frames_processed))
        self.deadline_shed = int(d.get("deadline_shed", self.deadline_shed))
        self._pending_restore = d

    def _apply_pending_restore(self) -> None:
        """Called from the service loop after governor/circuit/gate are
        built: re-arm the remembered OOM ceiling, circuit state, and
        fault counters from a warm-restart snapshot. Sections whose
        target object does not exist YET stay stashed (restore() on a
        just-started executor can race the service loop's
        _build_resilience — consuming them then would silently lose the
        remembered OOM ceiling); the loop's own post-build call picks
        them up."""
        d = self._pending_restore
        if not d:
            return
        pending: Dict[str, Any] = {}
        if "governor" in d:
            if self.bucket_governor is not None:
                self.bucket_governor.restore(d["governor"])
            else:
                pending["governor"] = d["governor"]
        if "circuit" in d:
            if self.device_circuit is not None:
                self.device_circuit.restore(d["circuit"])
            else:
                pending["circuit"] = d["circuit"]
        snap = d.get("faults")
        if snap:
            fs = self.fault_stats
            if fs is not None:
                fs.errors = int(snap.get("errors", 0))
                fs.dropped = int(snap.get("dropped", 0))
                fs.routed = int(snap.get("routed", 0))
                fs.routed_unlinked = int(snap.get("routed_unlinked", 0))
                fs.retries = int(snap.get("retries", 0))
                fs.retry_exhausted = int(snap.get("retry_exhausted", 0))
            else:
                pending["faults"] = snap
        self._pending_restore = pending or None
        self._update_degraded_gauge()

    def make_fault_gate(self, policy, elem=None) -> Optional[FaultGate]:
        """Build this node's error-policy applicator (None when the
        policy is ``stop`` — the default path stays untouched). Called
        from run(), AFTER the executor wired self.outs, so the route
        closure can see whether the element's error pad has a consumer.

        Only elements that DECLARE the fault surface (``on-error`` in
        their PROPERTIES) participate: a class that never opted in must
        not have an [executor] on_error default applied to it, nor its
        own same-named knobs misread — tensor_query_client's
        ``retry-max`` configures transport reconnects, not frame
        retries."""
        if elem is not None and "on-error" not in type(elem).property_schema():
            return None
        if policy is None:
            policy = resolve_fault_policy([elem] if elem is not None else [])
        if not policy.active:
            return None
        route = None
        err_pad = getattr(elem, "error_pad", None) if elem is not None else None
        if err_pad is not None and err_pad in self.outs:
            def route(err_frame, _pad=err_pad):
                self.push_out(_pad, err_frame)
        gate = FaultGate(
            policy, self.name, stop_event=self.ex.stop_event, route=route,
            raise_through=(_Stop,), stop_exc=_Stop,
        )
        self.fault_stats = gate.stats
        self.fault_gate = gate  # watchdog reads backoff_deadline
        return gate

    def make_batch_collector(self, cfg, elem, cap=None):
        """BatchCollector on input pad 0 with the upstream-QoS drop
        predicate for `elem` (one definition of skipped-upstream
        accounting for both batched service loops). ``cap`` is the OOM
        bucket governor's live ceiling callable (docs/resilience.md):
        a degraded segment must not even COLLECT windows wider than it
        can dispatch."""
        from nnstreamer_tpu.pipeline.batching import BatchCollector

        drop = None
        if elem.qos_sources:
            def drop(frame, _elem=elem):
                if _elem.qos_would_drop(frame):
                    for q in _elem.qos_sources:
                        q.skipped_upstream += 1
                    return True
                return False

        return BatchCollector(
            self.in_queues[0], self.ex.stop_event, cfg, drop=drop, cap=cap
        )

    def stat_batch(self, t0: float, n: int, bucket: int, wait_s: float) -> None:
        """Per-BATCH accounting: frames_processed counts frames, the EMA
        tracks per-batch wall time, and with a tracer attached one
        batch-assembly span records size/bucket/wait/pad-waste."""
        self._advance(n)
        now = time.perf_counter()
        dt = (now - t0) * 1000.0
        a = 0.2
        self.proc_time_ema_ms = (1 - a) * self.proc_time_ema_ms + a * dt
        if dt > self.max_invoke_ms:
            self.max_invoke_ms = dt
        lat = self._lat_hist
        if lat is not None:
            # one latency observation per INVOKE (the device dispatch is
            # the unit the tail percentiles describe), n frames counted
            lat.observe((now - t0) * 1e6)
            self._frames_ctr.inc(n)
            if self._postproc_ctr is not None:
                self._postproc_ctr.inc(n)
            if self._batch_hist is None:
                self._batch_hist = self.ex.metrics.histogram(
                    "nns_batch_size", lo=1.0, growth=2.0 ** 0.5,
                    nbuckets=16, element=self.name,
                )
            self._batch_hist.observe(n)
            for h, q in zip(self._depth_hists, self.in_queues):
                h.observe(len(q))
        tracer = trace.get()
        if tracer is not None:
            tracer.batch(
                self.name, t0, now - t0, batch=n, bucket=bucket,
                wait_s=wait_s, frame=self.frames_processed,
            )


class SourceNode(Node):
    def __init__(self, ex, elem: Source) -> None:
        super().__init__(ex, elem.name)
        self.elem = elem

    def run(self) -> None:
        pause = self.ex.pause_event
        stop = self.ex.stop_event
        while not stop.is_set():
            if pause.is_set():
                # Executor.drain(): park at a frame boundary — nothing
                # new enters the graph until resume() clears the event
                time.sleep(0.005)
                continue
            t0 = time.perf_counter()
            item = self.elem.generate()
            if item is EOS_FRAME:
                break
            if item is None:  # no data yet — re-poll (bounded-wait sources)
                continue
            self.stat(t0)
            self.push_out(0, item)
        self.broadcast_eos()


class FusedNode(Node):
    def __init__(self, ex, seg: FusedSegment) -> None:
        super().__init__(ex, seg.name)
        self.seg = seg

    def _build_resilience(self, cfg) -> None:
        """Instantiate the device circuit + OOM bucket governor from the
        plan-time device policy (pipeline/device_faults.py): the circuit
        guards every path, the governor only batched segments (bucket 1
        has nothing left to shrink)."""
        pol = self.seg.device_policy
        if pol is None:
            return
        if pol.get("device-fallback"):
            self.device_circuit = DeviceCircuit(
                after=pol["device-fallback-after"],
                probe_every=pol["device-probe-every"],
            )
        if (
            cfg is not None and cfg.active
            and pol.get("oom-policy") == "degrade"
        ):
            self.bucket_governor = BucketGovernor(
                cfg.buckets,
                cooldown_s=pol["oom-reprobe-ms"] / 1000.0,
            )

    def run(self) -> None:
        cfg = self.seg.batch_config
        self._build_resilience(cfg)
        try:
            # compile before first frame (PAUSED-state parity)
            self.seg.build()
        except Exception as exc:
            # a compile failure at build opens the circuit (when armed)
            # exactly like one on the first frame would
            kind = self._device_fault(exc)
            circ = self.device_circuit
            if kind is None or circ is None or not circ.record_fault(kind):
                raise
            self._update_degraded_gauge()
        gate = self.make_fault_gate(self.seg.fault_policy, self.seg.first)
        self._apply_pending_restore()
        if cfg is not None and cfg.active:
            self._run_batched(cfg, gate)
            return
        first = self.seg.first
        ring = _FrameRing(
            self, self.seg.ring_depth or 1, self._out_wants_host()
        )
        self._ring = ring
        # H2D staging (pipeline/transfer.py): host tensors become fresh
        # device arrays via async device_put BEFORE dispatch, so frame
        # N+1's wire time overlaps frame N's compute. Bypassed on a
        # process-local CPU backend (the jitted ingest IS the cheaper
        # copy) and for identity segments (nothing dispatches at all).
        stage_on = (
            not transfer.default_backend_is_cpu()
            and not self.seg.is_identity()
        )
        # donation needs exclusive buffer ownership and replay safety:
        # _process_frame stages a PRIVATE device copy of an all-host
        # frame and donates THAT, so the circuit's eager fallback can
        # always restage from the caller's intact host buffers. A retry
        # gate re-invokes through its own callback (no donate kwarg),
        # so gated streams keep un-donated semantics.
        donate_ok = self.seg.donate and gate is None
        chan = self.in_queues[0]
        stop = self.ex.stop_event
        while True:
            item = chan.get_nowait()
            if item is _EMPTY:
                # idle input: deliver what's in flight rather than
                # holding frames across the gap, then block
                ring.flush()
                item = chan.get(stop)
            if item is EOS_FRAME:
                break
            if self.shed_if_expired(item):
                continue
            if first.qos_would_drop(item):
                # downstream rate limiter will drop this frame: skip the
                # whole fused program (reference upstream-QoS work skip)
                for q in first.qos_sources:
                    q.skipped_upstream += 1
                continue
            t0 = time.perf_counter()
            if stage_on:
                if donate_ok and not any(
                    transfer.is_device_array(t) for t in item.tensors
                ):
                    # all-host frame: _process_frame stages the private
                    # upload and donates it. A frame carrying an
                    # upstream device array (resident handoff, tee
                    # share) is partly someone ELSE's memory — never
                    # donated, staged below instead.
                    ring.put(self._process_frame(item, donate=True), t0)
                    continue
                try:
                    staged = transfer.stage_frame(item)
                except _Stop:
                    raise
                except Exception as exc:
                    # H2D put failed before any invoke: same off-ladder
                    # disposal as an async delivery fault
                    if self.dispose_fault(item, exc):
                        continue
                    raise
                if staged is not item:
                    item = staged
            if gate is None:
                out = self._process_frame(item)
            else:
                delivered, out = gate.process(item, self._process_frame)
                if not delivered:
                    continue
            ring.put(out, t0)
        ring.flush()
        self.broadcast_eos()

    # -- device-resilient invoke paths ------------------------------------
    def _process_frame(self, item, donate: bool = False):
        """seg.process with the device circuit around it: repeated
        device faults (or one compile failure) open the circuit and this
        frame — and the stream after it — serves from the eager path;
        while open, periodic probes close it on recovery. Below the
        open threshold the typed exception propagates to the node's
        error policy (stop/drop/retry/route), PR-3 semantics.
        ``donate`` requires an ALL-HOST frame: a private device copy is
        staged HERE and donated, so every replay path — the circuit's
        eager fallback, a later retry attempt — reads the caller's
        intact host buffers, never a donated (deleted) array."""
        circ = self.device_circuit
        if circ is not None and circ.open:
            return self._degraded_process(item)
        dev = transfer.stage_frame(item, force=True) if donate else item
        if circ is None:
            return self.seg.process(dev, donate)
        try:
            out = self.seg.process(dev, donate)
        except _Stop:
            raise
        except Exception as exc:
            kind = self._device_fault(exc)
            if kind is None:
                raise
            if circ.record_fault(kind):
                self._update_degraded_gauge()
                circ.eager_invokes += 1
                return self.seg.process_eager(item)
            raise
        circ.record_ok()
        return out

    def _degraded_process(self, item):
        """Serve one frame while the circuit is open: eager path, with
        the compiled path probed every probe-every frames — a probe
        that succeeds closes the circuit and serves its frame from the
        recovered program."""
        circ = self.device_circuit
        if circ.should_probe():
            try:
                out = self.seg.process(item)
            except _Stop:
                raise
            except Exception as exc:
                kind = self._device_fault(exc)
                if kind is None:
                    raise
                circ.record_fault(kind)  # stays open; kind counted
            else:
                circ.close()
                self._update_degraded_gauge()
                return out
        circ.eager_invokes += 1
        return self.seg.process_eager(item)

    def _serve_degraded(self, chunk, gate):
        """Eager per-frame service of a window while the circuit is
        open (vmap IS tracing, so a broken compile path cannot serve a
        stacked window)."""
        outs = []
        for f in chunk:
            if gate is None:
                outs.append(self._degraded_process(f))
            else:
                delivered, out = gate.process(f, self._degraded_process)
                if delivered:
                    outs.append(out)
        return outs

    def _invoke_window(self, frames, cfg, gate):
        """One collected window through the degradation ladder
        (docs/resilience.md). Returns (outs, rows_dispatched):

        1. the window is chunked to the OOM governor's live ceiling;
        2. a chunk that OOMs shrinks the ceiling one ladder rung and is
           RETRIED (never dropped) — at bucket 1 the OOM stops being
           shrinkable and falls through to (3);
        3. other device faults feed the circuit; once open, the chunk
           (and the stream) serves from the eager path;
        4. anything non-device-plane keeps PR-3 semantics: the failed
           window splits per-frame through the error-policy gate."""
        gov = self.bucket_governor
        circ = self.device_circuit
        outs: List = []
        rows = 0
        pending = deque([frames])
        while pending:
            chunk = pending.popleft()
            cap = gov.cap() if gov is not None else None
            if cap is not None and len(chunk) > cap:
                # split to the live ceiling; remainder keeps its order
                pending.appendleft(chunk[cap:])
                chunk = chunk[:cap]
            if circ is not None and circ.open:
                outs.extend(self._serve_degraded(chunk, gate))
                rows += len(chunk)
                continue
            try:
                if len(chunk) == 1:
                    # lone frame: the per-frame program, no stack/split
                    got, bucket = [self.seg.process(chunk[0])], 1
                else:
                    got, bucket = self.seg.process_batch(chunk, cfg)
            except _Stop:
                raise
            except Exception as exc:
                kind = self._device_fault(exc)
                if kind == "oom" and gov is not None:
                    attempted = (
                        cfg.bucket_for(len(chunk)) if len(chunk) > 1 else 1
                    )
                    if gov.on_oom(attempted) is not None:
                        self._update_degraded_gauge()
                        pending.appendleft(chunk)  # retry, shrunk
                        continue
                    # bucket 1 still OOMs: nothing left to shrink —
                    # treat like any other device fault below
                if kind is not None and circ is not None:
                    if circ.record_fault(kind):
                        self._update_degraded_gauge()
                        outs.extend(self._serve_degraded(chunk, gate))
                        rows += len(chunk)
                        continue
                # not device-plane (or circuit below threshold/absent):
                # the error-policy split — one bad frame must not
                # discard its batchmates
                if gate is None:
                    raise
                for f in chunk:
                    delivered, out = gate.process(f, self._process_frame)
                    if delivered:
                        outs.append(out)
                # per-frame programs pad nothing: rows == chunk size
                rows += len(chunk)
                continue
            if circ is not None:
                circ.record_ok()
            if gov is not None and gov.on_ok(bucket):
                self._update_degraded_gauge()
            outs.extend(got)
            rows += bucket
        return outs, rows

    def _run_batched(self, cfg, gate=None) -> None:
        """Micro-batched service loop: drain up to max-batch frames (the
        OOM governor's ceiling when degraded), ONE batched device invoke
        per chunk, split results back in order. Failure handling is the
        degradation ladder in _invoke_window."""
        gov = self.bucket_governor
        collector = self.make_batch_collector(
            cfg, self.seg.first, cap=(gov.cap if gov is not None else None)
        )
        # window-granular double buffer: delivery of window K's frames
        # (and their coalesced D2H when the link negotiated host) lags
        # up to ring_depth frames behind the dispatch of window K+1
        ring = _FrameRing(
            self, self.seg.ring_depth or 1, self._out_wants_host()
        )
        self._ring = ring
        while True:
            if not self.in_queues[0]:
                # idle input: don't hold delivered-able frames across
                # the collector's blocking wait
                ring.flush()
            frames, eos, wait_s = collector.collect()
            if frames:
                frames = [
                    f for f in frames if not self.shed_if_expired(f)
                ]
            if frames:
                t0 = time.perf_counter()
                outs, rows = self._invoke_window(frames, cfg, gate)
                self.seg.batch_stats.record(len(frames), rows, wait_s)
                self.stat_batch(t0, len(frames), rows, wait_s)
                for f in outs:
                    ring.put(f)
            if eos:
                break
        ring.flush()
        self.broadcast_eos()


class ChainNode(Node):
    """ONE service thread — and in steady state ONE XLA dispatch per
    unrolled window — for a whole compiled chain
    (pipeline/chain_program.py, docs/chain-analysis.md "Compiled
    chains"). Replaces the member segments' FusedNodes: ``_build`` maps
    every member op here, so interior links never materialize channels
    and the boundary bytes between member segments are structurally
    zero (``transfer_crosscheck`` asserts exactly that). Any runtime
    hazard — device fault, unshrinkable OOM, a compile failure at
    build — latches the STICKY whole-chain fallback when the device
    policy allows it (raises otherwise): every later frame serves
    through the member segments' own per-node programs,
    ``ChainProgram.process_frame_fallback``, the bitwise parity
    oracle."""

    def __init__(self, ex, chain, program) -> None:
        super().__init__(ex, chain.name)
        self.chain = chain
        self.program = program
        # sticky fallback latch + window counter: single-writer (this
        # node's service thread); observers get GIL-atomic reads
        self.fallback_latched = False
        self.fallback_windows = 0
        self._fallback_allowed = True
        self._stage_on = False
        # nns-obs handles, wired by _build when a registry is active
        self._chain_launch_ctr = None
        self._chain_fallback_ctr = None

    def _update_degraded_gauge(self) -> None:
        # the chain's degraded state is the fallback latch (there is no
        # device circuit here — the latch IS the open circuit), plus the
        # shared OOM-governor criterion
        if self.ex.metrics is None:
            return
        if self._deg_gauge is None:
            self._deg_gauge = self.ex.metrics.gauge(
                "nns_degraded_segments", element=self.name
            )
        gov = self.bucket_governor
        self._deg_gauge.set(
            1 if (
                self.fallback_latched
                or (gov is not None and gov.degraded)
            ) else 0
        )

    def _latch_fallback(self) -> None:
        """Engage the sticky per-node fallback. Latched, not probed:
        the hazard already proved the one-launch program wrong for this
        run, and the per-node path is the semantics baseline — flapping
        between the two mid-stream buys nothing."""
        if not self.fallback_latched:
            self.fallback_latched = True
            _log.warning(
                "chain %s: falling back to the per-node parity path",
                self.name,
            )
            self._update_degraded_gauge()

    def run(self) -> None:
        from nnstreamer_tpu.pipeline.batching import chain_window_config

        pol = resolve_device_policy(self.chain.ops)
        self._fallback_allowed = bool(pol.get("device-fallback"))
        if pol.get("oom-policy") == "degrade" and self.program.unroll > 1:
            self.bucket_governor = BucketGovernor(
                self.program.buckets,
                cooldown_s=pol["oom-reprobe-ms"] / 1000.0,
            )
        try:
            # compile the window program before the first frame
            # (PAUSED-state parity, FusedNode discipline)
            self.program.build()
        except Exception as exc:
            kind = self._device_fault(exc)
            if kind is None or not self._fallback_allowed:
                raise
            self._latch_fallback()
        self._apply_pending_restore()
        self._stage_on = (
            not transfer.default_backend_is_cpu()
            and not self.program.is_identity()
        )
        gov = self.bucket_governor
        cfg = chain_window_config(self.program.unroll)
        collector = self.make_batch_collector(
            cfg, self.chain.first,
            cap=(gov.cap if gov is not None else None),
        )
        ring = _FrameRing(
            self, transfer.resolve_ring_depth(self.chain.ops),
            self._out_wants_host(),
        )
        self._ring = ring
        while True:
            if not self.in_queues[0]:
                # idle input: deliver in-flight frames across the wait
                ring.flush()
            frames, eos, wait_s = collector.collect()
            if frames:
                frames = [
                    f for f in frames if not self.shed_if_expired(f)
                ]
            if frames:
                t0 = time.perf_counter()
                outs, rows = self._invoke_chain_window(frames)
                self.stat_batch(t0, len(frames), rows, wait_s)
                for f in outs:
                    ring.put(f)
            if eos:
                break
        ring.flush()
        self.broadcast_eos()

    def _serve_fallback(self, chunk):
        """Per-frame service through the member segments' OWN programs
        (the parity oracle). A device fault inside drops that frame one
        more rung to the segments' eager paths — a chain whose compiled
        AND per-segment programs both fault still serves (device-
        circuit semantics)."""
        outs = []
        for f in chunk:
            try:
                outs.append(self.program.process_frame_fallback(f))
            except _Stop:
                raise
            except Exception as exc:
                if self._device_fault(exc) is None:
                    raise
                outs.append(self.program.process_frame_eager(f))
        return outs

    def _invoke_chain_window(self, frames):
        """One collected window through the chain's degradation ladder.
        Returns (outs, rows_dispatched):

        1. the window is chunked to the OOM governor's live ceiling;
        2. a chunk that OOMs shrinks the ceiling one ladder rung and is
           RETRIED (never dropped) — an unshrinkable OOM falls to (3);
        3. any other device fault latches the sticky per-node fallback
           (policy permitting; raises otherwise) and the chunk — and
           the stream after it — serves per frame from the parity
           oracle, eager rung underneath (docs/resilience.md)."""
        gov = self.bucket_governor
        outs: List = []
        rows = 0
        pending = deque([frames])
        while pending:
            chunk = pending.popleft()
            cap = gov.cap() if gov is not None else None
            if cap is not None and len(chunk) > cap:
                # split to the live ceiling; remainder keeps its order
                pending.appendleft(chunk[cap:])
                chunk = chunk[:cap]
            if self.fallback_latched:
                outs.extend(self._serve_fallback(chunk))
                rows += len(chunk)
                self.fallback_windows += 1
                if self._chain_fallback_ctr is not None:
                    self._chain_fallback_ctr.inc()
                continue
            donate = False
            chunk_in = chunk
            if self._stage_on and self.program.donate and not any(
                transfer.is_device_array(t)
                for f in chunk for t in f.tensors
            ):
                # all-host window: stage PRIVATE device copies and
                # donate THOSE, so every retry/fallback path re-reads
                # the caller's intact host buffers, never a donated
                # (deleted) array — _process_frame's replay discipline
                try:
                    chunk_in = [
                        transfer.stage_frame(f, force=True)
                        for f in chunk
                    ]
                    donate = True
                except _Stop:
                    raise
                except Exception as exc:
                    if self._device_fault(exc) is None:
                        raise
                    chunk_in, donate = chunk, False
            try:
                got, width, launched = self.program.process_window(
                    chunk_in, donate
                )
            except _Stop:
                raise
            except Exception as exc:
                kind = self._device_fault(exc)
                if kind == "oom" and gov is not None:
                    attempted = self.program.bucket_for(len(chunk))
                    if gov.on_oom(attempted) is not None:
                        self._update_degraded_gauge()
                        pending.appendleft(chunk)  # retry, shrunk
                        continue
                if kind is None or not self._fallback_allowed:
                    raise
                self._latch_fallback()
                pending.appendleft(chunk)  # re-served by the oracle
                continue
            if gov is not None and gov.on_ok(width):
                self._update_degraded_gauge()
            if launched and self._chain_launch_ctr is not None:
                self._chain_launch_ctr.inc()
            outs.extend(got)
            rows += width
        return outs, rows


class _PlaneWindowRing:
    """In-flight PLANE-WINDOW FIFO for the async submit loop: entries
    are (frames, ticket, wait_s) tuples parked between
    submit_window_async and the ordered host_collect_window. Exposed as
    the node's ``_ring`` so ``Node.inflight()`` — the drain/watchdog
    surface — counts the parked FRAMES (a ticket holds a whole
    window)."""

    __slots__ = ("_q",)

    def __init__(self) -> None:
        self._q: deque = deque()

    def __len__(self) -> int:
        return sum(len(e[0]) for e in self._q)

    def __bool__(self) -> bool:
        return bool(self._q)

    @property
    def windows(self) -> int:
        return len(self._q)

    def append(self, entry) -> None:
        self._q.append(entry)

    def popleft(self):
        return self._q.popleft()


class TensorOpHostNode(Node):
    """Host-path adapter for non-traceable TensorOps (e.g. tensor_filter
    with a torch/tflite backend) — a fusion barrier."""

    def __init__(self, ex, elem: TensorOp) -> None:
        super().__init__(ex, elem.name)
        self.elem = elem

    def run(self) -> None:
        # resolved at plan time (graph.py compile_plan); fall back for
        # hand-built ExecPlans that bypassed it
        cfg = getattr(self.elem, "batch_config", None)
        if cfg is None:
            from nnstreamer_tpu.pipeline.batching import resolve_batch_config

            cfg = resolve_batch_config([self.elem])
        gate = self.make_fault_gate(
            getattr(self.elem, "fault_policy", None), self.elem
        )
        if cfg.active and self.elem.is_batch_capable():
            self._run_batched(cfg, gate)
            return
        self._apply_pending_restore()
        # in-flight ring (docs/streaming.md): host nodes stay
        # synchronous (depth 1) unless the element set ring-depth — a
        # host backend whose invoke dispatches async work (or holds
        # device outputs) then overlaps delivery with the next invoke.
        # A DEVICE_PASSTHROUGH node (queue/capsfilter) carries device
        # arrays; when ITS consumers read bytes on host it arms the
        # coalesced prefetch, so a handoff chained across a queue still
        # lands as ONE overlapped D2H instead of the reader paying
        # per-tensor synchronous fetches.
        depth = getattr(self.elem, "ring_depth", 1) or 1
        to_host = (
            getattr(type(self.elem), "DEVICE_PASSTHROUGH", False)
            and self._out_wants_host()
        )
        if to_host and depth < 2:
            depth = 2  # overlap the fetch with the next hop
        ring = _FrameRing(self, depth, to_host)
        self._ring = ring
        chan = self.in_queues[0]
        stop = self.ex.stop_event
        while True:
            item = chan.get_nowait()
            if item is _EMPTY:
                ring.flush()
                item = chan.get(stop)
            if item is EOS_FRAME:
                ring.flush()
                for f in self.elem.flush():
                    self.push_out(0, f)
                break
            if self.shed_if_expired(item):
                continue
            if self.elem.qos_would_drop(item):
                for q in self.elem.qos_sources:
                    q.skipped_upstream += 1
                continue
            t0 = time.perf_counter()
            if gate is None:
                out = self.elem.host_process(item)
            else:
                delivered, out = gate.process(item, self.elem.host_process)
                if not delivered:
                    continue
            self.stat(t0)
            if out is None:  # absorbed (e.g. batching mid-window)
                continue
            for f in out if isinstance(out, list) else [out]:
                ring.put(f)
        ring.flush()
        self.broadcast_eos()

    def _run_batched(self, cfg, gate=None) -> None:
        """Host micro-batching for backends that declared the
        ``batchable`` capability (backends/base.py) — host backends that
        did not (tflite's set/invoke/get is strictly per-frame) keep the
        per-frame loop above. A window whose batched invoke OOMs rides
        the same degradation ladder as fused segments: the bucket
        governor shrinks the window ceiling and the chunk retries
        (docs/resilience.md)."""
        from nnstreamer_tpu.pipeline.batching import BatchStats

        elem = self.elem
        if getattr(elem, "batch_stats", None) is None:
            # host elements sit outside fused segments, so plan time did
            # not hand them a shared stats object
            elem.batch_stats = BatchStats()
        depth = int(getattr(elem, "plane_inflight", 1) or 1)
        if (
            depth > 1
            and getattr(elem, "plane", "")
            and hasattr(elem, "host_submit_window_async")
        ):
            # async serving-plane submits (docs/serving-plane.md):
            # collected windows ride tickets through a per-stream
            # in-flight ring instead of blocking the service thread
            # for the full plane round trip
            self._run_plane_async(cfg, gate, depth)
            return
        pol = getattr(elem, "device_policy", None)
        if pol is not None and pol.get("oom-policy") == "degrade":
            self.bucket_governor = BucketGovernor(
                cfg.buckets, cooldown_s=pol["oom-reprobe-ms"] / 1000.0
            )
        gov = self.bucket_governor
        self._apply_pending_restore()
        collector = self.make_batch_collector(
            cfg, elem, cap=(gov.cap if gov is not None else None)
        )
        stats = elem.batch_stats
        while True:
            frames, eos, wait_s = collector.collect()
            if frames:
                frames = [
                    f for f in frames if not self.shed_if_expired(f)
                ]
            if frames:
                t0 = time.perf_counter()
                outs = self._invoke_host_window(frames, gate)
                # host path never pads: bucket == batch size
                stats.record(len(frames), len(frames), wait_s)
                self.stat_batch(t0, len(frames), len(frames), wait_s)
                for f in outs:
                    self.push_out(0, f)
            if eos:
                for f in elem.flush():
                    self.push_out(0, f)
                break
        self.broadcast_eos()

    def _invoke_host_window(self, frames, gate) -> List:
        """One collected window through the host-path ladder: chunks
        bounded by the OOM governor's live ceiling; an OOM'd chunk
        shrinks the ceiling and retries; everything else keeps PR-3
        semantics (the failed window splits per-frame through the
        error-policy gate)."""
        elem = self.elem
        gov = self.bucket_governor
        outs: List = []
        pending = deque([frames])
        while pending:
            chunk = pending.popleft()
            cap = gov.cap() if gov is not None else None
            if cap is not None and len(chunk) > cap:
                pending.appendleft(chunk[cap:])
                chunk = chunk[:cap]
            try:
                outs.extend(elem.host_process_batch(chunk))
            except _Stop:
                raise
            except Exception as exc:
                kind = self._device_fault(exc)
                if kind == "oom" and gov is not None and len(chunk) > 1:
                    if gov.on_oom(len(chunk)) is not None:
                        self._update_degraded_gauge()
                        pending.appendleft(chunk)  # retry, shrunk
                        continue
                # split the failed window per-frame through the
                # policy (retry/drop/route each) — one bad frame
                # must not discard its batchmates
                if gate is None:
                    raise
                for f in chunk:
                    delivered, out = gate.process(f, elem.host_process)
                    if not delivered or out is None:
                        continue
                    outs.extend(out if isinstance(out, list) else [out])
                continue
            if gov is not None and gov.on_ok(len(chunk)):
                self._update_degraded_gauge()
        return outs

    def _run_plane_async(self, cfg, gate, depth: int) -> None:
        """Async plane submits (docs/serving-plane.md): each collected
        window SUBMITS as a non-blocking ticket; the oldest ticket is
        redeemed — and its frames delivered — only once ``depth``
        tickets are in flight (or input idles / EOS flushes), so window
        N+1 submits while the plane computes window N and window N−1
        delivers downstream. Delivery is strictly FIFO at every depth
        (per-stream order is structural), and a failed in-flight window
        splits per frame through THIS node's own error-policy gate via
        the blocking single-frame submit — the PR-3/6/7 fault/NACK/
        deadline accounting stays per stream, identical to the sync
        path. Plane outputs deliver untouched: device arrays stay
        resident for device-capable consumers (the PR-8 handoff)."""
        elem = self.elem
        pol = getattr(elem, "device_policy", None)
        if pol is not None and pol.get("oom-policy") == "degrade":
            # async keeps the sync path's OOM degradation ladder:
            # failed windows re-run through _invoke_host_window below,
            # and the collector caps at the governor's live ceiling
            self.bucket_governor = BucketGovernor(
                cfg.buckets, cooldown_s=pol["oom-reprobe-ms"] / 1000.0
            )
        gov = self.bucket_governor
        self._apply_pending_restore()
        collector = self.make_batch_collector(
            cfg, elem, cap=(gov.cap if gov is not None else None)
        )
        stats = elem.batch_stats
        ring = _PlaneWindowRing()
        self._ring = ring
        chan = self.in_queues[0]

        def deliver_one() -> None:
            frames, ticket, wait_s = ring.popleft()
            # timed from delivery start, not submit: a parked window
            # intentionally waits depth-1 dispatches in the ring, and
            # folding that into the node's batch latency would read as
            # a depth× slowdown — the residual redeem wait is the
            # honest async number (matching nns_plane_submit_wait_ms)
            t0 = time.perf_counter()
            if ticket is None:
                # submit itself failed: blocking re-invoke through the
                # sync ladder (OOM governor shrink, then per-frame
                # gate split — the exact _run_batched semantics)
                outs = self._invoke_host_window(frames, gate)
            else:
                try:
                    outs = elem.host_collect_window(ticket)
                except _Stop:
                    raise
                except Exception:
                    # failed in-flight window: re-run it through the
                    # SAME degradation ladder the sync path uses — an
                    # OOM shrinks the governor ceiling and retries,
                    # anything else splits per frame through this
                    # node's gate (or re-raises with no gate), so
                    # enabling async never changes fault semantics
                    outs = self._invoke_host_window(frames, gate)
            stats.record(len(frames), len(frames), wait_s)
            self.stat_batch(t0, len(frames), len(frames), wait_s)
            for f in outs:
                self.push_out(0, f)

        while True:
            while ring and len(chan) == 0:
                # idle input: drain the in-flight windows in order so
                # latency stays bounded (the _FrameRing idle-flush
                # discipline) instead of parking them until the next
                # arrival
                deliver_one()
            frames, eos, wait_s = collector.collect()
            if frames:
                frames = [
                    f for f in frames if not self.shed_if_expired(f)
                ]
            if frames:
                ticket = None
                try:
                    ticket = elem.host_submit_window_async(frames)
                except _Stop:
                    raise
                except Exception:
                    if gate is None:
                        raise
                ring.append((frames, ticket, wait_s))
                while ring.windows >= depth:
                    deliver_one()
            if eos:
                while ring:
                    deliver_one()
                for f in elem.flush():
                    self.push_out(0, f)
                break
        self.broadcast_eos()


class HostNode(Node):
    def __init__(self, ex, elem: HostElement) -> None:
        super().__init__(ex, elem.name)
        self.elem = elem

    def run(self) -> None:
        gate = self.make_fault_gate(
            getattr(self.elem, "fault_policy", None), self.elem
        )
        self._apply_pending_restore()
        while True:
            item = self.pop(0)
            if item is EOS_FRAME:
                for f in self.elem.flush():
                    self.push_out(0, f)
                break
            if self.shed_if_expired(item):
                continue
            if self.elem.qos_would_drop(item):
                for q in self.elem.qos_sources:
                    q.skipped_upstream += 1
                continue
            t0 = time.perf_counter()
            if gate is None:
                out = self.elem.process(item)
            else:
                delivered, out = gate.process(item, self.elem.process)
                if not delivered:
                    continue
            self.stat(t0)
            if out is None:
                continue
            for f in out if isinstance(out, list) else [out]:
                self.push_out(0, f)
        self.broadcast_eos()


class RoutingNode(Node):
    def __init__(self, ex, elem: Routing) -> None:
        super().__init__(ex, elem.name)
        self.elem = elem
        # producers notify() on push so the pad scan sleeps until there is
        # actually data, instead of busy-polling every pad on a 20 ms beat
        # (O(pads) idle wakeups/sec on wide mux fan-ins)
        self._wake = threading.Event()
        self._needs_notify = True

    def notify(self) -> None:
        self._wake.set()

    def run(self) -> None:
        n = len(self.in_queues)
        eos_seen = [False] * n
        # drain-all service of pads; Routing elements that need timestamp
        # sync buffer internally and emit when policy satisfied
        while not all(eos_seen):
            self._wake.clear()
            progressed = False
            for pad in range(n):
                if eos_seen[pad]:
                    continue
                while True:  # drain the pad without per-item timeouts
                    item = self.in_queues[pad].get_nowait()
                    if item is _EMPTY:
                        break
                    progressed = True
                    if item is EOS_FRAME:
                        eos_seen[pad] = True
                        for out_pad, f in self.elem.eos(pad):
                            self.push_out(out_pad, f)
                        break
                    t0 = time.perf_counter()
                    for out_pad, f in self.elem.receive(pad, item):
                        self.push_out(out_pad, f)
                    self.stat(t0)
            if self.ex.stop_event.is_set():
                raise _Stop()
            if not progressed and not all(eos_seen):
                # sleep until a producer pushes (bounded so stop_event is
                # still honored even if a notify is lost)
                self._wake.wait(timeout=0.1)
        self.broadcast_eos()


class SinkNode(Node):
    def __init__(self, ex, elem: Sink) -> None:
        super().__init__(ex, elem.name)
        self.elem = elem
        # wall-clock of the first/last completed render burst + frames
        # rendered: lets callers compute steady-state pipeline FPS with
        # the compile/warmup window excluded ((n_after_first)/(t_last -
        # t_first) — bench.py pipeline metrics)
        self.t_first_render: Optional[float] = None
        self.t_last_render: Optional[float] = None
        self.frames_rendered = 0
        self.first_burst_n = 0
        # per-frame e2e latencies (seconds) for wall-stamped frames
        # (videotestsrc stamp-wall=true): render time − generation time.
        # Bounded: a live pipeline renders forever, a per-frame float
        # list must not grow with it (the newest window is what p50
        # readers want anyway).
        self.latencies: deque = deque(maxlen=4096)

    def _mark_render(self, n: int, frames=()) -> None:
        now = time.perf_counter()
        if self.t_first_render is None:
            self.t_first_render = now
            self.first_burst_n = n
        self.t_last_render = now
        self.frames_rendered += n
        for f in frames:
            t0 = f.meta.get("wall_t0")
            if t0 is not None:
                self.latencies.append(now - t0)

    def run(self) -> None:
        window = getattr(self.elem, "sync_window", 1)
        pending: List = []  # frames trailing the device stream (sync-window)

        def _dev_key(f) -> tuple:
            keys = []
            for t in f.tensors:
                devs = getattr(t, "devices", None)
                if callable(devs):
                    try:
                        keys.extend(sorted(str(d) for d in devs()))
                    except Exception:  # noqa: BLE001 — deleted/host array
                        pass
            return tuple(keys)

        def _batch_fetch(frames: List) -> List:
            """ONE coalesced D2H for the whole window's tensors
            (pipeline/transfer.py fetch_window) instead of a fetch per
            tensor per frame — per-transfer cost dominates small
            results on a remote-attached device, so W frames × T
            tensors must not pay W·T round trips. The packed path
            degrades internally (local CPU arrays fetch by memcpy,
            cross-device windows fall back per-tensor with placement
            untouched); None only on a hard failure, restoring the
            per-frame prefetch."""
            try:
                return transfer.fetch_window(frames)
            except Exception:  # noqa: BLE001 — fetch is an optimization
                return None

        def flush() -> None:
            # one fence on the newest frame per device covers the window
            # (each device executes its dispatches in order, but ordering
            # holds only within a device — a window mixing frames pinned to
            # different devices needs one fence per device); each
            # block_until_ready is a device round-trip, so per-frame
            # fencing would pay the full RTT per frame on remote-attached
            # devices
            if not pending:
                return
            newest_per_device = {}
            for f in pending:
                newest_per_device[_dev_key(f)] = f
            for f in newest_per_device.values():
                f.block_until_ready()
            n = len(pending)
            ready = None
            if getattr(self.elem, "READS_HOST", True):
                ready = _batch_fetch(pending)
                if ready is None:
                    # heterogeneous window: restore the overlapped
                    # per-frame async copies the stacked path replaces
                    for f in pending:
                        f.prefetch_host()
            if ready is None:
                ready = pending
            for f in ready:
                f.mark_synced()
                self.elem.render(f)
            self._mark_render(n, ready)
            pending.clear()

        while True:
            item = self.pop(0)
            if item is EOS_FRAME:
                flush()
                self.elem.on_eos()
                break
            t0 = time.perf_counter()
            if window > 1:
                # no per-frame prefetch: flush() batch-fetches the whole
                # window in ONE stacked transfer (per-frame
                # copy_to_host_async is a full round trip each on a
                # remote-attached device — W of them per window was the
                # cost this path exists to avoid)
                pending.append(item)
                if len(pending) >= window:
                    flush()
            else:
                if getattr(self.elem, "READS_HOST", True) and any(
                    transfer.is_device_array(t) for t in item.tensors
                ):
                    # one coalesced (and tallied) fetch per frame via
                    # the transfer engine, instead of render()'s
                    # per-tensor on-demand np.asarray
                    item = item.with_tensors(
                        transfer.fetch_frame(item).finish()
                    ).mark_synced()
                self.elem.render(item)
                self._mark_render(1, (item,))
            self.stat(t0)
        self.ex.sink_done(self)


class Executor:
    def __init__(self, plan: ExecPlan) -> None:
        self.plan = plan
        self.stop_event = threading.Event()
        # warm-restart support (docs/resilience.md): drain() sets this to
        # park sources at a frame boundary; resume() clears it
        self.pause_event = threading.Event()
        self.errors: List[Exception] = []
        self._err_lock = threading.Lock()
        self.nodes: List[Node] = []
        self._node_of: Dict[Element, Node] = {}
        self._pending_sinks = 0
        self._sinks_cv = threading.Condition()
        self._started = False
        self.finished = False
        # stall watchdog ([executor] watchdog_timeout_ms; 0 = disabled):
        # resolved at construction so tests/operators can also override
        # the attribute on the instance before start()
        self.watchdog_timeout_ms = watchdog_timeout_ms()
        self._watchdog: Optional[threading.Thread] = None
        self.stalled = False
        # nns-san runtime sanitizer (NNS_TPU_SANITIZE=1 / [executor]
        # sanitize): instrumented channels, frame-accounting latch,
        # lock-order watch, thread-leak report. Resolved at construction
        # (before _build, which materializes the channels).
        self.sanitizer: Optional[Sanitizer] = None
        self.leaked_threads: List[str] = []
        self._threads_at_start: Optional[set] = None
        if sanitize_enabled():
            self.sanitizer = Sanitizer()
            self._err_lock = self.sanitizer.lock("executor._err_lock")
            self._sinks_cv = threading.Condition(
                self.sanitizer.lock("executor._sinks_cv")
            )
        # nns-obs metrics (obs/metrics.py): resolved at construction like
        # the sanitizer (opt-in via obs.enable() / NNS_TPU_METRICS /
        # [executor] metrics / a metrics port). None — the default —
        # keeps the hot path at one attribute check per frame.
        self.metrics = obs_metrics.get()
        self._metrics_server = None
        # nns-xray cost-model cross-check (NNS_XRAY_CROSSCHECK env /
        # [executor] xray_crosscheck): stop() then compares the static
        # transfer prediction against TransferTally measured bytes and
        # logs the verdict (docs/chain-analysis.md)
        self.xray_crosscheck = transfer.xray_crosscheck_enabled()
        self._t_run0: Optional[float] = None
        # transfer-tally baseline, re-snapshotted at start()
        self._transfer_t0: Dict[str, int] = transfer.tally.snapshot()
        self._t_run_end: Optional[float] = None
        self._build()

    def make_chan(self, size: int, node: "Node", pad: int) -> _Chan:
        """Channel factory: the instrumented SanChan under the sanitizer,
        the queue-wait-metered chan under the metrics registry, the
        lock-free _Chan otherwise (sanitizer wins when both are on —
        its conformance checks need its own channel class)."""
        if self.sanitizer is not None:
            return san_chan_cls()(size, self.sanitizer, node.name, pad)
        if self.metrics is not None:
            return _MeteredChan(size, self.metrics.histogram(
                "nns_queue_wait_us", element=node.name, pad=str(pad)
            ))
        return _Chan(size)

    # -- construction ------------------------------------------------------
    def _build(self) -> None:
        p = self.plan.pipeline
        from nnstreamer_tpu.elements.flow import Queue as _QueueElem
        from nnstreamer_tpu.elements.flow import Tee as _TeeElem

        # ---- forwarding-element elimination ----
        # tee and queue do no per-frame WORK: tee re-emits the same
        # immutable frame to every branch, queue forwards 1:1. As nodes
        # they'd each cost a thread + an extra channel hop per frame —
        # pure overhead on exactly the branched pipelines where host
        # budget is tightest. Their PLANNING roles survive elimination:
        # queue already split fusion segments at plan time (its two
        # sides stay separate threads), and its max-size-buffers rides
        # along as the rewritten link's channel depth; tee becomes
        # multi-consumer fan-out on the producer's out pad.
        links = [[l.src, l.src_pad, l.dst, l.dst_pad, None] for l in p.links]
        eliminated = set()
        for e in p.elements:
            if type(e) not in (_TeeElem, _QueueElem):
                continue
            ins = [L for L in links if L[2] is e]
            outs_ = [L for L in links if L[0] is e]
            if len(ins) != 1 or not outs_:
                continue  # odd wiring: keep the real node
            src, src_pad, _, _, in_size = ins[0]
            if type(e) is _QueueElem:
                # a queue chain (q1 ! q2) collapses to ONE channel: honor
                # the tighter bound of the two depths — q1's elimination
                # attached its depth as the link's in_size override, and
                # taking q2's unconditionally would silently widen it
                size = (
                    min(e.queue_size, in_size)
                    if in_size is not None else e.queue_size
                )
            else:
                size = in_size
            links = [L for L in links if L[0] is not e and L[2] is not e]
            for o in outs_:
                # the outgoing link may already carry a depth override
                # (a DOWNSTREAM queue eliminated earlier — element order
                # is construction order, not topological): combine, same
                # tighter-bound rule as above
                merged = (
                    min(size, o[4])
                    if size is not None and o[4] is not None
                    else (size if size is not None else o[4])
                )
                links.append([src, src_pad, o[2], o[3], merged])
            eliminated.add(e)

        # ---- whole-chain compile units (pipeline/chain_program.py) ----
        # decide once per chain (the SAME verdict nns-xray's `compiled`
        # column and the NNS-W125 lint report): an eligible chain under
        # chain_mode=auto gets ONE ChainNode absorbing every member op,
        # so its interior links never materialize channels and steady
        # state is one XLA dispatch per unrolled window. Everything else
        # keeps the per-node path — the parity oracle.
        from nnstreamer_tpu.pipeline.chain_program import (
            ChainProgram,
            decide_chain,
        )

        chain_of: Dict[Any, Tuple[Any, ChainProgram]] = {}
        for chain in self.plan.chains():
            decision = decide_chain(self.plan, chain)
            if not decision.compiles:
                continue
            program = ChainProgram(chain, decision.unroll)
            for op in chain.ops:
                chain_of[op] = (chain, program)

        # create nodes
        for e in p.elements:
            if e in eliminated:
                continue
            if isinstance(e, TensorOp):
                cp = chain_of.get(e)
                if cp is not None:
                    chain, program = cp
                    if chain.first is e:
                        node = ChainNode(self, chain, program)
                        for op in chain.ops:
                            self._node_of[op] = node
                    continue
                seg = self.plan.seg_of.get(e)
                if seg is None:  # non-traceable: host-path adapter
                    self._node_of[e] = TensorOpHostNode(self, e)
                elif seg.first is e:
                    node = FusedNode(self, seg)
                    for op in seg.ops:
                        self._node_of[op] = node
                continue
            if isinstance(e, Source):
                node = SourceNode(self, e)
            elif isinstance(e, Sink):
                node = SinkNode(self, e)
            elif isinstance(e, Routing):
                node = RoutingNode(self, e)
            elif isinstance(e, HostElement):
                node = HostNode(self, e)
            else:
                raise TypeError(f"cannot execute element {e!r}")
            self._node_of[e] = node
        self.nodes = list(dict.fromkeys(self._node_of.values()))
        # single assignment (not a per-sink += in the loop): after build,
        # only sink_done mutates the count, and it holds _sinks_cv
        self._pending_sinks = sum(
            1 for n in self.nodes if isinstance(n, SinkNode)
        )
        # wire channels: only links that cross node boundaries materialize
        for src, src_pad, dst, dst_pad, size in links:
            src_node = self._node_of[src]
            dst_node = self._node_of[dst]
            if src_node is dst_node:
                continue  # intra-segment link (fused away)
            # node-level pad indices: fused/chain nodes expose single
            # in/out pad
            sp = 0 if isinstance(src_node, (FusedNode, ChainNode)) else src_pad
            dp = 0 if isinstance(dst_node, (FusedNode, ChainNode)) else dst_pad
            while len(dst_node.in_queues) <= dp:
                dst_node.add_in_queue(dst.queue_size)
            if size is not None:  # an eliminated queue's depth override
                dst_node.in_queues[dp] = self.make_chan(size, dst_node, dp)
            if self.sanitizer is not None:
                # pin the consumer pad's negotiated spec to the channel so
                # every put is conformance-checked (STATIC specs only:
                # flexible/media links negotiate per frame)
                spec = (
                    dst.in_specs[dst_pad]
                    if dst_pad < len(dst.in_specs) else None
                )
                if isinstance(spec, TensorsSpec) and spec.is_static:
                    dst_node.in_queues[dp].expected_spec = spec
            src_node.outs.setdefault(sp, []).append((dst_node, dp))
        if self.sanitizer is not None:
            # pre-register every (node, pad) push counter (lock-free
            # per-frame increments, resize-safe snapshots) and resolve
            # the pad-row poison decision ONCE for the fused segments
            # (graph.py process_batch reads the flag, not the config)
            for n in self.nodes:
                for pad in n.outs:
                    self.sanitizer.register_pad(n.name, pad)
            for seg in self.plan.segments:
                seg.sanitize_poison = True
            for n in self.nodes:
                if isinstance(n, ChainNode):
                    n.program.sanitize_poison = True
        if self.metrics is not None:
            # per-node observability handles, created once here so the
            # per-frame path is attribute reads (no registry lookups)
            for n in self.nodes:
                n._lat_hist = self.metrics.histogram(
                    "nns_element_latency_us", element=n.name
                )
                n._frames_ctr = self.metrics.counter(
                    "nns_element_frames_total", element=n.name
                )
                n._depth_hists = [
                    self.metrics.histogram(
                        "nns_queue_depth", lo=1.0, growth=2.0,
                        nbuckets=16, element=n.name, pad=str(i),
                    )
                    for i in range(len(n.in_queues))
                ]
                if getattr(getattr(n, "seg", None), "postproc_ops", 0):
                    # fused pre/post-processing frames
                    # (docs/on-device-ops.md): one counter per segment
                    # that carries decode/image/normalize ops
                    n._postproc_ctr = self.metrics.counter(
                        "nns_fused_postproc_total", element=n.name
                    )
                if isinstance(n, ChainNode):
                    # compiled-chain telemetry (docs/observability.md):
                    # launches counts window dispatches of the resident
                    # program, fallback counts windows the per-node
                    # parity path served after the latch
                    n._chain_launch_ctr = self.metrics.counter(
                        "nns_chain_launches_total", element=n.name
                    )
                    n._chain_fallback_ctr = self.metrics.counter(
                        "nns_chain_fallback_total", element=n.name
                    )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._t_run0 = time.perf_counter()
        # run-scoped transfer accounting (pipeline/transfer.py): the
        # module tally is process-global, so this run's H2D/D2H bytes
        # are the delta against this baseline (totals()["transfer"],
        # mirrored into nns_transfer_bytes_total at stop)
        self._transfer_t0 = transfer.tally.snapshot()
        if self.metrics is not None:
            port = obs_metrics.resolve_port()
            if port is not None:
                from nnstreamer_tpu.config import conf
                from nnstreamer_tpu.obs.expo import MetricsServer

                # loopback by default: the endpoint is unauthenticated,
                # so exposing it beyond the host is an explicit opt-in
                # ([executor] metrics_host = 0.0.0.0)
                host = conf().get(
                    "executor", "metrics_host", "127.0.0.1"
                )
                try:
                    self._metrics_server = MetricsServer(
                        self.metrics, stats_fn=self.stats,
                        totals_fn=self.totals, host=host, port=port,
                    ).start()
                except OSError as exc:
                    # a scrape endpoint must never keep a pipeline from
                    # starting (port squatted by a previous run, ...)
                    _log.error("metrics endpoint failed to bind: %s", exc)
        if self.sanitizer is not None:
            # baseline BEFORE element start: threads that appear during
            # the run (element/edge service threads) and survive stop()
            # land in the leak report
            self._threads_at_start = set(threading.enumerate())
        for e in self.plan.pipeline.elements:
            e.start()
        for n in self.nodes:
            n.start()
        if self.watchdog_timeout_ms and self.watchdog_timeout_ms > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="nns-watchdog", daemon=True
            )
            self._watchdog.start()

    # -- stall watchdog ----------------------------------------------------
    def progress_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-node progress: frames processed + per-pad queue depths
        (the payload of PipelineStallError)."""
        return {
            n.name: {
                "frames": n.frames_processed,
                "queued": [len(q) for q in n.in_queues],
            }
            for n in self.nodes
        }

    def _watchdog_loop(self) -> None:
        """Detect hangs: data queued somewhere but NO node progressing for
        longer than watchdog-timeout-ms. An all-idle pipeline with empty
        queues (a live source waiting for data) is NOT a stall — the
        queued-data condition keeps the watchdog quiet there — and a
        node parked in a retry backoff (fault_gate.backoff_deadline) is
        recovering, not hung. On detection the hang becomes a typed
        PipelineStallError recorded like any node error, so wait()/run()
        report it instead of a silent timeout kill.

        Granularity: the detector cannot see INSIDE one invoke — a hang
        inside element code is precisely what it exists to catch, so a
        legitimately slow single invoke (first-frame jit compile, a
        mid-stream bucket retrace, a cold model load) is
        indistinguishable from one. The timeout must therefore be set
        ABOVE the worst-case single-invoke latency; it defaults to off
        (0)."""
        timeout_s = self.watchdog_timeout_ms / 1000.0
        beat = max(0.01, min(timeout_s / 4.0, 0.25))

        def _counts():
            # retry/disposal activity counts as progress: a node working
            # through its error policy is not hung even though
            # frames_processed stands still
            return tuple(
                (
                    n.frames_processed,
                    (n.fault_stats.errors, n.fault_stats.retries)
                    if n.fault_stats is not None else (0, 0),
                )
                for n in self.nodes
            )

        last = _counts()
        t_last = time.monotonic()
        while not self.stop_event.wait(beat):
            if self._pending_sinks <= 0 or self.errors:
                return
            cur = _counts()
            now = time.monotonic()
            if cur != last:
                last, t_last = cur, now
                continue
            if now - t_last <= timeout_s:
                continue
            if not any(
                len(q) for n in self.nodes for q in n.in_queues
            ) and not any(n.inflight() for n in self.nodes):
                # idle, not stuck: nothing queued AND nothing parked in
                # an in-flight ring is waiting to move
                t_last = now
                continue
            if any(
                n.fault_gate is not None
                and n.fault_gate.backoff_deadline >= now
                for n in self.nodes
            ):
                # a node is parked in a LEGITIMATE retry backoff (the
                # deadline is live and bounded by backoff_cap_ms) — a
                # recovering pipeline must not be killed as stalled
                t_last = now
                continue
            snapshot = self.progress_snapshot()
            self.stalled = True
            _log.error("stall watchdog fired: %s", snapshot)
            tracer = trace.get()
            if tracer is not None:
                tracer.fault("executor", "stall", None,
                             timeout_ms=self.watchdog_timeout_ms)
            self.record_error(
                PipelineStallError(self.watchdog_timeout_ms, snapshot)
            )
            return

    # -- warm restart: drain / snapshot / resume (docs/resilience.md) ------
    def drain(
        self, timeout: float = 30.0, settle_s: Optional[float] = None
    ) -> bool:
        """Quiesce the graph at a frame boundary: park the sources
        (nothing new enters), then wait until every channel is empty and
        no node has progressed across a settle window — the in-flight
        frames have all reached sinks (or been disposed by policy).
        True once quiescent; False on timeout or error (the pipeline
        keeps running either way — call resume() to continue).

        Granularity: like the stall watchdog, the detector cannot see
        inside one invoke, so the settle window must outlast the slowest
        single invoke's tail. It auto-sizes to 2x the slowest invoke
        observed so far (min 60 ms, capped at timeout/2); pass
        ``settle_s`` explicitly when the pipeline's worst invoke has not
        been seen yet (e.g. draining right after start)."""
        self.pause_event.set()
        if settle_s is None:
            worst_ms = max(
                (n.max_invoke_ms for n in self.nodes), default=0.0
            )
            settle_s = min(max(0.06, 2.0 * worst_ms / 1000.0),
                           max(0.06, timeout / 2.0))
        polls_needed = max(3, int(math.ceil(settle_s / 0.02)))
        deadline = time.monotonic() + timeout
        last = None
        settled = 0
        while time.monotonic() < deadline:
            if self.errors:
                return False
            counts = tuple(n.frames_processed for n in self.nodes)
            # a frame parked in a node's in-flight ring is neither
            # queued nor delivered — quiescence must wait for the
            # idle-input flush to hand it downstream
            empty = not any(
                len(q) for n in self.nodes for q in n.in_queues
            ) and not any(n.inflight() for n in self.nodes)
            if empty and counts == last:
                settled += 1
                if settled >= polls_needed:
                    return True
            else:
                settled = 0
            last = counts
            time.sleep(0.02)
        return False

    def snapshot(self) -> Dict[str, Any]:
        """Warm-restart snapshot: per-node stats + OOM batch ceilings +
        device-circuit fault history, plus any element/backend state
        exposed through a ``state_snapshot()`` hook (framecounter-style
        stateful backends). Call after drain() for a frame-boundary-
        consistent capture; JSON-serializable by construction so it can
        ride save_snapshot()/read_snapshot() (the parallel/checkpoint.py
        conventions: atomic replace, step-named files)."""
        snap: Dict[str, Any] = {"version": 1, "nodes": {}, "elements": {}}
        for n in self.nodes:
            snap["nodes"][n.name] = n.device_snapshot()
        for e in self.plan.pipeline.elements:
            hook = getattr(e, "state_snapshot", None)
            if hook is None:
                hook = getattr(
                    getattr(e, "backend", None), "state_snapshot", None
                )
            if callable(hook):
                snap["elements"][e.name] = hook()
        return snap

    def save_snapshot(self, path: str) -> Dict[str, Any]:
        """snapshot() to a JSON file via write-then-atomic-replace (the
        checkpoint.py discipline: a crashed writer never leaves a
        half-written snapshot where resume will read it)."""
        import json
        import os

        snap = self.snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        return snap

    @staticmethod
    def read_snapshot(path: str) -> Dict[str, Any]:
        import json

        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def restore(self, snap: Dict[str, Any]) -> None:
        """Apply a snapshot(): node counters land now; governor/circuit/
        fault-stats state is stashed per node and re-armed by the
        service loops (before start()) or applied directly (already
        running). Elements restore through their ``state_restore()``
        hook. Unknown node/element names are skipped — a restarted
        pipeline may legitimately differ at the edges."""
        by_name = {n.name: n for n in self.nodes}
        for name, d in (snap.get("nodes") or {}).items():
            node = by_name.get(name)
            if node is None:
                _log.warning("restore: no node %r in this pipeline", name)
                continue
            node.restore_state(d)
            if not self._started:
                continue
            # already running: the loop-built objects exist — apply now
            node._apply_pending_restore()
        elems = {e.name: e for e in self.plan.pipeline.elements}
        for name, d in (snap.get("elements") or {}).items():
            e = elems.get(name)
            if e is None:
                _log.warning("restore: no element %r in this pipeline", name)
                continue
            hook = getattr(e, "state_restore", None)
            if hook is None:
                hook = getattr(
                    getattr(e, "backend", None), "state_restore", None
                )
            if callable(hook):
                hook(d)

    def resume(self, snap: Optional[Dict[str, Any]] = None) -> None:
        """Un-park the sources after drain() — with ``snap``, restore it
        first, so drain()+snapshot() / restore()+resume() round-trips
        warm-restart a pipeline with its exact per-element stats, batch
        ceilings, and fault history (the persistent XLA compilation
        cache makes the recompile side fast; docs/resilience.md)."""
        if snap is not None:
            self.restore(snap)
        self.pause_event.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every sink saw EOS (or error). True if completed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._sinks_cv:
            while self._pending_sinks > 0 and not self.errors:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._sinks_cv.wait(timeout=0.1 if remaining is None else min(0.1, remaining))
        return self._pending_sinks == 0

    def sink_done(self, node: SinkNode) -> None:
        with self._sinks_cv:
            self._pending_sinks -= 1
            self._sinks_cv.notify_all()

    def record_error(self, exc: Exception) -> None:
        with self._err_lock:
            self.errors.append(exc)
        self.stop_event.set()
        with self._sinks_cv:
            self._sinks_cv.notify_all()

    def stop(self) -> None:
        """Shut the pipeline down: join every thread the executor started
        (service threads AND the watchdog) under one bounded budget,
        stop the elements, then report stragglers in
        ``self.leaked_threads`` instead of silently leaking daemons.
        Under the sanitizer, threads that appeared during the run
        (element/edge service threads) and outlived shutdown are
        reported too, and the per-node frame-accounting invariant is
        latched at clean EOS."""
        if self.finished:
            return
        self.stop_event.set()
        self._t_run_end = time.perf_counter()
        if self._metrics_server is not None:
            # closed BEFORE the leak sweep: the exposition thread is
            # executor-started and must not read as a leaked daemon
            self._metrics_server.close()
            self._metrics_server = None
        threads = [n.thread for n in self.nodes if n.thread is not None]
        if self._watchdog is not None:
            threads.append(self._watchdog)
        deadline = time.monotonic() + 5.0  # total, not per-thread
        for t in threads:
            t.join(timeout=max(0.05, deadline - time.monotonic()))
        if self.metrics is not None:
            # after the join so late in-flight fetches are counted
            transfer.mirror_into(self.metrics)
        if self.xray_crosscheck:
            # after the join for the same reason: the tally must hold
            # every fetch this run will ever make before it is compared
            try:
                cc = self.transfer_crosscheck()
                level = (
                    _log.warning if any(cc["delta"].values()) else _log.info
                )
                level(
                    "xray cross-check: predicted=%s measured=%s delta=%s",
                    cc["predicted"], cc["measured"], cc["delta"],
                )
            except Exception as exc:  # noqa: BLE001 — advisory, never fatal
                _log.warning("xray cross-check failed: %s", exc)
        for e in self.plan.pipeline.elements:
            e.stop()
        leaked = [t.name for t in threads if t.is_alive()]
        if self.sanitizer is not None and self._threads_at_start is not None:
            ours = set(threads)
            me = threading.current_thread()
            leaked += [
                t.name for t in threading.enumerate()
                if t.is_alive() and t is not me and t not in ours
                and t not in self._threads_at_start
            ]
        self.leaked_threads = leaked
        if leaked:
            _log.warning("threads alive after shutdown: %s", leaked)
            if self.sanitizer is not None:
                self.sanitizer.thread_leak(leaked)
        if (
            self.sanitizer is not None
            and self._pending_sinks == 0
            and not self.errors
        ):
            for n in self.nodes:
                if self._accounting_eligible(n):
                    self.sanitizer.check_accounting(n)
        self.finished = True

    def _accounting_eligible(self, n: Node) -> bool:
        """Nodes whose offered == delivered + dropped + routed invariant
        is well-defined: fused segments (pure 1:1 TensorOps) and nodes
        whose element declares SAN_ONE_TO_ONE — minus any with upstream
        QoS wired (those skips aren't attributable per node) and any
        whose thread never finished (counts still moving)."""
        if isinstance(n, FusedNode):
            elem = n.seg.first
        elif isinstance(n, ChainNode):
            # a compiled chain is 1:1 end to end (pure TensorOps, the
            # same invariant per member segment) — the whole-chain node
            # inherits the fused accounting contract
            elem = n.chain.first
        else:
            elem = getattr(n, "elem", None)
            if elem is None \
                    or not getattr(type(elem), "SAN_ONE_TO_ONE", False):
                return False
        if elem.qos_sources:
            return False
        return not (n.thread is not None and n.thread.is_alive())

    # -- introspection (per-element proctime, §5.1 parity) ----------------
    def stats(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        t_end = self._t_run_end or time.perf_counter()
        elapsed = (
            t_end - self._t_run0 if self._t_run0 is not None else 0.0
        )
        for n in self.nodes:
            s: Dict[str, Any] = {
                "frames": n.frames_processed,
                "proc_ms_ema": round(n.proc_time_ema_ms, 3),
            }
            if elapsed > 0:
                s["fps"] = round(n.frames_processed / elapsed, 2)
            if n.in_queues:
                s["queue_depth"] = [len(q) for q in n.in_queues]
            # nns-obs percentiles (docs/observability.md): per-invoke
            # latency tails and queue-wait tails when metrics are on
            lat = n._lat_hist
            if lat is not None and lat.count:
                p50, p95, p99 = lat.percentiles()
                s["latency_p50_ms"] = round(p50 / 1000.0, 3)
                s["latency_p95_ms"] = round(p95 / 1000.0, 3)
                s["latency_p99_ms"] = round(p99 / 1000.0, 3)
            whs = [
                q.wait_hist for q in n.in_queues
                if isinstance(q, _MeteredChan) and q.wait_hist.count
            ]
            wh = whs[0] if len(whs) == 1 else None
            if len(whs) > 1:
                # multi-pad joins: merge the pads' histograms (same
                # ladder by construction) so a backpressured pad can't
                # hide behind a trickle-fed one; per-pad detail stays
                # available as the raw nns_queue_wait_us series
                wh = obs_metrics.Histogram(
                    whs[0].name, {}, lo=whs[0].lo, growth=whs[0].growth,
                    nbuckets=len(whs[0].counts),
                )
                for h in whs:
                    wh.merge(h)
            if wh is not None:
                s["queue_wait_p50_ms"] = round(
                    wh.quantile(0.50) / 1000.0, 3
                )
                s["queue_wait_p99_ms"] = round(
                    wh.quantile(0.99) / 1000.0, 3
                )
            # filter invoke stats (reference latency/throughput read-only
            # properties, tensor_filter.c:334-433) surface per element
            elem = getattr(n, "elem", None)
            istats = getattr(elem, "invoke_stats", None)
            if istats is not None and istats.total_invoke_num:
                s["invoke_count"] = istats.total_invoke_num
                s["invoke_latency_us"] = round(istats.latency_us, 1)
                s["invoke_throughput_fps"] = round(istats.throughput_fps, 1)
            # serving elements (tensor_llm_serversrc) surface the
            # batcher's token-granularity counters the same way
            sstats = getattr(elem, "serving_stats", None)
            if callable(sstats):
                got = sstats()
                if got:
                    s.update({f"serving_{k}": v for k, v in got.items()})
            # fused pre/post-processing (docs/on-device-ops.md): the
            # number of decode/image/normalize ops riding this segment
            # (nns-top renders the `fused-post` note from it)
            pp = getattr(getattr(n, "seg", None), "postproc_ops", 0)
            if pp:
                s["fused_postproc"] = pp
            # compiled chains (pipeline/chain_program.py): window width,
            # resident-program dispatches, and the parity-path windows
            # served after a fallback latch (nns-top renders the `chain`
            # note from chain_segments)
            if isinstance(n, ChainNode):
                s["chain_segments"] = len(n.chain.segments)
                s["chain_unroll"] = n.program.unroll
                s["chain_launches"] = n.program.launches
                if n.fallback_windows:
                    s["chain_fallback_windows"] = n.fallback_windows
                if n.fallback_latched:
                    s["device_degraded"] = 1
            # micro-batching observability (fused segments and batchable
            # host filters): avg batch size, pad waste, straggler wait
            bstats = getattr(
                getattr(n, "seg", None), "batch_stats", None
            ) or getattr(elem, "batch_stats", None)
            if bstats is not None and bstats.batches:
                s.update(bstats.snapshot())
            # fault-tolerance counters (pipeline/faults.py): per-node
            # errors/drops/routes/retries when an error policy is active
            fstats = n.fault_stats
            if fstats is not None and (fstats.errors or fstats.retries):
                s.update(fstats.snapshot())
            # deadline-aware shedding (docs/edge-serving.md)
            if n.deadline_shed:
                s["deadline_shed"] = n.deadline_shed
            # device resilience (pipeline/device_faults.py,
            # docs/resilience.md): circuit + OOM-ladder state when the
            # node has seen device-plane trouble
            circ = n.device_circuit
            if circ is not None and (circ.faults or circ.opens):
                s["device_degraded"] = 1 if circ.open else 0
                s["device_faults"] = circ.faults
                s["device_fault_kinds"] = dict(circ.kinds)
                s["device_eager_invokes"] = circ.eager_invokes
                s["device_circuit_opens"] = circ.opens
            gov = n.bucket_governor
            if gov is not None and gov.ooms:
                s["oom_events"] = gov.ooms
                s["batch_ceiling"] = gov.ceiling
                s["oom_reprobes"] = gov.reprobes
                if gov.degraded:
                    s["device_degraded"] = 1
                else:
                    s.setdefault("device_degraded", 0)
            # admission control (edge/admission.py): per-server budget
            # and per-client counters when the element serves a fleet
            astats = getattr(elem, "admission_stats", None)
            if callable(astats):
                got = astats()
                if got:
                    s.update({f"adm_{k}": v for k, v in got.items()})
            # fleet client (edge/fleet.py): per-endpoint health/served/
            # failover rows plus hedge/duplicate counters when the
            # element dispatches over a hosts= endpoint fleet
            flstats = getattr(elem, "fleet_stats", None)
            if callable(flstats):
                got = flstats()
                if got:
                    s.update({f"fleet_{k}": v for k, v in got.items()})
            # circuit-breaker fallback (tensor_filter fallback-framework/
            # fallback-model): primary failures, opens, fallback serves
            cstats = getattr(elem, "circuit_stats", None)
            if callable(cstats):
                got = cstats()
                if got:
                    s.update({f"cb_{k}": v for k, v in got.items()})
            # replica failover (parallel/replicas.py): health, failovers,
            # per-replica serve/fault counts when replicas=N is on
            rstats = getattr(elem, "replica_stats", None)
            if callable(rstats):
                got = rstats()
                if got:
                    s.update({f"rep_{k}": v for k, v in got.items()})
            # serving plane (serving_plane/plane.py): shared-batcher
            # occupancy/queue plus THIS stream's admit/serve counts
            # when the filter serves through a plane
            plstats = getattr(elem, "plane_stats", None)
            if callable(plstats):
                got = plstats()
                if got:
                    s.update({f"plane_{k}": v for k, v in got.items()})
            # sanitizer counters (pipeline/sanitize.py): per-node frame
            # accounting as the instrumented channels saw it
            if self.sanitizer is not None:
                s.update(self.sanitizer.node_snapshot(n))
            out[n.name] = s
        return out

    def totals(self) -> Dict[str, Any]:
        """Pipeline-wide frame accounting (VERDICT r4 #6, the soak
        test's leak/loss detector): frames the sources produced must be
        accounted for as rendered at sinks, dropped with a reason, or
        (mid-run) in flight. Cardinality-changing elements (aggregator
        windows, frames-per-tensor batching, demux fan-out) make the
        identity chain-specific; for 1:1 chains plus rate/if elements:
        produced + created == rendered + dropped after EOS."""
        produced = rendered = 0
        dropped: Dict[str, int] = {}
        created: Dict[str, int] = {}
        for n in self.nodes:
            if isinstance(n, SourceNode):
                produced += n.frames_processed
            elif isinstance(n, SinkNode):
                rendered += n.frames_processed
            elem = getattr(n, "elem", None)
            # explicit contract: drop_stats() = frames REMOVED by
            # reason; create_stats() = frames ADDED by reason (two
            # methods, so a misnamed key cannot land in the wrong
            # bucket and silently skew the balance)
            for attr, bucket in (("drop_stats", dropped),
                                 ("create_stats", created)):
                fn = getattr(elem, attr, None)
                if callable(fn):
                    for reason, count in fn().items():
                        bucket[reason] = bucket.get(reason, 0) + count
            # error-policy accounting: dropped frames leave the stream
            # with a reason; ROUTED frames reach a dead-letter sink and
            # count as rendered there, so they stay out of `dropped`
            fs = n.fault_stats
            if fs is not None:
                for reason, count in (
                    ("on-error-drop", fs.dropped - fs.routed_unlinked),
                    ("on-error-route-unlinked", fs.routed_unlinked),
                ):
                    if count:
                        dropped[reason] = dropped.get(reason, 0) + count
            if n.deadline_shed:
                dropped["deadline-shed"] = (
                    dropped.get("deadline-shed", 0) + n.deadline_shed
                )
        return {
            "produced": produced,
            "rendered": rendered,
            "dropped": dropped,
            "created": created,
            "balance": produced + sum(created.values())
            - rendered - sum(dropped.values()),
            "transfer": self.transfer_totals(),
        }

    def transfer_totals(self) -> Dict[str, int]:
        """This run's host<->device traffic through the transfer engine
        (pipeline/transfer.py), bytes by direction — the module tally
        minus the baseline start() snapshotted. ``d2h == 0`` across a
        device-resident handoff chain is the zero-host-materialization
        invariant docs/streaming.md promises (and tests assert).
        The tally is process-global, so executors running CONCURRENTLY
        in one process see each other's traffic in this delta — assert
        on it from serial runs."""
        now = transfer.tally.snapshot()
        base = self._transfer_t0
        return {
            "h2d": now["h2d_bytes"] - base["h2d_bytes"],
            "d2h": now["d2h_bytes"] - base["d2h_bytes"],
        }

    def transfer_crosscheck(self) -> Dict[str, Any]:
        """Verify the static cost model against this run: the predicted
        host-boundary bytes (analysis/costmodel.py
        ``plan_transfer_boundaries`` — the same plan this executor
        built from) weighed by each boundary's OWN producer frame count,
        against the ``TransferTally`` measured totals. Rate limiters and
        aggregation windows change per-node cardinality, which is why
        each boundary multiplies by its producer node's
        ``frames_processed`` rather than a single pipeline frame count.
        Returns ``{"predicted": .., "measured": .., "delta": ..}``; a
        zero delta on a serial run is the model's proof
        (docs/chain-analysis.md "Runtime cross-check")."""
        from nnstreamer_tpu.analysis.costmodel import (
            plan_transfer_boundaries,
        )

        elems = {e.name: e for e in self.plan.pipeline.elements}
        boundaries = plan_transfer_boundaries(self.plan)
        predicted = {"h2d": 0, "d2h": 0}
        for b in boundaries:
            node = self._node_of.get(elems.get(b.producer))
            if node is None:
                continue
            predicted[b.direction] += b.bytes_per_frame * node.frames_processed
        measured = self.transfer_totals()
        # compiled chains (pipeline/chain_program.py): the model must
        # predict ZERO interior boundary bytes for a chain one resident
        # program serves, and the executor makes the measurement
        # structural — member ops all map to ONE node, so interior
        # links never materialize channels and nothing can cross there.
        chains = []
        for n in self.nodes:
            if not isinstance(n, ChainNode):
                continue
            member = {op.name for op in n.chain.ops}
            interior = 0
            for b in boundaries:
                if b.producer in member and b.consumer in member:
                    node = self._node_of.get(elems.get(b.producer))
                    frames = node.frames_processed if node else 0
                    interior += b.bytes_per_frame * frames
            chains.append({
                "chain": n.name,
                "unroll": n.program.unroll,
                "launches": n.program.launches,
                "predicted_interior": interior,
                "measured_interior": 0,
            })
        return {
            "predicted": predicted,
            "measured": measured,
            "delta": {
                k: measured[k] - predicted[k] for k in ("h2d", "d2h")
            },
            "chains": chains,
        }
