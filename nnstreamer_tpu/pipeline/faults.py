"""Fault-tolerant execution: per-element error policies for streaming.

The reference treats any element error as pipeline-fatal (GST_FLOW_ERROR
unwinds the whole stream), and the executor inherited that: one exception
in a node thread poisoned every queue. For a serving pipeline ("heavy
traffic from millions of users", ROADMAP) a single malformed frame or a
transient backend hiccup must not kill the stream. GStreamer's flow-return
design shows per-buffer error semantics composing with streaming; this
module is the TPU-native equivalent:

- ``on-error`` (declared by tensor_filter / tensor_transform /
  tensor_converter / tensor_decoder, and tensor_chaos):

  * ``stop``  — fail fast with the original typed exception (default;
    the reference-faithful behavior).
  * ``drop``  — skip the offending frame, keep streaming; counted.
  * ``retry`` — re-invoke with jittered exponential backoff
    (``retry-max``, ``retry-backoff-ms``; capped). Exhausted retries
    degrade to ``route`` when an error pad is linked, else ``drop`` —
    retry is a keep-streaming policy, never a delayed crash.
  * ``route`` — wrap the frame + exception into an ERROR FRAME emitted
    on a dedicated error pad (``<name>.src_1``) that links to any sink:
    the dead-letter queue. An unlinked error pad silently drops (nns-lint
    NNS-W107 warns about that wiring).

- :class:`FaultPolicy` resolution mirrors batching: element properties
  override the ``[executor]`` config defaults (``NNS_TPU_EXECUTOR_ON_ERROR``
  etc.), first element in chain order that sets a knob wins.
- :class:`FaultGate` is the per-node applicator the executor wraps around
  frame work; batched service loops split a failed batch through it
  per-frame so one bad frame never discards its batchmates.
- :class:`PipelineStallError` is the stall watchdog's typed conversion of
  a hang (executor monitor thread) — a per-node progress snapshot instead
  of a silent ``TimeoutError``.
"""

from __future__ import annotations

import random
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

# schema + pad installer live in elements.base (so element classes can
# spread FAULT_PROPS without importing the pipeline package); re-exported
# here because this module is the fault layer's front door
from nnstreamer_tpu.elements.base import (  # noqa: F401  (re-export)
    FAULT_PROPS,
    ON_ERROR_CHOICES,
    install_error_pad,
)
from nnstreamer_tpu.log import get_logger

_log = get_logger("faults")


class PipelineStallError(RuntimeError):
    """The stall watchdog detected queued data with no node progressing
    for longer than ``watchdog-timeout-ms``. Carries a per-node progress
    snapshot ({node: {frames, queued}}) so the hang localizes without a
    debugger attached."""

    def __init__(self, timeout_ms: float, snapshot: Dict[str, Dict]) -> None:
        self.timeout_ms = timeout_ms
        self.snapshot = snapshot
        stalled = [
            f"{name}(frames={s['frames']}, queued={s['queued']})"
            for name, s in sorted(snapshot.items())
            if any(s["queued"])
        ] or [f"{n}(frames={s['frames']})" for n, s in sorted(snapshot.items())]
        super().__init__(
            f"pipeline made no progress for {timeout_ms:.0f} ms with data "
            f"queued; suspect node(s): {', '.join(stalled)}"
        )


@dataclass(frozen=True)
class FaultPolicy:
    """Resolved error-policy knobs for one execution node."""

    on_error: str = "stop"
    retry_max: int = 3
    backoff_ms: float = 10.0
    backoff_cap_ms: float = 1000.0

    @property
    def active(self) -> bool:
        return self.on_error != "stop"


def _executor_fault_defaults() -> dict:
    """[executor] fault-tolerance defaults (env ``NNS_TPU_EXECUTOR_*``
    outranks ini). Malformed values fall back with a warning — a typo'd
    ini line must not fail every pipeline compile."""
    from nnstreamer_tpu.config import conf

    c = conf()

    def _num(key: str, cast, fallback):
        raw = c.get("executor", key, str(fallback))
        try:
            return cast(raw)
        except ValueError:
            _log.warning(
                "[executor] %s=%r is not a valid %s; using %s",
                key, raw, cast.__name__, fallback,
            )
            return fallback

    on_error = c.get("executor", "on_error", "stop").strip().lower()
    if on_error not in ON_ERROR_CHOICES:
        _log.warning(
            "[executor] on_error=%r not one of %s; using 'stop'",
            on_error, "/".join(ON_ERROR_CHOICES),
        )
        on_error = "stop"
    return {
        "on-error": on_error,
        "retry-max": _num("retry_max", int, 3),
        "retry-backoff-ms": _num("retry_backoff_ms", float, 10.0),
        "retry-backoff-cap-ms": _num("retry_backoff_cap_ms", float, 1000.0),
        "watchdog-timeout-ms": _num("watchdog_timeout_ms", float, 0.0),
    }


def watchdog_timeout_ms() -> float:
    """Executor stall-watchdog timeout (0 = disabled, the default)."""
    return _executor_fault_defaults()["watchdog-timeout-ms"]


def resolve_fault_policy(elements: Sequence[Any]) -> FaultPolicy:
    """Merge element-level fault properties over the executor default.

    Chain-order scan, first element that sets a knob wins (the same
    discipline as resolve_batch_config; for a fused segment the ops are
    the segment members)."""
    defaults = _executor_fault_defaults()
    on_error: Optional[str] = None
    retry_max: Optional[int] = None
    backoff_ms: Optional[float] = None

    def _coerce(elem, prop: str, fn, raw):
        try:
            return fn(raw)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"{getattr(elem, 'name', elem)}: bad {prop}={raw!r}: {exc}"
            ) from exc

    for e in elements:
        get = getattr(e, "get_property", None)
        if get is None:
            continue
        if on_error is None and get("on-error") is not None:
            raw = str(get("on-error")).strip().lower()
            if raw not in ON_ERROR_CHOICES:
                raise ValueError(
                    f"{getattr(e, 'name', e)}: on-error={raw!r} not one of "
                    f"{'/'.join(ON_ERROR_CHOICES)}"
                )
            on_error = raw
        if retry_max is None and get("retry-max") is not None:
            retry_max = _coerce(e, "retry-max", int, get("retry-max"))
        if backoff_ms is None and get("retry-backoff-ms") is not None:
            backoff_ms = _coerce(
                e, "retry-backoff-ms", float, get("retry-backoff-ms")
            )
    if on_error is None:
        on_error = defaults["on-error"]
    if retry_max is None:
        retry_max = defaults["retry-max"]
    if backoff_ms is None:
        backoff_ms = defaults["retry-backoff-ms"]
    return FaultPolicy(
        on_error=on_error,
        retry_max=max(0, int(retry_max)),
        backoff_ms=max(0.0, float(backoff_ms)),
        backoff_cap_ms=max(0.0, float(defaults["retry-backoff-cap-ms"])),
    )


def backoff_s(attempt: int, policy: FaultPolicy, rng: random.Random) -> float:
    """Jittered exponential backoff for the ``attempt``-th retry
    (0-based): base × 2^attempt ms, capped at backoff_cap_ms, with
    uniform jitter in [0.5, 1.0]× so synchronized failures de-correlate
    instead of retrying in lockstep."""
    full_ms = min(policy.backoff_ms * (2.0 ** attempt), policy.backoff_cap_ms)
    return (0.5 + 0.5 * rng.random()) * full_ms / 1000.0


def frame_deadline_expired(meta: Dict[str, Any],
                           now: Optional[float] = None) -> bool:
    """True when a frame's client SLO can no longer be met: the frame
    carries a ``deadline_ms`` budget (stamped by tensor_query_client or
    any producer) AND an ``admit_t`` local-monotonic admission stamp
    (tensor_query_serversrc, or the producer itself), and the budget has
    elapsed. Frames without BOTH keys never expire — shedding is strictly
    opt-in per request (docs/edge-serving.md)."""
    deadline_ms = meta.get("deadline_ms")
    if deadline_ms is None:
        return False
    t0 = meta.get("admit_t")
    if t0 is None:
        return False
    if now is None:
        now = time.monotonic()
    try:
        return (now - float(t0)) * 1000.0 >= float(deadline_ms)
    except (TypeError, ValueError):
        return False


def notify_shed(frame, node_name: str) -> None:
    """A node shed `frame` at dequeue (deadline missed before device
    time was spent). Record the trace event, and — when the frame is an
    admitted edge request (``_nns_srv`` meta) — NACK the client and
    release its admission budget so the request still reaches a terminal
    outcome. The edge import is lazy: pipelines that never shed edge
    frames never load the query layer."""
    from nnstreamer_tpu import trace

    tracer = trace.get()
    meta = frame.meta
    if tracer is not None:
        tracer.fault(
            node_name, "deadline-shed", None,
            frame_id=meta.get("frame_id"),
            deadline_ms=meta.get("deadline_ms"),
        )
    srv = meta.get("_nns_srv")
    if srv is not None:
        from nnstreamer_tpu.edge.query import nack_for_shed

        nack_for_shed(
            srv, meta.get("client_id"), frame_id=meta.get("frame_id")
        )


def notify_drain_flush(frame, node_name: str) -> None:
    """A draining query server flushed ``frame`` from its admitted queue
    before it consumed device time (``drain(flush_queued=True)`` —
    docs/edge-serving.md "Running a fleet"): record the trace event and
    NACK the client with the terminal-after-retry reason ``draining`` —
    a fleet client re-routes the request to another endpoint, so a
    rolling restart loses zero accepted requests. The admission budget
    releases through the same PR-6 path as every other disposal. Lazy
    edge import, same discipline as notify_shed."""
    from nnstreamer_tpu import trace

    meta = getattr(frame, "meta", None) or {}
    tracer = trace.get()
    if tracer is not None:
        tracer.fault(
            node_name, "drain-flush", None,
            frame_id=meta.get("frame_id"),
        )
    srv = meta.get("_nns_srv")
    if srv is not None:
        from nnstreamer_tpu.edge.query import drain_flushed

        drain_flushed(
            srv, meta.get("client_id"), frame_id=meta.get("frame_id")
        )


def notify_discard(frame, node_name: str, action: str) -> None:
    """A fault policy disposed of ``frame`` (``drop``: consumed outright;
    ``route``: delivered to a dead-letter consumer). When the frame is an
    admitted edge request (``_nns_srv`` meta), return its admission
    budget — and for drops, NACK the client (reason ``failed``; reason
    ``draining`` while the origin server is in a graceful drain, so the
    disposal reads as a restart artifact a fleet client re-routes, not a
    verdict) so the request still reaches a terminal outcome instead of
    a silent client-side timeout. Routed frames get no NACK: the
    dead-letter consumer now owns the request's fate (it may even reply
    through the serversink). Lazy edge import, same discipline as
    notify_shed."""
    meta = getattr(frame, "meta", None)
    if not meta:
        return
    srv = meta.get("_nns_srv")
    if srv is None:
        return
    from nnstreamer_tpu.edge.query import discard_admitted

    discard_admitted(
        srv, meta.get("client_id"), action,
        frame_id=meta.get("frame_id"),
    )


def make_error_frame(frame, exc: Exception, element: str):
    """Dead-letter frame: the ORIGINAL input tensors (so the consumer can
    replay or inspect the offending payload) plus structured error meta."""
    return frame.with_meta(
        error=True,
        error_element=element,
        error_type=type(exc).__name__,
        error_msg=str(exc),
    )


class FaultStats:
    """Single-writer (node thread) fault counters; GIL-atomic reads give
    observers a consistent-enough snapshot (same contract as BatchStats)."""

    __slots__ = ("errors", "dropped", "routed", "routed_unlinked",
                 "retries", "retry_exhausted", "backoff_total_s")

    def __init__(self) -> None:
        self.errors = 0           # raw element failures observed
        self.dropped = 0          # frames consumed by drop (incl. degraded)
        self.routed = 0           # error frames delivered to the error pad
        self.routed_unlinked = 0  # route policy with no error-pad consumer
        self.retries = 0          # re-invocations attempted
        self.retry_exhausted = 0  # frames whose retry budget ran out
        self.backoff_total_s = 0.0

    def snapshot(self) -> dict:
        return {
            "errors": self.errors,
            "error_dropped": self.dropped,
            "error_routed": self.routed,
            "error_retries": self.retries,
            "error_backoff_ms": round(self.backoff_total_s * 1000.0, 3),
        }


class FaultGate:
    """Applies one node's resolved :class:`FaultPolicy` around per-frame
    work. ``process(frame, fn)`` returns ``(delivered, result)``:
    ``delivered`` False means the policy consumed the frame (dropped or
    routed) and streaming continues. ``stop`` raises the original typed
    exception unchanged — the executor only builds a gate when the
    policy is active, so the default path stays zero-overhead."""

    def __init__(
        self,
        policy: FaultPolicy,
        name: str,
        stop_event=None,
        route: Optional[Callable[[Any], None]] = None,
        raise_through: Tuple[type, ...] = (),
        stop_exc: Optional[type] = None,
    ) -> None:
        self.policy = policy
        self.name = name
        self.stop_event = stop_event
        self.route = route  # callable(error_frame) when the pad is linked
        self.raise_through = raise_through
        self.stop_exc = stop_exc
        self.stats = FaultStats()
        # nns-obs registry resolved ONCE at gate construction (the
        # executor discipline): get() probes env+config on the None
        # path, which must not run per dropped/retried frame
        from nnstreamer_tpu.obs import metrics as obs_metrics

        self._obs_reg = obs_metrics.get()
        # monotonic deadline of an in-progress backoff sleep (0.0 = not
        # parked): the stall watchdog reads this so a node legitimately
        # backing off is never mistaken for a hang
        self.backoff_deadline = 0.0
        # deterministic per-node jitter stream (content-stable seed, not
        # hash(): PYTHONHASHSEED must not change retry timing between runs)
        self._rng = random.Random(zlib.crc32(name.encode()))

    def process(self, frame, fn: Callable[[Any], Any]) -> Tuple[bool, Any]:
        policy = self.policy
        attempt = 0
        while True:
            try:
                return True, fn(frame)
            except self.raise_through:
                raise
            except Exception as exc:  # noqa: BLE001 — the policy decides
                self.stats.errors += 1
                if policy.on_error == "retry" and attempt < policy.retry_max:
                    delay = backoff_s(attempt, policy, self._rng)
                    attempt += 1
                    self.stats.retries += 1
                    self.stats.backoff_total_s += delay
                    self._trace("retry", exc, attempt=attempt,
                                backoff_ms=round(delay * 1000.0, 3))
                    self._sleep(delay)
                    continue
                return False, self._dispose(frame, exc, attempt)

    def _dispose(self, frame, exc: Exception, attempts: int):
        """The frame failed past any retry budget: drop or route it."""
        policy = self.policy
        mode = policy.on_error
        if mode == "stop":
            raise exc
        if mode == "retry":
            # exhausted: degrade to the dead-letter pad when wired, else
            # drop — a retry policy never turns into a delayed crash
            self.stats.retry_exhausted += 1
            mode = "route" if self.route is not None else "drop"
        if mode == "route":
            if self.route is not None:
                self.stats.routed += 1
                self._trace("route", exc)
                err = make_error_frame(frame, exc, self.name)
                if frame.meta.get("_nns_srv") is not None:
                    # the admission budget is released HERE (below); a
                    # dead-letter consumer replying through the
                    # serversink must not release it a second time
                    err = err.with_meta(_nns_budget_released=True)
                self.route(err)
                notify_discard(frame, self.name, "route")
                return None
            self.stats.routed_unlinked += 1
            self.stats.dropped += 1
            self._trace("route-unlinked", exc)
            _log.warning(
                "%s: on-error=route but the error pad is unlinked; "
                "dropping frame (%s: %s)", self.name, type(exc).__name__, exc,
            )
            notify_discard(frame, self.name, "drop")
            return None
        self.stats.dropped += 1
        self._trace("drop", exc, attempts=attempts)
        _log.debug("%s: dropped frame after %s: %s",
                   self.name, type(exc).__name__, exc)
        notify_discard(frame, self.name, "drop")
        return None

    def _trace(self, action: str, exc: Exception, **extra) -> None:
        from nnstreamer_tpu import trace

        tracer = trace.get()
        if tracer is not None:
            tracer.fault(self.name, action, exc, **extra)
        reg = self._obs_reg
        if reg is not None:
            # cold path (one event per retry/drop/route, not per frame):
            # the per-event counter lookup is fine here
            reg.counter(
                "nns_fault_events_total", element=self.name, action=action
            ).inc()

    def _sleep(self, delay: float) -> None:
        """Bounded-slice backoff sleep that still honors the executor's
        stop event — a parked retry must not stall pipeline teardown."""
        deadline = time.monotonic() + delay
        self.backoff_deadline = deadline  # visible to the stall watchdog
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                if self.stop_event is not None and self.stop_event.is_set():
                    if self.stop_exc is not None:
                        raise self.stop_exc()
                    return
                time.sleep(min(0.05, remaining))
        finally:
            self.backoff_deadline = 0.0
