"""Adaptive micro-batching for fused segments (and batch-capable filters).

The executor pipelines frames across stages, but a per-frame jitted call
leaves most of a TPU's matmul units idle — device utilization scales with
the leading axis, not with dispatch count. The StreamTensor/Hermes lesson
(PAPERS.md): streaming dataflow frameworks win by aggregating stream
elements into device-sized work units while *bounding* the latency cost.
This module is that aggregation layer:

- :class:`BatchConfig` — resolved knobs for one execution node. Stream
  properties (``batching=true``, ``max-batch``, ``batch-timeout-ms``,
  ``batch-buckets`` on ``tensor_filter``) override the executor-level
  defaults from the ``[executor]`` config section (env:
  ``NNS_TPU_EXECUTOR_BATCHING`` etc.).
- :class:`BatchCollector` — drains up to ``max-batch`` queued frames from
  a node's input channel. Adaptive discipline: when the queue is deep the
  collector takes what is there and returns immediately (queue depth is
  free batch — NO added latency under load); only when trickle-fed (the
  blocking pop yielded a single frame and the queue is empty) does it
  wait up to ``batch-timeout-ms`` for stragglers.
- :class:`BatchStats` — per-segment observability: average batch size,
  padding waste, and collector wait time, surfaced as read-only
  ``tensor_filter`` properties next to ``latency``/``throughput`` and in
  ``Executor.stats()``.

Bucketing: batch sizes are rounded UP to a fixed bucket ladder
(default 1,2,4,...,max-batch) and padded with replicas of the last frame,
so each fused segment retraces at most O(log max-batch) times instead of
once per observed batch size. The pad rows are computed and discarded —
``pad-waste-pct`` reports the cost.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

from nnstreamer_tpu.elements.base import _parse_bool
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import EOS_FRAME

_log = get_logger("batching")


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch``, always ending exactly at
    ``max_batch`` (so max-batch=6 buckets as 1,2,4,6 — the cap the user
    asked for is always a real bucket, never overshot)."""
    max_batch = max(1, int(max_batch))
    out: List[int] = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


@dataclass(frozen=True)
class BatchConfig:
    """Resolved micro-batching knobs for one execution node."""

    enabled: bool = False
    max_batch: int = 8
    timeout_ms: float = 1.0
    buckets: Tuple[int, ...] = ()

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n is already clamped to max_batch)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1] if self.buckets else n

    @property
    def active(self) -> bool:
        return self.enabled and self.max_batch > 1


def chain_window_config(unroll: int) -> BatchConfig:
    """Window-collection config for a compiled chain's service loop
    (pipeline/chain_program.py): drain up to ``unroll`` queued frames
    per window and NEVER wait for one to fill (timeout 0) — a
    trickle-fed chain keeps per-frame latency while a saturated one
    amortizes its single XLA launch over full windows. The bucket
    ladder is the standard 1,2,4,...,unroll so the resident program
    traces O(log K) variants, exactly the micro-batching discipline."""
    u = max(1, int(unroll))
    return BatchConfig(
        enabled=True, max_batch=u, timeout_ms=0.0,
        buckets=default_buckets(u),
    )


def _executor_defaults() -> dict:
    """Executor-level batching defaults ([executor] config section; env
    ``NNS_TPU_EXECUTOR_*`` outranks ini, the standard config layering).
    Malformed config values fall back to the built-in default with a
    warning — a typo'd ini line must not fail EVERY pipeline compile
    (element properties, by contrast, raise with context: the user set
    them on purpose, right here)."""
    from nnstreamer_tpu.config import conf

    c = conf()

    def _num(key: str, cast, fallback):
        raw = c.get("executor", key, str(fallback))
        try:
            return cast(raw)
        except ValueError:
            _log.warning(
                "[executor] %s=%r is not a valid %s; using %s",
                key, raw, cast.__name__, fallback,
            )
            return fallback

    timeout_ms = _num("batch_timeout_ms", float, 1.0)
    max_batch = _num("max_batch", int, 8)
    buckets_raw = c.get("executor", "batch_buckets", "").strip()
    try:
        buckets = [
            int(p) for p in buckets_raw.split(",") if p.strip()
        ]
    except ValueError:
        _log.warning(
            "[executor] batch_buckets=%r is not a comma list of ints; "
            "using the default ladder", buckets_raw,
        )
        buckets = []
    return {
        "batching": c.get_bool("executor", "batching", False),
        "max-batch": max_batch,
        "batch-timeout-ms": timeout_ms,
        "batch-buckets": buckets,
    }


def _parse_buckets(
    vals: Optional[List[int]], max_batch: int
) -> Tuple[int, ...]:
    if not vals:
        return default_buckets(max_batch)
    kept = sorted({v for v in vals if 1 <= v <= max_batch})
    dropped = sorted(set(vals) - set(kept))
    if dropped:
        # an explicitly configured ladder must not be rewritten silently
        _log.warning(
            "batch-buckets entries %s outside [1, max-batch=%d] ignored",
            dropped, max_batch,
        )
    added = []
    if not kept or kept[-1] != max_batch:
        # a ladder not reaching max-batch would leave full windows
        # without a bucket to dispatch as
        kept.append(max_batch)
        added.append(max_batch)
    if kept[0] != 1:
        # a bucket ladder without 1 would pad EVERY lone frame up to the
        # smallest bucket — trickle traffic must stay pad-free
        kept.insert(0, 1)
        added.append(1)
    if added:
        _log.warning(
            "batch-buckets: adding required bucket(s) %s (ladder must "
            "span [1, max-batch=%d]); effective ladder %s",
            sorted(added), max_batch, tuple(kept),
        )
    return tuple(kept)


def resolve_batch_config(elements: Sequence[Any]) -> BatchConfig:
    """Merge element-level batching properties over the executor default.

    Scans the elements in chain order; for each knob the first element
    that sets it explicitly wins. Only tensor_filter DECLARES the
    batching PropSpecs (lint-clean launch strings); the scan reads any
    op's properties so programmatic set_property overrides still work."""
    defaults = _executor_defaults()
    enabled: Optional[bool] = None
    max_batch: Optional[int] = None
    timeout_ms: Optional[float] = None
    buckets: Optional[List[int]] = None

    def _coerce(elem, prop: str, fn, raw):
        try:
            return fn(raw)
        except (TypeError, ValueError) as exc:
            # name the element and property (PR-1 diagnostics discipline:
            # a bare int() traceback from a node thread localizes nothing)
            raise ValueError(
                f"{getattr(elem, 'name', elem)}: bad {prop}={raw!r}: {exc}"
            ) from exc

    def _int_list(raw) -> List[int]:
        return [int(p) for p in str(raw).split(",") if str(p).strip()]

    for e in elements:
        get = getattr(e, "get_property", None)
        if get is None:
            continue
        if enabled is None and get("batching") is not None:
            enabled = _parse_bool(get("batching"))
        if max_batch is None and get("max-batch") is not None:
            max_batch = _coerce(e, "max-batch", int, get("max-batch"))
        if timeout_ms is None and get("batch-timeout-ms") is not None:
            timeout_ms = _coerce(
                e, "batch-timeout-ms", float, get("batch-timeout-ms")
            )
        if buckets is None and get("batch-buckets") is not None:
            buckets = _coerce(
                e, "batch-buckets", _int_list, get("batch-buckets")
            )
    if enabled is None:
        enabled = defaults["batching"]
    if max_batch is None:
        max_batch = defaults["max-batch"]
    if timeout_ms is None:
        timeout_ms = defaults["batch-timeout-ms"]
    if buckets is None:
        buckets = defaults["batch-buckets"]
    max_batch = max(1, int(max_batch))
    return BatchConfig(
        enabled=bool(enabled),
        max_batch=max_batch,
        timeout_ms=max(0.0, float(timeout_ms)),
        buckets=_parse_buckets(buckets, max_batch),
    )


class BatchStats:
    """Single-writer (the node thread) batching counters; readers see a
    consistent-enough snapshot (GIL-atomic attribute reads)."""

    __slots__ = ("batches", "frames", "padded_rows", "bucket_rows",
                 "wait_ns")

    def __init__(self) -> None:
        self.batches = 0
        self.frames = 0
        self.padded_rows = 0   # pad rows computed and thrown away
        self.bucket_rows = 0   # total rows dispatched (incl. padding)
        self.wait_ns = 0       # collector straggler-wait time

    def record(self, n: int, bucket: int, wait_s: float) -> None:
        self.batches += 1
        self.frames += n
        self.bucket_rows += bucket
        self.padded_rows += bucket - n
        self.wait_ns += int(wait_s * 1e9)

    @property
    def avg_batch_size(self) -> float:
        return self.frames / self.batches if self.batches else 0.0

    @property
    def pad_waste_pct(self) -> float:
        """Percent of dispatched device rows that were padding."""
        if not self.bucket_rows:
            return 0.0
        return 100.0 * self.padded_rows / self.bucket_rows

    @property
    def batch_wait_ms(self) -> float:
        """Average straggler wait per batch, ms (latency the batching
        layer itself added; 0 under load — drain-what's-there)."""
        if not self.batches:
            return 0.0
        return self.wait_ns / self.batches / 1e6

    def snapshot(self) -> dict:
        return {
            "avg_batch_size": round(self.avg_batch_size, 3),
            "pad_waste_pct": round(self.pad_waste_pct, 2),
            "batch_wait_ms": round(self.batch_wait_ms, 4),
        }


class BatchCollector:
    """Drains up to ``max_batch`` frames per call from a bounded channel.

    ``collect()`` returns ``(frames, eos, wait_s)``:
    - blocks for the first frame (honoring the node's stop event);
    - drains whatever else is queued, without blocking, up to the cap —
      under load this is the whole batch and costs zero added latency;
    - only when trickle-fed (exactly one frame and an empty queue) waits
      up to ``timeout_ms`` for stragglers, then goes with what arrived;
    - an EOS sentinel mid-drain ends collection: the partial batch is
      returned first with ``eos=True`` so in-flight frames flush before
      EOS propagates (EOS ordering parity with the per-frame path).

    ``drop`` is the per-frame upstream-QoS predicate (frames a
    downstream rate limiter will certainly discard are skipped before
    they can occupy batch slots).

    ``cap`` is an optional live window-limit callable — the OOM bucket
    governor's ceiling (pipeline/device_faults.py): a degraded segment
    collects at most ``min(max_batch, cap())`` per window, re-read per
    collect so upward re-probes widen collection again.
    """

    def __init__(
        self,
        chan,
        stop_event: threading.Event,
        config: BatchConfig,
        drop: Optional[Callable[[Any], bool]] = None,
        cap: Optional[Callable[[], int]] = None,
    ) -> None:
        self.chan = chan
        self.stop_event = stop_event
        self.config = config
        self.drop = drop
        self.cap = cap
        self._pending_eos = False

    def collect(self) -> Tuple[List[Any], bool, float]:
        if self._pending_eos:
            self._pending_eos = False
            return [], True, 0.0
        cfg = self.config
        limit = cfg.max_batch
        if self.cap is not None:
            limit = max(1, min(limit, self.cap()))
        batch: List[Any] = []
        # first frame: plain blocking pop (frame path latency untouched)
        while True:
            item = self.chan.get(self.stop_event)
            if item is EOS_FRAME:
                return [], True, 0.0
            if self.drop is not None and self.drop(item):
                continue
            batch.append(item)
            break
        # drain-what's-there: everything already queued rides this batch
        eos = self._drain_queued(batch, limit)
        wait_s = 0.0
        if (
            not eos
            and len(batch) == 1
            and cfg.timeout_ms > 0.0
            and limit > 1
        ):
            # trickle-fed: bounded wait for stragglers. One wake is
            # enough — whatever arrived by then is the batch (waiting
            # again after each arrival would turn the bound into a
            # rolling window and stretch worst-case latency).
            t0 = time.perf_counter()
            deadline = time.monotonic() + cfg.timeout_ms / 1000.0
            item = self.chan.get_until(deadline, self.stop_event)
            if item is not None:
                if item is EOS_FRAME:
                    eos = True
                elif self.drop is not None and self.drop(item):
                    pass
                else:
                    batch.append(item)
                if not eos:
                    eos = self._drain_queued(batch, limit)
            wait_s = time.perf_counter() - t0
        if eos and batch:
            # deliver the flushed batch now; report EOS on the next call
            self._pending_eos = True
            return batch, False, wait_s
        return batch, eos, wait_s

    def _drain_queued(self, batch: List[Any], cap: int) -> bool:
        items = self.chan.drain(cap - len(batch))
        for item in items:
            if item is EOS_FRAME:
                return True
            if self.drop is not None and self.drop(item):
                continue
            batch.append(item)
        return False
