"""Pipeline graph: build → negotiate → compile (fuse) → execute.

The reference's pipeline bring-up (SURVEY.md §3.1: parse description,
create elements, negotiate caps at PAUSED, stream at PLAYING) becomes:

    Pipeline.add/link (or pipeline/parse.py from a description string)
    → negotiate(): one topological pass propagating TensorsSpec/MediaSpec
    → compile(): partition the graph into execution nodes, FUSING maximal
      linear chains of TensorOp elements into single jitted XLA programs
      (the TPU-first move: the reference runs one chain function per
      element per frame with map/unmap; we run one XLA program for the
      whole chain with tensors resident in HBM)
    → Executor (pipeline/executor.py): one streaming thread per node with
      bounded queues (GStreamer streaming-thread parity → pipeline
      parallelism and backpressure).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from nnstreamer_tpu.elements.base import (
    Element,
    HostElement,
    NegotiationError,
    Routing,
    Sink,
    Source,
    Spec,
    TensorOp,
)
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

_log = get_logger("pipeline")


@dataclass(frozen=True)
class Link:
    src: Element
    src_pad: int
    dst: Element
    dst_pad: int


class Pipeline:
    def __init__(self, name: str = "pipeline") -> None:
        self.name = name
        self.elements: List[Element] = []
        self.links: List[Link] = []
        self._by_name: Dict[str, Element] = {}
        self._negotiated = False
        self._executor = None

    # -- build -------------------------------------------------------------
    def add(self, *elements: Element) -> "Pipeline":
        for e in elements:
            if e in self.elements:
                continue
            if e.name in self._by_name:
                raise ValueError(f"duplicate element name {e.name!r}")
            self.elements.append(e)
            self._by_name[e.name] = e
        return self

    def __getitem__(self, name: str) -> Element:
        return self._by_name[name]

    def link(
        self,
        src: Element,
        dst: Element,
        src_pad: Optional[int] = None,
        dst_pad: Optional[int] = None,
    ) -> "Pipeline":
        self.add(src, dst)
        if src_pad is None:
            src_pad = self._next_free_src_pad(src)
        if dst_pad is None:
            dst_pad = self._next_free_dst_pad(dst)
        for l in self.links:
            if l.src is src and l.src_pad == src_pad:
                raise ValueError(f"{src.name} src pad {src_pad} already linked")
            if l.dst is dst and l.dst_pad == dst_pad:
                raise ValueError(f"{dst.name} sink pad {dst_pad} already linked")
        if src.N_SRCS is not None and src_pad >= src.N_SRCS:
            raise ValueError(f"{src.name} has no src pad {src_pad}")
        if dst.N_SINKS is not None and dst_pad >= dst.N_SINKS:
            raise ValueError(f"{dst.name} has no sink pad {dst_pad}")
        self.links.append(Link(src, src_pad, dst, dst_pad))
        return self

    def chain(self, *elements: Element) -> "Pipeline":
        """Link a linear chain e1 ! e2 ! ... (gst-launch `!`)."""
        for a, b in zip(elements, elements[1:]):
            self.link(a, b)
        return self

    def _next_free_src_pad(self, e: Element) -> int:
        used = {l.src_pad for l in self.links if l.src is e}
        pad = 0
        while pad in used:
            pad += 1
        return pad

    def _next_free_dst_pad(self, e: Element) -> int:
        used = {l.dst_pad for l in self.links if l.dst is e}
        pad = 0
        while pad in used:
            pad += 1
        return pad

    # -- introspection -----------------------------------------------------
    def out_links(self, e: Element) -> List[Link]:
        return sorted(
            (l for l in self.links if l.src is e), key=lambda l: l.src_pad
        )

    def in_links(self, e: Element) -> List[Link]:
        return sorted(
            (l for l in self.links if l.dst is e), key=lambda l: l.dst_pad
        )

    def n_srcs(self, e: Element) -> int:
        return e.N_SRCS if e.N_SRCS is not None else len(self.out_links(e))

    def n_sinks(self, e: Element) -> int:
        return e.N_SINKS if e.N_SINKS is not None else len(self.in_links(e))

    # -- negotiation -------------------------------------------------------
    def toposort_partial(self) -> Tuple[List[Element], List[Element]]:
        """Kahn's algorithm; returns (topological order, leftover). A
        non-empty leftover means those elements sit in (or behind) a
        cycle. The static analyzer consumes the partial form; negotiate()
        treats leftover as fatal via _toposort()."""
        indeg = {e: len(self.in_links(e)) for e in self.elements}
        ready = [e for e in self.elements if indeg[e] == 0]
        order: List[Element] = []
        while ready:
            e = ready.pop(0)
            order.append(e)
            for l in self.out_links(e):
                indeg[l.dst] -= 1
                if indeg[l.dst] == 0:
                    ready.append(l.dst)
        ordered = set(order)
        leftover = [e for e in self.elements if e not in ordered]
        return order, leftover

    def _toposort(self) -> List[Element]:
        order, leftover = self.toposort_partial()
        if leftover:
            cyclic = [e.name for e in leftover]
            raise NegotiationError(
                f"pipeline has a cycle through {cyclic}; use tensor_repo "
                "(reposink/reposrc) for feedback loops"
            )
        return order

    def negotiate(self) -> "Pipeline":
        """One topological pass: propagate specs, validate links
        (the reference's PAUSED-state caps negotiation)."""
        for e in self.elements:
            ins, outs = self.n_sinks(e), self.n_srcs(e)
            if isinstance(e, Routing):
                e.set_pad_counts(ins, outs)
            if ins != len(self.in_links(e)) and ins > 0:
                raise NegotiationError(
                    f"{e.name}: {len(self.in_links(e))}/{ins} sink pads linked"
                )
        for e in self._toposort():
            in_specs: List[Spec] = [None] * self.n_sinks(e)  # type: ignore
            for l in self.in_links(e):
                in_specs[l.dst_pad] = l.src.out_specs[l.src_pad]
            try:
                e.fix_negotiation(in_specs)
            except NegotiationError:
                raise
            except Exception as exc:
                raise NegotiationError(f"{e.name}: {exc}") from exc
            if len(e.out_specs) != self.n_srcs(e):
                raise NegotiationError(
                    f"{e.name}: negotiated {len(e.out_specs)} specs for "
                    f"{self.n_srcs(e)} src pads"
                )
        self._wire_qos()
        self._negotiated = True
        return self

    def _wire_qos(self) -> None:
        """Attach each tensor_rate's QoS hint to its upstream linear path
        (the reference's upstream QoS event propagation,
        gsttensor_rate.c:452): producers on the path skip frames the rate
        limiter would drop. The walk stops at fan-in/fan-out boundaries —
        a shared upstream (tee) may feed branches that still need the
        frame — and at elements that restructure timestamps or windows
        (aggregator, another rate, batching converter): skipping THEIR
        inputs would change the content of outputs the limiter keeps."""
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.elements.flow import Queue
        from nnstreamer_tpu.elements.transform import TensorTransform

        passthrough_timing = (TensorFilter, TensorTransform, Queue)
        for e in self.elements:
            qos = getattr(e, "qos", None)
            if qos is None or not getattr(qos, "enabled", False):
                continue
            cur = e
            while True:
                ins = self.in_links(cur)
                if len(ins) != 1:
                    break
                up = ins[0].src
                if len(self.out_links(up)) != 1:
                    break  # tee/demux boundary: other branches need frames
                if not isinstance(up, passthrough_timing):
                    break  # timestamp-restructuring or unknown element
                up.add_qos_source(qos)
                cur = up

    # -- compile: fuse linear TensorOp chains ------------------------------
    def compile_plan(self) -> "ExecPlan":
        if not self._negotiated:
            self.negotiate()
        # group consecutive TensorOps with 1:1 linkage into segments.
        # NNS_NO_FUSE=1 keeps every element its own segment — the
        # reference-faithful per-element execution mode (one program
        # per element, queue hops between), useful to localize a fault
        # to an element vs the fusion, and the oracle the fused-vs-
        # unfused equivalence tests compare against.
        import os

        no_fuse = os.environ.get("NNS_NO_FUSE", "").lower() in (
            "1", "true", "yes", "on",
        )
        seg_of: Dict[Element, "FusedSegment"] = {}
        segments: List[FusedSegment] = []
        from nnstreamer_tpu.pipeline.batching import (
            BatchStats,
            resolve_batch_config,
        )
        from nnstreamer_tpu.pipeline.device_faults import (
            resolve_device_policy,
        )
        from nnstreamer_tpu.pipeline.faults import resolve_fault_policy
        from nnstreamer_tpu.pipeline.transfer import (
            donation_enabled,
            resolve_ring_depth,
        )

        for e in self._toposort():
            # non-traceable TensorOps (host-bound backends) execute as host
            # nodes; they are fusion barriers like HostElement. An element
            # whose dead-letter error pad is LINKED is also a barrier:
            # per-frame error routing needs per-frame invokes, which a
            # fused program cannot give it (an unlinked pad — retry with
            # no overflow sink — costs nothing and fuses normally).
            err_routed = e.error_pad is not None and any(
                l.src_pad == e.error_pad for l in self.out_links(e)
            )
            if (
                not isinstance(e, TensorOp)
                or err_routed
                or not e.is_traceable()
            ):
                if isinstance(e, TensorOp):
                    # host-path batching/fault config resolves at PLAN time
                    # like the segments below, so a bad property fails
                    # compile_plan() instead of poisoning a running node
                    e.batch_config = resolve_batch_config([e])
                    if e.batch_stats is None:
                        e.batch_stats = BatchStats()
                    e.fault_policy = resolve_fault_policy([e])
                    e.device_policy = resolve_device_policy([e])
                    # host nodes keep the synchronous loop unless the
                    # element asks for a ring explicitly (a host
                    # backend's invoke can't overlap with itself, so
                    # the config-level default would only add latency)
                    raw = e.get_property("ring-depth")
                    e.ring_depth = (
                        resolve_ring_depth([e]) if raw is not None else 1
                    )
                continue
            ups = self.in_links(e)
            up = ups[0].src if len(ups) == 1 else None
            if (
                not no_fuse
                and up is not None
                and isinstance(up, TensorOp)
                and up in seg_of
                and len(self.out_links(up)) == 1
            ):
                seg = seg_of[up]
                seg.ops.append(e)
                seg_of[e] = seg
            else:
                seg = FusedSegment(ops=[e])
                segments.append(seg)
                seg_of[e] = seg
        # resolve micro-batching per segment (element properties over the
        # executor-level [executor] config default) and share the stats
        # object with the ops so tensor_filter's read-only avg-batch-size/
        # pad-waste-pct/batch-wait-ms properties report their segment
        from nnstreamer_tpu.elements.converter import TensorConverter
        from nnstreamer_tpu.elements.decoder import TensorDecoder
        from nnstreamer_tpu.elements.transform import TensorTransform

        def _postproc_op(op: TensorOp) -> bool:
            """Member ops that are fused pre/post-processing rather than
            model invokes (docs/on-device-ops.md): a device-path
            decoder, an image-op transform, or a normalizing converter.
            Counted per segment so the executor can emit
            nns_fused_postproc_total and nns-top can flag the node."""
            if isinstance(op, TensorDecoder):
                return True  # only traceable decoders reach a segment
            if isinstance(op, TensorTransform):
                return op.mode in ("resize", "crop-resize")
            if isinstance(op, TensorConverter):
                return op.input_norm is not None
            return False

        for seg in segments:
            seg.batch_config = resolve_batch_config(seg.ops)
            seg.fault_policy = resolve_fault_policy(seg.ops)
            seg.device_policy = resolve_device_policy(seg.ops)
            seg.ring_depth = resolve_ring_depth(seg.ops)
            seg.donate = donation_enabled()
            seg.postproc_ops = sum(1 for op in seg.ops if _postproc_op(op))
            for op in seg.ops:
                op.batch_stats = seg.batch_stats
        return ExecPlan(self, segments, seg_of)

    # -- run ---------------------------------------------------------------
    def start(self):
        from nnstreamer_tpu.pipeline.executor import Executor

        if self._executor is not None and self._executor.finished:
            raise RuntimeError(
                f"pipeline {self.name!r} already ran to completion; build a "
                "fresh Pipeline to run again"
            )
        if self._executor is None:
            self._executor = Executor(self.compile_plan())
        self._executor.start()
        return self._executor

    def run(self, timeout: Optional[float] = None):
        """Start, wait for EOS (or error), stop. Returns the executor for
        inspecting sink results. Raises TimeoutError if `timeout` elapses
        before EOS."""
        ex = self.start()
        completed = ex.wait(timeout)
        ex.stop()
        # NNS_TRACE=<path> env opt-in (GST_DEBUG_DUMP_DOT_DIR-style):
        # flush the chrome trace when the pipeline winds down
        import os

        from nnstreamer_tpu import trace as trace_mod

        trace_path = os.environ.get("NNS_TRACE")
        if trace_path:
            tracer = trace_mod.get()
            if tracer is not None:
                tracer.save(trace_path)
        if ex.errors:
            raise ex.errors[0]
        if not completed:
            raise TimeoutError(
                f"pipeline {self.name!r} did not reach EOS within {timeout}s"
            )
        return ex

    def stop(self) -> None:
        if self._executor is not None:
            self._executor.stop()

    def dump_dot(self, diagnostics=None, specs=None) -> str:
        """Graphviz dump (reference GST_DEBUG_DUMP_DOT_DIR parity).

        `diagnostics`: optional iterable of nns-lint Diagnostics; offending
        nodes are painted (red = error, orange = warning) with their codes
        appended to the label, and pipeline-level findings become the
        graph label. `specs`: optional {element name: out_specs} override
        for the spec line (nns-lint's dry-run results — this pipeline's
        own elements stay un-negotiated)."""
        by_elem: Dict[str, List] = {}
        graph_level: List[str] = []
        for d in diagnostics or ():
            if d.element is None:
                graph_level.append(d.code)
            else:
                by_elem.setdefault(d.element, []).append(d)
        lines = [f'digraph "{self.name}" {{', "  rankdir=LR;"]
        if graph_level:
            lines.append(f'  label="{" ".join(sorted(set(graph_level)))}";')
        for e in self.elements:
            spec = ""
            out = (specs or {}).get(e.name) or e.out_specs
            if out:
                s = out[0]
                spec = f"\\n{s}" if s is not None else ""
            style = ""
            diags = by_elem.get(e.name)
            if diags:
                codes = " ".join(sorted({d.code for d in diags}))
                spec += f"\\n{codes}"
                worst = (
                    "red"
                    if any(d.severity.value == "error" for d in diags)
                    else "orange"
                )
                style = f', style=filled, fillcolor="{worst}"'
            lines.append(
                f'  "{e.name}" [label="{e.FACTORY_NAME}\\n{e.name}{spec}"'
                f", shape=box{style}];"
            )
        for l in self.links:
            lines.append(f'  "{l.src.name}" -> "{l.dst.name}" [label="{l.src_pad}→{l.dst_pad}"];')
        lines.append("}")
        return "\n".join(lines)


class FusedSegment:
    """A maximal linear chain of TensorOps compiled into ONE jitted fn.

    Compiled programs are cached by (arity, shapes, dtypes, batch
    bucket, op fn versions), NOT by "compiled once": a spec
    renegotiation (different shapes/dtypes arriving after a rebuild), a
    different micro-batch bucket, or a same-shape model hot swap
    (reload_model ticks the op's fn_version) gets its own entry with
    freshly collected op fns — a stale program can never be silently
    reused. ``n_traces`` counts cache
    fills (each entry traces exactly once: shapes are fixed per key), so
    tests can assert the bucket ladder bounds retracing at
    O(log max-batch).
    """

    def __init__(self, ops: List[TensorOp]) -> None:
        self.ops = ops
        # (sig, bucket, fn versions) -> jitted fn; bucket 0 = per-frame
        self._cache: Dict[tuple, Callable] = {}
        self._last: Optional[tuple] = None  # (full_key, fn) fast path
        self.n_traces = 0
        # micro-batching (pipeline/batching.py): resolved at plan time;
        # stats shared with the ops so tensor_filter can surface them
        self.batch_config = None
        # error policy (pipeline/faults.py): resolved at plan time from
        # the member ops' on-error/retry-* properties. Segments never
        # carry a route policy — route ops are fusion barriers.
        self.fault_policy = None
        # device-resilience policy (pipeline/device_faults.py): resolved
        # at plan time like the fault policy; the executor builds the
        # OOM bucket governor + device circuit from it per node
        self.device_policy = None
        # eager (un-jitted) program: the degraded path the device
        # circuit serves from — no XLA compile, minimal device arena
        self._eager: Optional[tuple] = None
        # device_probe hooks of member backends (chaos injectors):
        # resolved once, empty for real pipelines so the hot path pays
        # one len() check per batched dispatch
        self._probes: Optional[list] = None
        # set by the executor when its sanitizer is active: pad rows in
        # process_batch are then poison, not last-frame replicas. One
        # flag resolved at build — the hot path never re-reads config.
        self.sanitize_poison = False
        # resident streaming (pipeline/transfer.py, docs/streaming.md):
        # ring_depth = in-flight frames the executor keeps for this
        # segment; donate = node-owned activation buffers (staged
        # uploads, stacked windows) are donated to the program so XLA
        # reuses them for outputs. Both resolved at plan time.
        self.ring_depth: Optional[int] = None
        self.donate = False
        # fused pre/post-processing member count (docs/on-device-ops.md):
        # resolved at plan time; >0 arms the nns_fused_postproc_total
        # emitter and the nns-top `fused-post` note
        self.postproc_ops = 0
        # identity short-circuit: a segment of only-identity ops (the
        # passthrough backend) serves frames without ANY device program
        # — per-frame XLA dispatch is pure overhead there. Resolved on
        # first use (backends must be open).
        self._identity: Optional[bool] = None
        from nnstreamer_tpu.pipeline.batching import BatchStats

        self.batch_stats = BatchStats()

    @property
    def first(self) -> TensorOp:
        return self.ops[0]

    @property
    def last(self) -> TensorOp:
        return self.ops[-1]

    @property
    def name(self) -> str:
        return "+".join(o.name for o in self.ops)

    @staticmethod
    def _sig_of(tensors) -> tuple:
        # raw (shape, dtype) pairs: np.dtype is hashable and equality-
        # stable, so no string normalization — this runs per frame on
        # the fused hot path
        return tuple((tuple(t.shape), t.dtype) for t in tensors)

    def _compose(self) -> Callable:
        """Collect the ops' CURRENT fns (re-run per cache fill so a
        renegotiated/reloaded op contributes its fresh fn)."""
        fns = [op.make_fn() for op in self.ops]

        def composed(*tensors):
            t = tuple(tensors)
            for f in fns:
                t = tuple(f(t))
            return t

        return composed

    def is_identity(self) -> bool:
        """True when every member op declares is_identity(): process()
        then returns the frame untouched (no compile, no dispatch)."""
        if self._identity is None:
            try:
                self._identity = all(op.is_identity() for op in self.ops)
            except Exception:  # noqa: BLE001 — unopened backend: not identity
                self._identity = False
        return self._identity

    def _jitted_for(
        self, sig: tuple, bucket: int = 0, donate: bool = False
    ) -> Callable:
        # fn_version ticks on model hot swap (reload_model): same shapes,
        # different weights — the old program must not be served
        versions = tuple(op.fn_version for op in self.ops)
        key = (sig, bucket, versions, donate)
        last = self._last
        if last is not None and last[0] == key:
            return last[1]
        fn = self._cache.get(key)
        if fn is None:
            composed = self._compose()
            target = jax.vmap(composed) if bucket else composed
            # donate_argnums on the activations: the caller OWNS these
            # buffers (staged uploads / stacked windows — never an
            # upstream element's arrays), so XLA may reuse them for
            # outputs instead of growing the device arena per in-flight
            # frame (docs/streaming.md). Only inputs whose (shape,
            # dtype) matches an output can actually be aliased — a
            # uint8 image feeding a float program would just be deleted
            # with an XLA "unusable donation" warning, so those stay
            # un-donated.
            kw = {}
            if donate:
                argnums = self._aliasable_argnums(target, sig, bucket)
                if argnums:
                    kw = {"donate_argnums": argnums}
            fn = jax.jit(target, **kw)
            self._cache[key] = fn
            self.n_traces += 1
        self._last = (key, fn)
        return fn

    @staticmethod
    def _aliasable_argnums(target, sig, bucket: int) -> tuple:
        """Input indices whose buffer XLA can actually reuse for an
        output: exact (shape, dtype) match, each output absorbing at
        most one input. eval_shape runs abstractly (no compile, no
        device) — a trace failure just disables donation for this
        entry."""
        try:
            shapes = [
                jax.ShapeDtypeStruct(
                    (bucket, *shape) if bucket else shape, dtype
                )
                for shape, dtype in sig
            ]
            outs = jax.eval_shape(target, *shapes)
            pool: Dict[tuple, int] = {}
            for o in outs:
                k = (tuple(o.shape), np.dtype(o.dtype))
                pool[k] = pool.get(k, 0) + 1
            argnums = []
            for i, (shape, dtype) in enumerate(sig):
                k = (
                    ((bucket, *shape) if bucket else tuple(shape)),
                    np.dtype(dtype),
                )
                if pool.get(k, 0) > 0:
                    pool[k] -= 1
                    argnums.append(i)
            return tuple(argnums)
        except Exception:  # noqa: BLE001 — donation is an optimization
            return ()

    def _negotiated_sig(self) -> Optional[tuple]:
        spec = self.first.in_specs[0] if self.first.in_specs else None
        if not isinstance(spec, TensorsSpec) or not spec.is_static:
            return None
        return tuple(
            (tuple(t.shape), t.dtype.np_dtype) for t in spec
        )

    def build(self) -> Optional[Callable]:
        """Instantiate the per-frame program for the negotiated spec
        (PAUSED-state parity); per-signature entries fill lazily. With
        batching active, also warm the max-batch bucket — the
        steady-state program under load — by invoking it on zeros, so
        the first full batch doesn't stall the stream on an XLA compile
        (smaller buckets stay lazy: they only appear at trickle/EOS
        boundaries where a one-off compile stall is tolerable)."""
        sig = self._negotiated_sig()
        if sig is None or self.is_identity():
            return None
        # warm the variants steady state will actually SERVE: the cache
        # key includes `donate`, so warming the un-donated program when
        # the executor then calls the donated one would leave the first
        # live frame stalling on a full XLA compile at PLAYING. The
        # per-frame path donates only off-CPU (the staging path); the
        # batched path donates its stacked windows everywhere.
        from nnstreamer_tpu.pipeline.transfer import default_backend_is_cpu

        fn = self._jitted_for(
            sig, 0, self.donate and not default_backend_is_cpu()
        )
        cfg = self.batch_config
        if cfg is not None and cfg.active:
            try:
                import numpy as _np

                bucket = cfg.buckets[-1]
                zeros = [
                    _np.zeros((bucket,) + shape, dtype)
                    for shape, dtype in sig
                ]
                jax.block_until_ready(
                    self._jitted_for(sig, bucket, self.donate)(*zeros)
                )
            except Exception as exc:
                from nnstreamer_tpu.pipeline.device_faults import (
                    classify_device_fault,
                )

                if classify_device_fault(exc) == "compile":
                    # deterministic: re-trying per frame would recompile
                    # forever — surface it so the executor's build
                    # handler opens the device circuit at PAUSED state,
                    # not mid-stream. OOM/transient warmup faults stay
                    # swallowed: the runtime governor ladder degrades
                    # those gracefully, frame by frame.
                    raise
                # otherwise the warmup is an optimization
                _log.warning("%s: batched warmup failed: %s", self.name, exc)
        return fn

    def process(self, frame: Frame, donate: bool = False) -> Frame:
        """One frame through the compiled program. ``donate=True`` hands
        the frame's tensors to XLA for output reuse — ONLY legal when
        the caller owns every buffer (the executor's staged-H2D path;
        donated arrays are deleted, so a shared/reused input would die
        under its other holders)."""
        identity = self._identity
        if identity or (identity is None and self.is_identity()):
            f = frame
        else:
            fn = self._jitted_for(self._sig_of(frame.tensors), 0, donate)
            f = frame.with_tensors(fn(*frame.tensors))
        for op in self.ops:
            f = op.transform_meta(f)
        return f

    def process_eager(self, frame: Frame) -> Frame:
        """Run the composed ops WITHOUT jit — the degraded path the
        device circuit (pipeline/device_faults.py) serves from when the
        compiled program cannot: no XLA compile (a deterministic compile
        failure would just recur), per-op dispatch instead of one fused
        arena (an OOM'd segment gets room back). Semantics identical to
        process(); slower by construction."""
        versions = tuple(op.fn_version for op in self.ops)
        if self._eager is None or self._eager[0] != versions:
            self._eager = (versions, self._compose())
        out = self._eager[1](*frame.tensors)
        f = frame.with_tensors(tuple(out))
        for op in self.ops:
            f = op.transform_meta(f)
        return f

    def _device_probes(self) -> list:
        """Member backends' ``device_probe(rows)`` hooks (chaos
        injectors declare one; real backends don't, so this is [] and
        the batched hot path pays a single truthiness check)."""
        if self._probes is None:
            self._probes = [
                hook
                for op in self.ops
                for hook in (
                    getattr(
                        getattr(op, "backend", None), "device_probe", None
                    ),
                )
                if hook is not None
            ]
        return self._probes

    def process_batch(self, frames, cfg) -> Tuple[List[Frame], int]:
        """ONE batched device invoke for a window of same-spec frames.

        Stacks each tensor index on a NEW leading axis, pads up to the
        next bucket with replicas of the last frame (rows computed and
        discarded — the price of a bounded trace count), runs the
        vmapped program, and splits results back per frame in order
        with per-frame metadata/timestamps applied exactly as the
        per-frame path would."""
        import jax.numpy as jnp

        n = len(frames)
        if self.is_identity():
            # no program to batch for: per-frame passthrough, no padding
            return [self.process(f) for f in frames], n
        sig = self._sig_of(frames[0].tensors)
        if any(self._sig_of(f.tensors) != sig for f in frames[1:]):
            # heterogeneous window (flexible stream / renegotiation
            # boundary): frames can't share one stacked invoke — fall
            # back to per-frame programs, semantics identical
            return [self.process(f) for f in frames], n
        bucket = cfg.bucket_for(n)
        probes = self._device_probes()
        if probes:
            # deterministic capacity boundary (chaos injectors): probe
            # with the PADDED bucket — that is the width the device sees
            for probe in probes:
                probe(bucket)
        # the stacked cols are freshly built below — this call owns
        # them, so donation is always safe here (an OOM retry restacks
        # from the still-live member frames)
        fn = self._jitted_for(sig, bucket, self.donate)
        pad = bucket - n
        filler = None
        if pad and self.sanitize_poison:
            # sanitizer on: pad rows are poison (NaN / int max) instead
            # of last-frame replicas — a split/index bug then yields
            # garbage instead of a plausibly-stale frame
            from nnstreamer_tpu.pipeline.sanitize import poison_like

            filler = poison_like
        cols = []
        for i in range(len(frames[0].tensors)):
            rows = [f.tensors[i] for f in frames]
            if pad:
                last = frames[-1].tensors[i]
                rows.extend([filler(last) if filler else last] * pad)
            cols.append(jnp.stack(rows))
        outs = fn(*cols)
        result: List[Frame] = []
        for j, frame in enumerate(frames):
            f = frame.with_tensors([o[j] for o in outs])
            for op in self.ops:
                f = op.transform_meta(f)
            result.append(f)
        return result, bucket


@dataclass
class Chain:
    """One compile unit (docs/chain-analysis.md): a maximal run of
    fused segments joined by device-resident handoffs. An eligible
    multi-segment chain under ``[executor] chain_mode=auto`` compiles
    into ONE resident program the executor dispatches once per
    unrolled window (pipeline/chain_program.py ``decide_chain`` /
    ``ChainProgram``); anything else runs each segment as its own XLA
    program with a device-array pass between nodes — the parity
    oracle the compiled path falls back to. ``nns-xray`` reports and
    lints at this granularity either way."""

    segments: List[FusedSegment]

    @property
    def first(self) -> TensorOp:
        return self.segments[0].first

    @property
    def last(self) -> TensorOp:
        return self.segments[-1].last

    @property
    def name(self) -> str:
        return " => ".join(s.name for s in self.segments)

    @property
    def ops(self) -> List[TensorOp]:
        return [op for s in self.segments for op in s.ops]


@dataclass
class ExecPlan:
    pipeline: Pipeline
    segments: List[FusedSegment]
    seg_of: Dict[Element, FusedSegment]

    def _device_successor(
        self, seg: FusedSegment
    ) -> Optional[FusedSegment]:
        """The unique fused segment ``seg`` hands frames to on device:
        reachable from ``seg.last`` across only ``DEVICE_PASSTHROUGH``
        plumbing (queue, capsfilter — the executor's resident handoff
        rides through those untouched, the same transparency
        ``Node._out_wants_host`` negotiates). Anything else on the path
        — a host-path op, routing, tee fan-out, a ``WANTS_HOST``
        consumer — severs the chain, as does reaching two different
        segments (no single linear program covers a fork)."""
        frontier = [l.dst for l in self.pipeline.out_links(seg.last)]
        seen: set = set()
        hit: Optional[FusedSegment] = None
        while frontier:
            e = frontier.pop()
            if id(e) in seen:
                continue
            seen.add(id(e))
            s2 = self.seg_of.get(e)
            if s2 is not None and s2 is not seg:
                if hit is not None and hit is not s2:
                    return None
                hit = s2
            elif getattr(type(e), "DEVICE_PASSTHROUGH", False):
                frontier.extend(
                    l.dst for l in self.pipeline.out_links(e)
                )
        return hit

    def chains(self) -> List[Chain]:
        """Compile units: maximal runs of fused segments joined by
        device handoffs (:class:`Chain`), in plan (topological) order.
        Every segment lands in exactly one chain; a pipeline with no
        host hop between its filters is a single chain end to end."""
        next_of: Dict[int, FusedSegment] = {}
        has_prev: set = set()
        for seg in self.segments:
            succ = self._device_successor(seg)
            if succ is not None:
                next_of[id(seg)] = succ
                has_prev.add(id(succ))
        out: List[Chain] = []
        for seg in self.segments:
            if id(seg) in has_prev:
                continue
            run = [seg]
            while id(run[-1]) in next_of:
                nxt = next_of[id(run[-1])]
                if any(s is nxt for s in run):  # cycle guard
                    break
                run.append(nxt)
            out.append(Chain(segments=run))
        return out
