"""Whole-chain resident dataflow programs (ROADMAP item 1,
docs/chain-analysis.md "Compiled chains").

A :class:`~nnstreamer_tpu.pipeline.graph.Chain` — fused segments joined
by device-resident handoffs — runs by default as one service thread per
segment, one XLA dispatch per segment per frame. At multi-kfps rates
the executor is host-dispatch-bound, not compute-bound (the
StreamTensor lesson, PAPERS.md: compile the inter-stage FIFOs INTO the
dataflow program instead of mediating them on the host). This module
makes the chain itself the compile unit:

- :func:`decide_chain` — the ONE eligibility/verdict function shared by
  the executor (should this chain get a ``ChainNode``?), ``nns-xray``
  (the chain report's ``compiled`` column), and the ``NNS-W125`` lint
  (eligible but configured off) — three consumers, one decision, so
  they can never disagree. Eligibility reuses the same jaxpr walkers
  the W120–W124 passes run (analysis/xray.py): any hazard that would
  fire there blocks compilation here.
- :class:`ChainProgram` — traces ONE jitted program threading every
  stage's outputs into the next as on-device values, unrolled K frames
  per launch (``[executor] chain_unroll``, clamped by the W124
  transient-HBM bound from ``analysis/costmodel.chain_cost``), with
  donation carried across the whole chain via the existing
  ``_aliasable_argnums`` discipline. Identity ops contribute
  passthrough fns and collapse out of the trace; an all-identity chain
  never dispatches at all.

The per-node path stays the PARITY ORACLE (exactly as ``kv_attn=gather``
does for block attention): :meth:`ChainProgram.process_frame_fallback`
serves a frame through each member segment's OWN program in order —
bitwise-identical to the member FusedNodes — and the executor's
``ChainNode`` latches onto it for any runtime hazard (device fault,
OOM at the last unroll rung, heterogeneous/renegotiated windows).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.pipeline.batching import default_buckets
from nnstreamer_tpu.pipeline.graph import FusedSegment
from nnstreamer_tpu.pipeline.transfer import (
    resolve_chain_mode,
    resolve_chain_unroll,
)

_log = get_logger("chain_program")


@dataclass(frozen=True)
class ChainDecision:
    """The shared compile verdict for one chain.

    ``eligible`` — a hazard-free multi-segment chain a single resident
    program can serve. ``reason`` — the FIRST blocking hazard/config
    when not eligible (the xray ``compiled`` column prints it).
    ``mode`` — the resolved ``chain-mode`` (member property over
    ``[executor] chain_mode``). ``unroll`` — frames per launch window,
    already clamped by the W124 bound. The executor compiles exactly
    when ``eligible and mode == "auto"``; nns-lint fires ``NNS-W125``
    exactly when ``eligible and mode == "off"``.
    """

    eligible: bool
    reason: Optional[str]
    mode: str
    unroll: int

    @property
    def compiles(self) -> bool:
        return self.eligible and self.mode == "auto"


def _gate_active(seg) -> bool:
    """Would the executor arm a per-frame error-policy gate for this
    segment? (Same participation rule as ``Node.make_fault_gate``: the
    element must DECLARE the fault surface.)"""
    pol = seg.fault_policy
    if pol is None or not getattr(pol, "active", False):
        return False
    elem = seg.first
    return "on-error" in type(elem).property_schema()


def _interior_external_consumer(plan, chain):
    """An element OUTSIDE the chain that consumes an interior handoff
    (a queue between two member segments also feeding a sink): the
    compiled program keeps interior values inside the trace, so such a
    consumer would starve — the chain must stay on the per-node path."""
    pipeline = plan.pipeline
    for a, b in zip(chain.segments, chain.segments[1:]):
        member = {id(op) for op in b.ops}
        frontier = [ln.dst for ln in pipeline.out_links(a.last)]
        seen: set = set()
        while frontier:
            e = frontier.pop()
            if id(e) in seen or id(e) in member:
                continue
            seen.add(id(e))
            if (
                getattr(type(e), "DEVICE_PASSTHROUGH", False)
                and plan.seg_of.get(e) is None
            ):
                frontier.extend(ln.dst for ln in pipeline.out_links(e))
                continue
            return e
    return None


def _interior_external_producer(plan, chain):
    """An element OUTSIDE the chain that FEEDS an interior entry point
    (a second producer into a downstream member segment, e.g. two
    branches funneled through one queue): the compiled program only
    services the chain head's input, so frames from the other producer
    would be lost — the chain must stay on the per-node path."""
    pipeline = plan.pipeline
    member = {id(op) for op in chain.ops}
    for seg in chain.segments[1:]:
        frontier = [ln.src for ln in pipeline.in_links(seg.first)]
        seen: set = set()
        while frontier:
            e = frontier.pop()
            if id(e) in seen or id(e) in member:
                continue
            seen.add(id(e))
            if (
                getattr(type(e), "DEVICE_PASSTHROUGH", False)
                and plan.seg_of.get(e) is None
            ):
                frontier.extend(ln.src for ln in pipeline.in_links(e))
                continue
            return e
    return None


def _hazard(chain) -> Optional[str]:
    """First W120–W124 finding that blocks whole-chain compilation —
    the SAME walkers the nns-xray passes run (analysis/xray.py), so the
    executor and the report can never disagree about a hazard. Identity
    segments skip the trace-based walks (nothing dispatches there)."""
    import importlib

    # the analysis package re-exports the xray() FUNCTION under the
    # same name as its module — resolve the module explicitly
    _x = importlib.import_module("nnstreamer_tpu.analysis.xray")
    from nnstreamer_tpu.analysis.costmodel import (
        chain_cost,
        configured_device_bound,
    )

    for seg in chain.segments:
        if seg.is_identity():
            continue
        try:
            jaxpr = _x.segment_jaxpr(seg)
        except Exception as exc:  # noqa: BLE001 — untraceable: no program
            return f"segment {seg.name} untraceable ({exc})"
        if jaxpr is None:
            return f"segment {seg.name} has a flexible input spec"
        prims = _x.host_callback_prims(jaxpr)
        if prims:
            return (
                f"NNS-W120 host callback `{prims[0]}` in segment "
                f"{seg.name}"
            )
        declared = None
        out_spec = seg.last.out_specs[0] if seg.last.out_specs else None
        if out_spec is not None and getattr(out_spec, "is_static", False):
            declared = tuple(t.dtype.np_dtype for t in out_spec)
        msgs = _x.dtype_findings(jaxpr, declared)
        if msgs:
            return f"NNS-W122 in segment {seg.name}: {msgs[0]}"
        if _x.cache_key_finding(seg) is not None:
            return f"NNS-W121 cache-key hazard in segment {seg.name}"
        if _x.donation_finding(seg) is not None:
            return f"NNS-W123 donation hazard in segment {seg.name}"
    bound = configured_device_bound()
    if bound is not None:
        cost = chain_cost(chain, open_backends=True)
        if cost.resident_bytes > bound:
            return (
                f"NNS-W124 resident {cost.resident_bytes} B over the "
                f"[plane] memory_per_device bound ({bound} B)"
            )
    return None


def _clamp_unroll(chain, unroll: int) -> int:
    """Shrink the unroll window until the chain's whole-window working
    set (params + per-frame peak transient × K) fits the declared
    device bound — the W124 discipline applied to the launch width
    (``analysis/costmodel.chain_cost``). No bound declared = the
    configured width stands."""
    from nnstreamer_tpu.analysis.costmodel import (
        chain_cost,
        configured_device_bound,
    )

    bound = configured_device_bound()
    if bound is None or unroll <= 1:
        return unroll
    try:
        cost = chain_cost(chain, open_backends=True)
    except Exception:  # noqa: BLE001 — no estimate: keep the config width
        return unroll
    per = max(1, cost.transient_bytes)
    while unroll > 1 and cost.params_bytes + per * unroll > bound:
        unroll //= 2
    return unroll


def decide_chain(plan, chain) -> ChainDecision:
    """The shared executor/xray/lint verdict for one chain (see
    :class:`ChainDecision`). Cheap checks run first; the jaxpr-walking
    hazard pass only runs for chains that structurally qualify."""
    mode = resolve_chain_mode(chain.ops)
    unroll = resolve_chain_unroll(chain.ops)
    if len(chain.segments) < 2:
        return ChainDecision(
            False, "single segment (the per-node path is already one "
            "program)", mode, unroll,
        )
    if os.environ.get("NNS_NO_FUSE", "").lower() in ("1", "true", "yes"):
        return ChainDecision(
            False, "NNS_NO_FUSE per-element oracle active", mode, unroll
        )
    for seg in chain.segments:
        cfg = seg.batch_config
        if cfg is not None and getattr(cfg, "active", False):
            return ChainDecision(
                False, f"micro-batching active on segment {seg.name}",
                mode, unroll,
            )
        if _gate_active(seg):
            return ChainDecision(
                False,
                f"per-frame error policy active on segment {seg.name}",
                mode, unroll,
            )
    for op in chain.ops:
        if getattr(op, "qos_sources", None):
            return ChainDecision(
                False, f"upstream QoS wired through {op.name}", mode,
                unroll,
            )
    if chain.segments[0]._negotiated_sig() is None and not all(
        seg.is_identity() for seg in chain.segments
    ):
        return ChainDecision(
            False, "flexible input spec at the chain head", mode, unroll
        )
    ext = _interior_external_consumer(plan, chain)
    if ext is not None:
        return ChainDecision(
            False,
            f"interior handoff also feeds {getattr(ext, 'name', ext)} "
            "outside the chain", mode, unroll,
        )
    ext = _interior_external_producer(plan, chain)
    if ext is not None:
        return ChainDecision(
            False,
            f"interior segment also fed by {getattr(ext, 'name', ext)} "
            "outside the chain", mode, unroll,
        )
    try:
        hazard = _hazard(chain)
    except Exception as exc:  # noqa: BLE001 — analysis failure: stay safe
        hazard = f"hazard analysis failed ({exc})"
    if hazard is not None:
        return ChainDecision(False, hazard, mode, unroll)
    return ChainDecision(True, None, mode, _clamp_unroll(chain, unroll))


class ChainProgram:
    """ONE jitted resident program for a whole chain, unrolled K frames
    per launch.

    The trace composes every member op's current fn in chain order —
    interior handoffs become on-device values threaded stage to stage,
    never a host hop — and applies it to each of the K frame slots of a
    window, so steady state dispatches one XLA launch per window
    instead of one per node per frame. Windows are padded up to a
    bucket ladder (1,2,4,...,K — replicas of the last frame, or poison
    under the sanitizer, exactly the ``process_batch`` discipline) so
    the trace count stays O(log K). The jit cache is keyed (per-frame
    sig, bucket, member fn versions, donate) like ``FusedSegment``'s —
    a renegotiated spec or a model hot swap can never be served a stale
    program.
    """

    def __init__(self, chain, unroll: int) -> None:
        self.chain = chain
        self.unroll = max(1, int(unroll))
        self.buckets: Tuple[int, ...] = default_buckets(self.unroll)
        # (sig, bucket, versions, donate) -> jitted fn; _last fast path
        self._cache: Dict[tuple, Callable] = {}
        self._last: Optional[tuple] = None
        self.n_traces = 0
        # single-writer (the owning ChainNode's service thread): one XLA
        # dispatch per increment — the launch-count pin tests assert on
        self.launches = 0
        self.donate = all(seg.donate for seg in chain.segments)
        # set by the executor when its sanitizer is active (pad rows
        # become poison instead of last-frame replicas)
        self.sanitize_poison = False
        self._identity: Optional[bool] = None
        # ops whose class actually overrides transform_meta — skipping
        # the base-class identity hops keeps the per-frame cost of a
        # window O(overriders), not O(members) (at kfps window rates
        # three no-op Python calls per frame are real money)
        from nnstreamer_tpu.elements.base import TensorOp as _TensorOp

        self._meta_ops = [
            op for op in chain.ops
            if type(op).transform_meta is not _TensorOp.transform_meta
        ]

    @property
    def name(self) -> str:
        return self.chain.name

    def is_identity(self) -> bool:
        if self._identity is None:
            self._identity = all(
                seg.is_identity() for seg in self.chain.segments
            )
        return self._identity

    def _versions(self) -> tuple:
        return tuple(op.fn_version for op in self.chain.ops)

    def _compose(self) -> Callable:
        """The whole chain's composed fn, collected FRESH per cache
        fill (a reloaded/renegotiated member contributes its current
        fn). Identity ops contribute passthroughs and collapse out of
        the trace — XLA sees only the real math."""
        fns = [op.make_fn() for op in self.chain.ops]

        def composed(*tensors):
            t = tuple(tensors)
            for f in fns:
                t = tuple(f(t))
            return t

        return composed

    def _unrolled(self, k: int) -> Callable:
        """K literal repetitions of the composed chain over a flat
        argument list of K × T tensors — one program, K independent
        per-frame slices, so results stay bitwise-identical to the
        per-frame path (no vmap re-association)."""
        composed = self._compose()
        if k == 1:
            return composed

        def prog(*flat):
            t = len(flat) // k
            outs: list = []
            for i in range(k):
                outs.extend(composed(*flat[i * t:(i + 1) * t]))
            return tuple(outs)

        return prog

    def bucket_for(self, n: int) -> int:
        n = min(max(1, n), self.unroll)
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _jitted_for(
        self, sig: tuple, bucket: int, donate: bool
    ) -> Callable:
        key = (sig, bucket, self._versions(), donate)
        last = self._last
        if last is not None and last[0] == key:
            return last[1]
        fn = self._cache.get(key)
        if fn is None:
            target = self._unrolled(bucket)
            kw = {}
            if donate:
                # whole-chain donation: the W-window's staged uploads
                # are node-owned, and _aliasable_argnums matches each
                # output slot to at most one input buffer across the
                # ENTIRE unrolled program — interior activations are
                # XLA's to reuse already (they never escape the trace)
                argnums = FusedSegment._aliasable_argnums(
                    target, tuple(sig) * bucket, 0
                )
                if argnums:
                    kw = {"donate_argnums": argnums}
            fn = jax.jit(target, **kw)
            self._cache[key] = fn
            self.n_traces += 1
        self._last = (key, fn)
        return fn

    def build(self) -> None:
        """Warm the steady-state window program at the negotiated spec
        (PAUSED-state parity, ``FusedSegment.build`` discipline): the
        full-unroll bucket on zeros so the first loaded window doesn't
        stall on an XLA compile; smaller buckets fill lazily at
        trickle/EOS boundaries. A deterministic compile failure
        re-raises (the node latches its fallback at build, not
        mid-stream); anything else is a skipped optimization."""
        if self.is_identity():
            return
        sig = self.chain.segments[0]._negotiated_sig()
        if sig is None:
            return
        import numpy as _np

        self._jitted_for(sig, 1, False)
        if self.unroll > 1:
            try:
                zeros = [
                    _np.zeros(shape, dtype)
                    for shape, dtype in sig
                ] * self.unroll
                jax.block_until_ready(
                    self._jitted_for(sig, self.unroll, False)(*zeros)
                )
            except Exception as exc:
                from nnstreamer_tpu.pipeline.device_faults import (
                    classify_device_fault,
                )

                if classify_device_fault(exc) == "compile":
                    raise
                _log.warning(
                    "%s: window warmup failed: %s", self.name, exc
                )

    def _apply_meta(self, f):
        for op in self._meta_ops:
            f = op.transform_meta(f)
        return f

    def process_window(self, frames, donate: bool = False):
        """One window through the resident program. Returns
        ``(out_frames, rows, launched)``: ``rows`` is the dispatched
        bucket width (pad rows included, batch-stats discipline) and
        ``launched`` is False on the no-dispatch paths — an identity
        chain (frames pass untouched) or a heterogeneous/renegotiating
        window (served per frame by the parity oracle, semantics
        identical)."""
        n = len(frames)
        if self.is_identity():
            if not self._meta_ops:
                return list(frames), n, False
            return [self._apply_meta(f) for f in frames], n, False
        sig = FusedSegment._sig_of(frames[0].tensors)
        if n > 1 and any(
            FusedSegment._sig_of(f.tensors) != sig for f in frames[1:]
        ):
            out = [self.process_frame_fallback(f) for f in frames]
            return out, n, False
        bucket = self.bucket_for(n)
        for seg in self.chain.segments:
            probes = seg._device_probes()
            if probes:
                # chaos injectors see the PADDED width — the width the
                # device would see (process_batch parity)
                for probe in probes:
                    probe(bucket)
        fn = self._jitted_for(sig, bucket, donate)
        pad = bucket - n
        flat: list = []
        for f in frames:
            flat.extend(f.tensors)
        if pad:
            filler = None
            if self.sanitize_poison:
                from nnstreamer_tpu.pipeline.sanitize import poison_like

                filler = poison_like
            last = frames[-1].tensors
            for _ in range(pad):
                flat.extend(
                    [filler(t) if filler else t for t in last]
                )
        outs = fn(*flat)
        self.launches += 1
        t = len(outs) // bucket
        meta = self._meta_ops
        result = []
        for j, frame in enumerate(frames):
            f = frame.with_tensors(list(outs[j * t:(j + 1) * t]))
            result.append(self._apply_meta(f) if meta else f)
        return result, bucket, True

    # -- the parity oracle -------------------------------------------------
    def process_frame_fallback(self, frame):
        """One frame through each member segment's OWN jitted program
        in chain order — the exact computation the member FusedNodes
        would run, so results are bitwise-identical to the per-node
        path (the oracle the compiled chain is always checked
        against)."""
        f = frame
        for seg in self.chain.segments:
            f = seg.process(f)
        return f

    def process_frame_eager(self, frame):
        """The degraded-degraded rung: every member segment's un-jitted
        path (a chain whose compiled AND per-segment programs both fault
        still serves, device-circuit semantics)."""
        f = frame
        for seg in self.chain.segments:
            f = seg.process_eager(f)
        return f
