"""Device-plane resilience: classify backend faults, degrade, recover.

PR 3/PR 6 made the frame and client planes fault-tolerant, but the thing
the paper makes TPU-native — the device plane — still died on first
contact: an XLA ``RESOURCE_EXHAUSTED`` inside a fused segment, a
Pallas/jit compile failure, or a lost device killed the executor with no
degradation path. This module is the missing layer
(docs/resilience.md):

- :func:`classify_device_fault` buckets backend exceptions into
  ``oom | compile | device_lost | transient`` (None for ordinary
  element errors — those stay with pipeline/faults.py's per-frame
  policies). Classification is by typed :class:`DeviceFaultError`
  first (the chaos injectors raise these), then by status-message
  sniffing on real XLA runtime errors.
- :class:`BucketGovernor` is the OOM ladder: on OOM the batch bucket
  HALVES (next ladder rung down) and the segment remembers the safe
  ceiling, so adaptive batching can never OOM-loop; after a cooldown
  it re-probes one rung up, reclaiming headroom when the pressure
  (a neighbor's arena, fragmentation) goes away.
- :class:`DeviceCircuit` is the compile/dispatch breaker: a compile
  failure (deterministic — retrying recompiles forever) opens it
  immediately, repeated device faults open it after ``after``
  consecutive hits; while open the segment serves from the host/eager
  path (FusedSegment.process_eager) and probes the jitted path every
  ``probe_every`` frames, closing on recovery. ``device_degraded``
  surfaces in Executor.stats() and nns-obs.

The executor (FusedNode/TensorOpHostNode batched loops) wires these per
segment; parallel/replicas.py reuses the classifier for replica health.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence, Tuple

from nnstreamer_tpu.log import get_logger

_log = get_logger("device_faults")

DEVICE_FAULT_KINDS = ("oom", "compile", "device_lost", "transient")


class DeviceFaultError(RuntimeError):
    """Typed device-plane fault (base). The chaos injectors
    (backends/fakes.py FaultyBackend, elements/chaos.py tensor_chaos)
    raise these so every degradation path is deterministically
    testable; real XLA errors classify by message instead."""

    kind = "transient"


class DeviceOOMError(DeviceFaultError):
    """Device memory exhausted (XLA RESOURCE_EXHAUSTED analogue)."""

    kind = "oom"


class DeviceCompileError(DeviceFaultError):
    """XLA/Pallas compilation failed for this program."""

    kind = "compile"


class DeviceLostError(DeviceFaultError):
    """The accelerator went away (preemption, reset, link loss)."""

    kind = "device_lost"


class ReplicaExhaustedError(RuntimeError):
    """Every replica in a ReplicaSet is unhealthy (parallel/replicas.py);
    carries the last underlying device fault as __cause__."""


# status markers, checked in order — OOM before compile: an OOM raised
# DURING compilation ("while allocating ... for buffer assignment") is a
# memory problem, shrinking helps, recompiling the same program doesn't
_OOM_MARKERS = (
    "resource_exhausted", "out of memory", "out_of_memory", "oom",
    "allocation failure", "ran out of memory",
)
_COMPILE_MARKERS = (
    "compilation failure", "compilation failed", "failed to compile",
    "mosaic", "unimplemented", "unsupported hlo", "lowering",
)
_DEVICE_LOST_MARKERS = (
    "device lost", "device_lost", "device is lost", "device unavailable",
    "failed to connect", "socket closed", "connection reset",
    "deadline_exceeded", "device not found", "tpu driver",
)


def _is_xla_error(exc: BaseException) -> bool:
    # jaxlib.xla_extension.XlaRuntimeError without a hard jaxlib import
    # (class path moved across jax releases; the name has not)
    for klass in type(exc).__mro__:
        if klass.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
    return False


def classify_device_fault(exc: BaseException) -> Optional[str]:
    """``oom | compile | device_lost | transient`` for device-plane
    faults; None for ordinary element errors (bad input, user code) —
    those belong to the per-frame on-error policies, not the device
    resilience layer."""
    if isinstance(exc, DeviceFaultError):
        return exc.kind
    if not _is_xla_error(exc):
        return None
    msg = str(exc).lower()
    for marker in _OOM_MARKERS:
        if marker in msg:
            return "oom"
    for marker in _COMPILE_MARKERS:
        if marker in msg:
            return "compile"
    for marker in _DEVICE_LOST_MARKERS:
        if marker in msg:
            return "device_lost"
    return "transient"


def _executor_device_defaults() -> dict:
    """[executor] device-resilience defaults (env ``NNS_TPU_EXECUTOR_*``
    outranks ini — the standard config layering). Malformed values fall
    back with a warning, same discipline as the batching/fault
    defaults."""
    from nnstreamer_tpu.config import conf

    c = conf()

    def _num(key: str, cast, fallback):
        raw = c.get("executor", key, str(fallback))
        try:
            return cast(raw)
        except ValueError:
            _log.warning(
                "[executor] %s=%r is not a valid %s; using %s",
                key, raw, cast.__name__, fallback,
            )
            return fallback

    oom_policy = c.get("executor", "oom_policy", "degrade").strip().lower()
    if oom_policy not in ("degrade", "stop"):
        _log.warning(
            "[executor] oom_policy=%r not one of degrade/stop; "
            "using 'degrade'", oom_policy,
        )
        oom_policy = "degrade"
    return {
        "oom-policy": oom_policy,
        "device-fallback": c.get_bool("executor", "device_fallback", True),
        "device-fallback-after": _num("device_fallback_after", int, 3),
        "device-probe-every": _num("device_probe_every", int, 64),
        "oom-reprobe-ms": _num("oom_reprobe_ms", float, 30000.0),
    }


def resolve_device_policy(elements: Sequence[Any]) -> Dict[str, Any]:
    """Merge element-level ``oom-policy``/``device-fallback`` properties
    over the ``[executor]`` defaults — chain-order scan, first element
    that sets a knob wins (the resolve_batch_config discipline)."""
    from nnstreamer_tpu.elements.base import _parse_bool

    defaults = _executor_device_defaults()
    oom_policy: Optional[str] = None
    fallback: Optional[bool] = None
    for e in elements:
        get = getattr(e, "get_property", None)
        if get is None:
            continue
        if oom_policy is None and get("oom-policy") is not None:
            raw = str(get("oom-policy")).strip().lower()
            if raw not in ("degrade", "stop"):
                raise ValueError(
                    f"{getattr(e, 'name', e)}: oom-policy={raw!r} not one "
                    "of degrade/stop"
                )
            oom_policy = raw
        if fallback is None and get("device-fallback") is not None:
            fallback = _parse_bool(get("device-fallback"))
    return {
        "oom-policy": oom_policy or defaults["oom-policy"],
        "device-fallback": (
            defaults["device-fallback"] if fallback is None else fallback
        ),
        "device-fallback-after": max(1, defaults["device-fallback-after"]),
        "device-probe-every": max(1, defaults["device-probe-every"]),
        "oom-reprobe-ms": max(0.0, defaults["oom-reprobe-ms"]),
    }


class BucketGovernor:
    """Per-segment safe batch ceiling under OOM (single-writer: the
    node's service thread; observers get GIL-atomic reads).

    ``cap()`` is the window limit the batch collector and the split
    loop honor. On OOM, ``on_oom(attempted)`` drops the ceiling to the
    next ladder rung below the attempted bucket (None when already at
    1 — nothing left to shrink) and stamps a cooldown; once it
    elapses, ``cap()`` offers ONE rung above the ceiling as a probe,
    and ``on_ok``/``on_oom`` of that probe raises the ceiling or
    pushes the cooldown out again. The ladder is the segment's bucket
    ladder, so every ceiling is a real compiled-bucket size."""

    __slots__ = ("ladder", "ceiling", "cooldown_s", "ooms", "reprobes",
                 "_probe_at", "_clock")

    def __init__(
        self,
        ladder: Sequence[int],
        cooldown_s: float = 30.0,
        clock=time.monotonic,
    ) -> None:
        self.ladder: Tuple[int, ...] = tuple(sorted(set(int(b) for b in ladder))) or (1,)
        self.ceiling = self.ladder[-1]
        self.cooldown_s = cooldown_s
        self.ooms = 0          # OOM events observed
        self.reprobes = 0      # successful upward re-probes
        self._probe_at: Optional[float] = None  # monotonic reprobe gate
        self._clock = clock

    @property
    def degraded(self) -> bool:
        return self.ceiling < self.ladder[-1]

    def _stamp_cooldown(self) -> Optional[float]:
        """cooldown <= 0 means NEVER re-probe upward (a zero cooldown
        would otherwise offer the probe rung on every cap() call — a
        persistently-OOMing probe width then livelocks the service
        loop's shrink-retry ladder)."""
        if self.cooldown_s <= 0:
            return None
        return self._clock() + self.cooldown_s

    def cap(self) -> int:
        """Current window limit — the ceiling, or one rung above it
        when the reprobe cooldown has elapsed (the probe window)."""
        if (
            self.degraded
            and self._probe_at is not None
            and self._clock() >= self._probe_at
        ):
            i = self.ladder.index(self.ceiling)
            return self.ladder[min(i + 1, len(self.ladder) - 1)]
        return self.ceiling

    def on_ok(self, bucket: int) -> bool:
        """A dispatch at ``bucket`` rows succeeded. Returns True when
        this confirmed an upward probe (the ceiling moved) — a probe
        only confirms at the probe width itself; narrower dispatches
        during the probe window leave the ceiling untouched. The host
        path dispatches arbitrary widths (no bucket padding), so the
        confirmed width snaps DOWN to its ladder rung — the ceiling
        must stay a real rung or cap()'s ladder walk breaks."""
        below = [b for b in self.ladder if b <= bucket]
        rung = below[-1] if below else self.ladder[0]
        if rung > self.ceiling:
            # a probe succeeded: reclaim one rung; keep probing upward
            # (after another cooldown) until back at the full ladder
            self.ceiling = rung
            self.reprobes += 1
            _log.warning(
                "OOM ceiling re-probed up to %d%s", rung,
                "" if self.degraded else " (fully recovered)",
            )
            self._probe_at = (
                self._stamp_cooldown() if self.degraded else None
            )
            return True
        return False

    def on_oom(self, attempted: int) -> Optional[int]:
        """Shrink below ``attempted``; returns the new ceiling, or None
        when attempted was already the smallest bucket (the caller then
        treats the OOM like any other device fault)."""
        self.ooms += 1
        below = [b for b in self.ladder if b < max(1, int(attempted))]
        self._probe_at = self._stamp_cooldown()
        if not below:
            return None
        if below[-1] < self.ceiling or attempted > self.ceiling:
            self.ceiling = min(self.ceiling, below[-1])
        return below[-1]

    def snapshot(self) -> dict:
        return {
            "ceiling": self.ceiling,
            "max": self.ladder[-1],
            "ooms": self.ooms,
            "reprobes": self.reprobes,
        }

    def restore(self, snap: dict) -> None:
        """Warm-restart: re-arm the remembered safe ceiling (and its
        reprobe cooldown) so a restarted pipeline does not re-discover
        the OOM boundary by OOMing again."""
        ceiling = int(snap.get("ceiling", self.ladder[-1]))
        below = [b for b in self.ladder if b <= ceiling]
        self.ceiling = below[-1] if below else self.ladder[0]
        self.ooms = int(snap.get("ooms", 0))
        self.reprobes = int(snap.get("reprobes", 0))
        if self.degraded:
            self._probe_at = self._stamp_cooldown()


class DeviceCircuit:
    """Compile/dispatch circuit breaker for one execution node.

    ``record_fault(kind)`` returns True when the caller should serve
    the frame from the degraded (host/eager) path: immediately for
    ``compile`` (deterministic — a per-frame recompile loop is the
    failure mode this exists to prevent), after ``after`` CONSECUTIVE
    device faults otherwise. While open, ``should_probe()`` goes True
    every ``probe_every`` degraded frames; a successful probe
    ``close()``s the circuit. Mirrors tensor_filter's
    fallback-framework breaker, one level down the stack."""

    __slots__ = ("after", "probe_every", "open", "kinds", "_consec",
                 "_since_probe", "opens", "closes", "eager_invokes",
                 "faults")

    def __init__(self, after: int = 3, probe_every: int = 64) -> None:
        self.after = max(1, int(after))
        self.probe_every = max(1, int(probe_every))
        self.open = False
        self.faults = 0                      # classified device faults
        self.kinds: Dict[str, int] = {}      # kind -> count
        self.opens = 0
        self.closes = 0
        self.eager_invokes = 0               # frames served degraded
        self._consec = 0
        self._since_probe = 0

    def record_fault(self, kind: str) -> bool:
        self.faults += 1
        self.kinds[kind] = self.kinds.get(kind, 0) + 1
        self._consec += 1
        if self.open:
            return True
        if kind == "compile" or self._consec >= self.after:
            self.open = True
            self.opens += 1
            self._since_probe = 0
            _log.warning(
                "device circuit OPEN after %d fault(s) (last: %s); "
                "serving from the host/eager path", self._consec, kind,
            )
            return True
        return False

    def record_ok(self) -> None:
        self._consec = 0

    def should_probe(self) -> bool:
        """Call once per degraded frame; True on the probe beat."""
        self._since_probe += 1
        if self._since_probe >= self.probe_every:
            self._since_probe = 0
            return True
        return False

    def close(self) -> None:
        if self.open:
            self.open = False
            self.closes += 1
            _log.warning("device circuit closed: jitted path recovered")
        self._consec = 0

    def snapshot(self) -> dict:
        return {
            "open": self.open,
            "faults": self.faults,
            "kinds": dict(self.kinds),
            "opens": self.opens,
            "closes": self.closes,
            "eager_invokes": self.eager_invokes,
        }

    def restore(self, snap: dict) -> None:
        self.open = bool(snap.get("open", False))
        self.faults = int(snap.get("faults", 0))
        self.kinds = {
            str(k): int(v) for k, v in (snap.get("kinds") or {}).items()
        }
        self.opens = int(snap.get("opens", 0))
        self.closes = int(snap.get("closes", 0))
        self.eager_invokes = int(snap.get("eager_invokes", 0))
        self._consec = 0
        self._since_probe = 0
