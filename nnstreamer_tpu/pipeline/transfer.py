"""Async transfer engine: staged H2D, coalesced D2H, transfer accounting.

The device plane's three transfer disciplines (docs/streaming.md), built
for the resident streaming executor's double-buffered frame ring:

- **Staged H2D** (`stage_frame`, `stage_iter`): host tensors become
  device arrays via ``jax.device_put`` — an *async* call, so issuing the
  put for frame N+1 while frame N's compute occupies the device overlaps
  the wire time with useful work. On a process-local CPU backend the put
  is a pure pessimization (the "device" IS host memory, and the jitted
  call's own ingest is a plain — often zero-copy — memcpy), so staging
  there is a pass-through unless ``force`` asks for a real copy (the
  donation path needs one: ``jnp.asarray`` ALIASES host numpy buffers on
  CPU, and a donated alias would let the program scribble on the
  caller's array).
- **Coalesced D2H** (`FrameFetch`): a frame's (or a whole sink window's)
  tensors ride ONE ``copy_to_host_async`` instead of one per tensor —
  per-transfer latency dominates small results on a remote-attached
  device, so T tensors × W frames must not pay T·W round trips. A
  cached jitted packer bitcasts every tensor to a flat uint8 buffer and
  concatenates; the host side splits the single fetched buffer back by
  dtype/shape with numpy views (no second copy). Process-local CPU
  arrays skip the packer — ``np.asarray`` there is a memcpy, and the
  eager stack/concat ops the packer replaces cost more than they save.
- **Accounting** (`tally`, ``nns_transfer_bytes_total``): every byte
  that crosses the host↔device boundary through this module is counted
  by direction, so "adjacent fused segments hand off on device with
  ZERO host materialization" is an assertable number, not a hope.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from nnstreamer_tpu.log import get_logger

_log = get_logger("transfer")


# -- transfer accounting ----------------------------------------------------

class TransferTally:
    """Process-local transfer byte/event counters (always on — the obs
    registry mirrors them into ``nns_transfer_bytes_total`` when metrics
    are enabled). One short lock per *event* (a frame's worth of
    tensors), never per tensor: the lock rides a boundary that already
    implies a host↔device copy."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.h2d_events = 0
        self.d2h_events = 0

    def count(self, direction: str, nbytes: int) -> None:
        with self._lock:
            if direction == "h2d":
                self.h2d_bytes += nbytes
                self.h2d_events += 1
            else:
                self.d2h_bytes += nbytes
                self.d2h_events += 1

    def reset(self) -> None:
        with self._lock:
            self.h2d_bytes = self.d2h_bytes = 0
            self.h2d_events = self.d2h_events = 0

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "h2d_events": self.h2d_events,
                "d2h_events": self.d2h_events,
            }


#: module-level tally: tests assert zero-materialization handoffs here;
#: the executor adds per-element obs counters on top.
tally = TransferTally()

_mirror_lock = threading.Lock()
_mirrored = {"h2d": 0, "d2h": 0}


def mirror_into(metrics) -> None:
    """Advance the ``nns_transfer_bytes_total`` counters to match the
    process tally. Watermark-based: several executors stopping in one
    process each publish only the not-yet-mirrored delta, so the
    global counter never double-counts shared traffic (per-run
    attribution lives in ``Executor.totals()["transfer"]``)."""
    snap = tally.snapshot()
    with _mirror_lock:
        for direction, key in (("h2d", "h2d_bytes"), ("d2h", "d2h_bytes")):
            delta = snap[key] - _mirrored[direction]
            if delta > 0:
                _mirrored[direction] += delta
                metrics.counter(
                    "nns_transfer_bytes_total", direction=direction
                ).inc(delta)


def _nbytes(tensors: Iterable[Any]) -> int:
    total = 0
    for t in tensors:
        size = getattr(t, "nbytes", None)
        if size is None:
            size = int(np.prod(t.shape)) * np.dtype(t.dtype).itemsize
        total += int(size)
    return total


# -- placement probes -------------------------------------------------------

def is_device_array(t: Any) -> bool:
    """True for arrays living behind a device runtime (jax.Array duck
    type) — numpy and scalars are host by definition."""
    return hasattr(t, "copy_to_host_async")


def _platform_of(t: Any) -> Optional[str]:
    try:
        devs = t.devices()
        for d in devs:
            return d.platform
    except Exception:  # noqa: BLE001 — deleted/donated array
        return None
    return None


def is_local_cpu(t: Any) -> bool:
    """True when ``t`` lives on a process-local CPU backend: fetching is
    a memcpy (or free), so neither the packer nor async staging pays."""
    return _platform_of(t) == "cpu"


_default_cpu: Optional[bool] = None


def default_backend_is_cpu() -> bool:
    """Cached ``jax.default_backend() == 'cpu'`` (the staging bypass
    decision is per-process, not per-frame)."""
    global _default_cpu
    if _default_cpu is None:
        import jax

        _default_cpu = jax.default_backend() == "cpu"
    return _default_cpu


def _cpu_target(device) -> bool:
    """True when staging would target process-local CPU memory — the
    default backend with no explicit device, or an explicit CPU device.
    Either way the put is a copy into the same RAM the tensor already
    occupies."""
    if device is None:
        return default_backend_is_cpu()
    return getattr(device, "platform", None) == "cpu"


# -- staged H2D -------------------------------------------------------------

def stage_frame(frame, device=None, force: bool = False):
    """Upload a frame's host tensors to ``device`` via async
    ``jax.device_put``; device-resident tensors pass through untouched.
    Returns the staged frame (the SAME frame object when nothing moved).

    On a process-local CPU backend the put is skipped unless ``force``:
    the jitted call ingests host numpy directly (zero-copy on aligned
    buffers), and an explicit put would add a copy for nothing. ``force``
    exists for the donation path, which must own a private device buffer
    (``jax.device_put`` COPIES host memory — post-submit mutation of the
    source array cannot reach the program)."""
    if not force and _cpu_target(device):
        return frame
    host_idx = [
        i for i, t in enumerate(frame.tensors) if not is_device_array(t)
    ]
    if not host_idx:
        return frame
    import jax

    tensors = list(frame.tensors)
    moved = [tensors[i] for i in host_idx]
    tally.count("h2d", _nbytes(moved))
    for i in host_idx:
        tensors[i] = jax.device_put(tensors[i], device)
    return frame.with_tensors(tensors)


def stage_iter(arrays: Iterable[Any], device=None, depth: int = 3) -> Iterator[Any]:
    """Pipeline ``jax.device_put`` uploads on a feeder thread, yielding
    staged device arrays in order with up to ``depth`` uploads in
    flight — the bench's streaming-ingest harness (H2D of frame N+1
    overlaps compute of frame N even when the put itself blocks on a
    tunnel round trip). On a process-local CPU backend the arrays are
    yielded as-is: the jitted call's own ingest is the cheaper copy."""
    if _cpu_target(device):
        for a in arrays:
            yield a
        return
    import queue as queue_mod

    import jax

    q: queue_mod.Queue = queue_mod.Queue(maxsize=max(1, depth))
    _END = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def _put(item) -> bool:
        # bounded put that gives up when the consumer abandoned the
        # generator — a plain q.put would park this thread forever
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def _feed() -> None:
        try:
            for a in arrays:
                if stop.is_set():
                    return
                tally.count("h2d", _nbytes((a,)))
                if not _put(jax.device_put(a, device)):
                    return
        except Exception as exc:  # noqa: BLE001 — re-raised by consumer
            err.append(exc)
        finally:
            _put(_END)

    th = threading.Thread(target=_feed, name="nns-h2d-stager", daemon=True)
    th.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            yield item
        if err:
            # a mid-stream device_put failure must surface as an error,
            # not as a silently truncated stream (a bench loop counting
            # planned iterations would publish inflated fps)
            raise err[0]
    finally:
        stop.set()
        try:
            while True:  # unblock a feeder parked on a full queue
                q.get_nowait()
        except queue_mod.Empty:
            pass
        th.join(timeout=5.0)


# -- coalesced D2H ----------------------------------------------------------

# signature -> jitted packer. A signature is ((shape, dtype), ...) over
# every tensor in the fetch set; entries are tiny programs (bitcast +
# concat) and the set of signatures is bounded by the pipeline's
# negotiated specs × sink window sizes.
_packer_cache: Dict[tuple, Callable] = {}
_packer_lock = threading.Lock()


def _sig_of(tensors) -> tuple:
    return tuple((tuple(t.shape), np.dtype(t.dtype)) for t in tensors)


def _make_packer() -> Callable:
    import jax
    import jax.numpy as jnp
    from jax import lax

    def pack(*ts):
        parts = []
        for t in ts:
            if t.dtype == jnp.bool_:
                # bitcast rejects bool; uint8 has identical bytes
                t = t.astype(jnp.uint8)
            u = lax.bitcast_convert_type(t, jnp.uint8)
            parts.append(u.reshape(-1))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return jax.jit(pack)


def _packer_for(sig: tuple) -> Callable:
    with _packer_lock:
        fn = _packer_cache.get(sig)
        if fn is None:
            fn = _make_packer()
            _packer_cache[sig] = fn
    return fn


class FrameFetch:
    """One in-flight coalesced D2H fetch for an ordered set of device
    tensors (a frame's worth, or a whole sink window's).

    ``start`` dispatches the cached packer (one device-side flatten +
    concat) and begins ONE async host copy of the packed buffer;
    ``finish`` materializes numpy tensors by splitting the single
    fetched buffer with views. Anything that can't ride the packer —
    local CPU arrays, host tensors already, packer trace failures —
    degrades to per-tensor fetches, never an error: the fetch is an
    optimization, correctness lives in finish() always returning host
    arrays."""

    __slots__ = ("_tensors", "_sig", "_packed", "_dev_idx", "_per_tensor")

    def __init__(self, tensors: List[Any]) -> None:
        self._tensors = list(tensors)
        self._sig = None
        self._packed = None
        self._dev_idx: List[int] = []
        self._per_tensor = False

    def _fetch_per_tensor(self, dev_ts) -> "FrameFetch":
        """Shared degradation tail: one async copy per device tensor,
        best-effort (finish() materializes with np.asarray either
        way)."""
        self._per_tensor = True
        for t in dev_ts:
            try:
                t.copy_to_host_async()
            except Exception:  # noqa: BLE001 — fetch is best-effort
                pass
        return self

    def start(self) -> "FrameFetch":
        ts = self._tensors
        dev_idx = [i for i, t in enumerate(ts) if is_device_array(t)]
        dev_ts = [ts[i] for i in dev_idx]
        if not dev_ts:
            return self
        tally.count("d2h", _nbytes(dev_ts))
        if len(dev_ts) < 2 or is_local_cpu(dev_ts[0]):
            # a lone tensor is already one transfer; local CPU arrays
            # fetch by memcpy — the packer would only add dispatches
            return self._fetch_per_tensor(dev_ts)
        if len({_platform_of(t) for t in dev_ts}) > 1:
            # tensors pinned across devices can't share one packed
            # buffer without migrating them; per-tensor keeps placement
            return self._fetch_per_tensor(dev_ts)
        try:
            # only the DEVICE tensors ride the packer: jit-ingesting an
            # already-host tensor would pay a pointless H2D upload just
            # to copy the same bytes back; finish() splices host
            # tensors through untouched
            sig = _sig_of(dev_ts)
            packed = _packer_for(sig)(*dev_ts)
            packed.copy_to_host_async()
            self._sig = sig
            self._packed = packed
            self._dev_idx = dev_idx
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            _log.debug("packed fetch unavailable: %s", exc)
            return self._fetch_per_tensor(dev_ts)
        return self

    def finish(self) -> List[Any]:
        """Host (numpy) tensors, in order. Blocks only on whatever part
        of the async copy hasn't landed yet."""
        if self._packed is not None:
            buf = np.asarray(self._packed)
            fetched: List[Any] = []
            offset = 0
            for shape, dtype in self._sig:
                n = int(np.prod(shape)) * dtype.itemsize
                view = buf[offset:offset + n]
                if dtype == np.bool_:
                    arr = view.view(np.uint8).astype(np.bool_)
                else:
                    arr = view.view(dtype)
                fetched.append(arr.reshape(shape))
                offset += n
            out = list(self._tensors)
            for i, arr in zip(self._dev_idx, fetched):
                out[i] = arr
            return out
        return [
            np.asarray(t) if is_device_array(t) else t
            for t in self._tensors
        ]


def fetch_frame(frame) -> FrameFetch:
    """Start a coalesced async D2H for one frame's tensors."""
    return FrameFetch(list(frame.tensors)).start()


def fetch_window(frames: List[Any]) -> List[Any]:
    """Materialize a window of frames to host through ONE coalesced
    fetch across every tensor of every frame (the sink sync-window
    path), returning host-tensor frames in order. All-host windows
    (the executor-ceiling pipelines) return as-is — W×T ``is_device``
    probes are the only cost, not W new frame objects."""
    flat: List[Any] = []
    counts: List[int] = []
    for f in frames:
        counts.append(len(f.tensors))
        flat.extend(f.tensors)
    if not any(is_device_array(t) for t in flat):
        return frames
    fetched = FrameFetch(flat).start().finish()
    out = []
    i = 0
    for f, n in zip(frames, counts):
        out.append(f.with_tensors(fetched[i:i + n]).mark_synced())
        i += n
    return out


# -- stream (ring) configuration -------------------------------------------

def resolve_ring_depth(elems) -> int:
    """Resolve the in-flight frame ring depth for an execution node:
    the first member element's ``ring-depth`` property outranks the
    ``[executor] ring_depth`` config default (NNS_TPU_EXECUTOR_RING_DEPTH
    env over ini, the standard layering). Clamped to [1, 32]; 1 is the
    synchronous dispatch-and-deliver discipline."""
    from nnstreamer_tpu.config import conf

    raw = None
    for e in elems:
        raw = e.get_property("ring-depth")
        if raw is not None:
            break
    if raw is None:
        raw = conf().get("executor", "ring_depth", "2")
    try:
        depth = int(raw)
    except (TypeError, ValueError):
        _log.warning("ring-depth=%r is not an int; using 2", raw)
        depth = 2
    return max(1, min(32, depth))


def resolve_chain_mode(elems) -> str:
    """Resolve whole-chain compilation mode for one chain
    (pipeline/chain_program.py): ``off`` from ANY member element's
    ``chain-mode`` property outranks the ``[executor] chain_mode``
    config default (NNS_TPU_EXECUTOR_CHAIN_MODE env over ini) — one
    member opting out keeps the whole chain on the per-node parity
    path, mirroring how one non-traceable op severs fusion. Unknown
    values fall back to ``auto`` with a warning."""
    from nnstreamer_tpu.config import conf

    raw = None
    for e in elems:
        get = getattr(e, "get_property", None)
        got = get("chain-mode") if get is not None else None
        if got is not None:
            raw = str(got).strip().lower()
            if raw == "off":
                return "off"
    if raw is None:
        raw = str(conf().get("executor", "chain_mode", "auto")).strip().lower()
    if raw not in ("auto", "off"):
        _log.warning("chain-mode=%r not one of auto/off; using auto", raw)
        return "auto"
    return raw


def resolve_chain_unroll(elems) -> int:
    """Frames per compiled-chain launch window (``[executor]
    chain_unroll``, default 4, clamped to [1, 32]) — the STATIC ceiling;
    pipeline/chain_program.py further clamps it by the W124
    transient-HBM bound and the runtime OOM bucket governor rung."""
    from nnstreamer_tpu.config import conf

    raw = conf().get("executor", "chain_unroll", "4")
    try:
        unroll = int(raw)
    except (TypeError, ValueError):
        _log.warning("[executor] chain_unroll=%r is not an int; using 4", raw)
        unroll = 4
    return max(1, min(32, unroll))


def xray_crosscheck_enabled() -> bool:
    """``NNS_XRAY_CROSSCHECK`` env first, then ``[executor]
    xray_crosscheck`` (default off): the executor then compares the
    nns-xray static transfer prediction against this tally at stop()
    and logs the verdict — the cost model's verification loop
    (docs/chain-analysis.md)."""
    raw = os.environ.get("NNS_XRAY_CROSSCHECK")
    if raw is not None:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    from nnstreamer_tpu.config import conf

    return conf().get_bool("executor", "xray_crosscheck", False)


def donation_enabled() -> bool:
    """``[executor] donate`` (default on): donate node-OWNED activation
    buffers (staged H2D uploads, stacked batch windows) to the fused
    program so XLA reuses them for outputs instead of growing the
    arena. Only buffers this runtime itself created are ever donated —
    an upstream element's array may be shared or reused (tee fan-out,
    source frame pools), and donating one would delete it under the
    owner."""
    from nnstreamer_tpu.config import conf

    return conf().get_bool("executor", "donate", True)
