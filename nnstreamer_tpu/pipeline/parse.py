"""Pipeline description parser: gst-launch syntax → Pipeline.

The reference's user interface is gst-launch-1.0 pipeline strings
(SURVEY.md §1 L6; the flex/bison parser in tools/development/parser/).
This parser covers the practically-used grammar:

    chain    := node ( '!' node )*
    node     := element | caps | ref
    element  := NAME (key=value)*          # value may be 'quoted'
    caps     := media/type[,key=value...]  # becomes a capsfilter
    ref      := NAME. | NAME.src_N | NAME.sink_N | NAME.N

Branches: a chain starting with ``name.`` continues from that named
element (tee/demux fan-out), a chain ending in ``name.sink_N`` terminates
into it (mux fan-in) — gst-launch semantics:

    videotestsrc num-frames=8 ! tee name=t
        t. ! queue ! tensor_converter ! tensor_sink name=a
        t. ! queue ! tensor_converter ! tensor_sink name=b
"""

from __future__ import annotations

import re
import shlex
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import Element
from nnstreamer_tpu.pipeline.graph import Pipeline

_REF_RE = re.compile(r"^([A-Za-z_][\w-]*)\.(?:(src|sink)_(\d+)|(\d+))?$")
_PROP_RE = re.compile(r"^([A-Za-z_][\w-]*)=(.*)$", re.S)
_CAPS_RE = re.compile(r"^[a-z]+/[\w.+-]+(,.*)?$")


class ParseError(ValueError):
    pass


def _tokenize(description: str) -> List[str]:
    lex = shlex.shlex(description, posix=True)
    lex.whitespace_split = True
    lex.commenters = "#"
    return list(lex)


def _parse_caps(token: str) -> Tuple[str, Dict[str, str]]:
    parts = token.split(",")
    media = parts[0]
    fields: Dict[str, str] = {}
    for p in parts[1:]:
        if "=" not in p:
            raise ParseError(f"bad caps field {p!r} in {token!r}")
        k, v = p.split("=", 1)
        # strip any '(type)' annotation — (string), (int), (fraction),
        # (boolean), (uint), ... must never leak into the field value
        v = re.sub(r"^\([A-Za-z]\w*\)", "", v.strip())
        fields[k.strip()] = v
    return media, fields


def _make_caps_element(media: str, fields: Dict[str, str]) -> Element:
    cls = registry.get(registry.KIND_ELEMENT, "capsfilter")
    props: Dict[str, str] = {}
    if media == "other/tensors" or media == "other/tensor":
        if "dimensions" in fields:
            props["dimensions"] = fields["dimensions"]
        elif "dimension" in fields:
            props["dimensions"] = fields["dimension"]
        if "types" in fields:
            props["types"] = fields["types"]
        elif "type" in fields:
            props["types"] = fields["type"]
        if "format" in fields:
            props["format"] = fields["format"]
        if "framerate" in fields:
            props["framerate"] = fields["framerate"]
    else:
        props["media"] = media.split("/", 1)[0]
        props.update(fields)
    return cls(**props)


class _Builder:
    def __init__(self) -> None:
        self.pipeline = Pipeline()
        self.prev: Optional[Element] = None
        self.prev_src_pad: Optional[int] = None
        self.expect_link = False

    def attach(self, elem: Element) -> None:
        self._attach(elem, None)

    def ref_token(self, name: str, pad_kind: Optional[str], pad: Optional[int]) -> None:
        try:
            elem = self.pipeline[name]
        except KeyError as exc:
            raise ParseError(f"reference to unknown element {name!r}") from exc
        if self.expect_link:
            # link target: '... ! mux.sink_0' — chain terminates here
            dst_pad = pad if pad_kind in (None, "sink") else None
            self.pipeline.link(self.prev, elem, src_pad=self.prev_src_pad, dst_pad=dst_pad)
            self.prev = None
            self.prev_src_pad = None
            self.expect_link = False
        else:
            # branch start: 't. ! ...' — continue from named element
            self.prev = elem
            self.prev_src_pad = pad if pad_kind in (None, "src") else None

    def _attach(self, elem: Element, dst_pad: Optional[int]) -> None:
        if self.expect_link:
            if self.prev is None:
                raise ParseError("dangling '!'")
            self.pipeline.link(self.prev, elem, src_pad=self.prev_src_pad, dst_pad=dst_pad)
            self.expect_link = False
        self.prev = elem
        self.prev_src_pad = None

    def bang(self) -> None:
        if self.prev is None:
            raise ParseError("'!' with nothing to link from")
        if self.expect_link:
            raise ParseError("duplicate '!'")
        self.expect_link = True


def _scan(tokens: List[str]):
    """Token stream → item list: ('bang',), ('ref', name, kind, pad),
    ('caps', token), ('element', factory, props)."""
    items = []
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok == "!":
            items.append(("bang",))
            i += 1
            continue
        ref = _REF_RE.match(tok)
        if ref and "=" not in tok:
            name, kind, pad_s, pad2 = ref.groups()
            pad = int(pad_s) if pad_s is not None else (int(pad2) if pad2 else None)
            items.append(("ref", name, kind, pad))
            i += 1
            continue
        if _CAPS_RE.match(tok) and "=" not in tok.split(",")[0]:
            items.append(("caps", tok))
            i += 1
            continue
        if not re.match(r"^[A-Za-z_][\w-]*$", tok):
            raise ParseError(f"unexpected token {tok!r}")
        props: Dict[str, str] = {}
        j = i + 1
        while j < len(tokens):
            m = _PROP_RE.match(tokens[j])
            if not m or tokens[j] == "!":
                break
            props[m.group(1)] = m.group(2)
            j += 1
        items.append(("element", tok, props))
        i = j
    return items


def scan_description(description: str):
    """Tokenize + scan a launch string into structural items without
    instantiating anything — the shared front end of parse_pipeline and
    the static analyzer (nnstreamer_tpu.analysis). Raises ParseError."""
    tokens = _tokenize(description)
    if not tokens:
        raise ParseError("empty pipeline description")
    return _scan(tokens)


def parse_pipeline(description: str) -> Pipeline:
    items = scan_description(description)
    # pass 1: instantiate all elements so forward references ('! mux.sink_0'
    # before 'tensor_mux name=mux' appears, gst-launch-legal) resolve
    b = _Builder()
    instances: List[Optional[Element]] = []
    for item in items:
        if item[0] == "element":
            _, factory, props = item
            cls = registry.get(registry.KIND_ELEMENT, factory)
            props = dict(props)
            elem_name = props.pop("name", None)
            try:
                elem = cls(name=elem_name, **props)
            except TypeError as exc:
                # a bare TypeError from cls(**props) is useless to the
                # user — name the element and the offending property
                m = re.search(r"unexpected keyword argument '([^']+)'",
                              str(exc))
                what = (
                    f"unknown property {m.group(1)!r}" if m
                    else f"bad properties {sorted(props)}"
                )
                raise ParseError(
                    f"element {factory!r}"
                    f"{f' (name={elem_name})' if elem_name else ''}: "
                    f"{what}: {exc}"
                ) from exc
            b.pipeline.add(elem)
            instances.append(elem)
        elif item[0] == "caps":
            media, fields = _parse_caps(item[1])
            elem = _make_caps_element(media, fields)
            b.pipeline.add(elem)
            instances.append(elem)
        else:
            instances.append(None)
    # pass 2: wire links
    for item, inst in zip(items, instances):
        if item[0] == "bang":
            b.bang()
        elif item[0] == "ref":
            _, name, kind, pad = item
            b.ref_token(name, kind, pad)
        else:
            b.attach(inst)
    if b.expect_link:
        raise ParseError("pipeline ends with '!'")
    return b.pipeline
