"""nns-san runtime side: the pipeline sanitizer.

Enabled with ``NNS_TPU_SANITIZE=1`` (or ``[executor] sanitize = true``;
env wins, the standard layering). When on, the executor swaps every
inter-node channel for an instrumented :class:`SanChan` and checks the
invariants the streaming machinery is supposed to preserve but nothing
verified until now:

- **spec conformance (NNS-S001)** — every frame put onto a negotiated
  STATIC link must match the consumer pad's ``TensorsSpec`` (tensor
  count, shapes modulo wildcards, dtypes). A violation raises a typed
  :class:`SpecViolationError` through the producing node, so the stream
  fails AT the corruption point instead of wherever the drifted shape
  finally crashes (or silently retraces) downstream.
- **frame accounting (NNS-S002)** — at clean EOS, for every node whose
  element declares 1:1 cardinality (``SAN_ONE_TO_ONE``) or is a fused
  segment of pure TensorOps: ``offered == delivered + dropped + routed``.
  Catches frames silently vanishing (an element returning None without
  accounting) and duplication.
- **lock order (NNS-S003)** — :class:`TrackedLock` records per-thread
  acquisition order into a :class:`LockOrderGraph`; a cyclic edge set is
  a latent deadlock, reported with the cycle. The executor wraps its own
  locks; user/test code can watch more via :meth:`Sanitizer.lock`.
- **thread leaks (NNS-S004)** — ``Executor.stop()`` joins every thread it
  started with a bounded budget and reports stragglers; under the
  sanitizer, threads that appeared during the run (element/edge service
  threads) and outlive shutdown are reported too.
- **pad-row poison** — micro-batch padding rows are filled with poison
  (NaN / integer max) instead of replicas of the last frame, so an
  off-by-one in batch splitting surfaces as an obviously-wrong value
  instead of a plausibly-stale one (``graph.py process_batch``).

Findings are the same structured Diagnostics nns-lint uses (codes
``NNS-S0xx``), surfaced through ``Executor.sanitizer.report``,
``Executor.stats()`` per-node counters, and ``trace.py`` instant events.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.analysis.diagnostics import LintReport
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame
from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

_log = get_logger("sanitize")

_TRUTHY = ("1", "true", "yes", "on")


def sanitize_enabled() -> bool:
    """``NNS_TPU_SANITIZE`` env first (the documented one-knob opt-in),
    then ``[executor] sanitize`` through the layered config."""
    raw = os.environ.get("NNS_TPU_SANITIZE")
    if raw is not None:
        return raw.strip().lower() in _TRUTHY
    from nnstreamer_tpu.config import conf

    return conf().get_bool("executor", "sanitize", False)


class SpecViolationError(TypeError):
    """A frame failed the negotiated-spec check on a link (NNS-S001)."""

    def __init__(self, node: str, pad: int, detail: str) -> None:
        self.node = node
        self.pad = pad
        super().__init__(
            f"sanitizer: frame into {node!r} sink pad {pad} violates the "
            f"negotiated spec: {detail}"
        )


def frame_conforms(frame: Any, spec: TensorsSpec) -> Optional[str]:
    """None when `frame` matches `spec`, else a mismatch description.
    Only STATIC specs constrain; wildcard dims unify with anything."""
    if not isinstance(frame, Frame):
        return f"not a Frame: {type(frame).__name__}"
    if len(frame.tensors) != spec.num_tensors:
        return (
            f"{len(frame.tensors)} tensors, spec says {spec.num_tensors}"
        )
    for i, (t, ts) in enumerate(zip(frame.tensors, spec.tensors)):
        shape = tuple(int(d) for d in t.shape)
        if len(shape) != len(ts.shape) or any(
            want is not None and got != want
            for got, want in zip(shape, ts.shape)
        ):
            return f"tensor {i} shape {shape}, spec {ts.shape}"
        got_dt = np.dtype(t.dtype)
        if got_dt != ts.dtype.np_dtype:
            return f"tensor {i} dtype {got_dt.name}, spec {ts.dtype.value}"
    return None


def poison_like(t: Any) -> Any:
    """A same-shape/dtype array of obviously-wrong values (NaN for floats,
    the dtype max for ints): pad rows filled with this make a batch
    split/index bug show up as garbage instead of a plausible replica.
    An exotic dtype the poison recipe can't handle returns `t` itself —
    the padding then stays a replica rather than failing the batch."""
    try:
        dt = np.dtype(t.dtype)
        if np.issubdtype(dt, np.floating) or dt.name == "bfloat16":
            val: Any = np.nan
        elif dt == np.bool_:
            val = True
        else:
            val = np.iinfo(dt).max
        return np.full(tuple(int(d) for d in t.shape), val, dtype=dt)
    except Exception:
        return t


# -- lock-order watching -----------------------------------------------------

class LockOrderGraph:
    """Directed held→acquired edges across all threads; a cycle means two
    code paths take the watched locks in opposite orders."""

    def __init__(self, on_cycle=None) -> None:
        self._edges: Dict[str, set] = {}
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._reported: set = set()
        self._on_cycle = on_cycle

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def acquired(self, name: str) -> None:
        held = self._held()
        for h in held:
            if h != name:
                self._add_edge(h, name)
        held.append(name)

    def released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def _add_edge(self, a: str, b: str) -> None:
        with self._mu:
            peers = self._edges.setdefault(a, set())
            if b in peers:
                return
            peers.add(b)
            cycle = self._find_path(b, a)
        if cycle is not None:
            key = frozenset(cycle)
            if key in self._reported:
                return
            self._reported.add(key)
            chain = " -> ".join(cycle + [cycle[0]])
            if self._on_cycle is not None:
                self._on_cycle(chain)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Path src..dst over the edge set (call with self._mu held)."""
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        seen = set()
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in self._edges.get(cur, ()):
                stack.append((nxt, path + [nxt]))
        return None


class TrackedLock:
    """threading.Lock proxy that feeds a LockOrderGraph. Usable directly
    (`with lock:`) and as the lock behind a threading.Condition."""

    def __init__(self, name: str, graph: LockOrderGraph,
                 lock: Optional[threading.Lock] = None) -> None:
        self.name = name
        self._graph = graph
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._graph.acquired(self.name)
        return got

    def release(self) -> None:
        self._graph.released(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# -- instrumented channel ----------------------------------------------------

_san_chan_cls: Optional[type] = None


def san_chan_cls() -> type:
    """The instrumented _Chan twin (built lazily: executor imports this
    module, so the subclass cannot exist at import time)."""
    global _san_chan_cls
    if _san_chan_cls is not None:
        return _san_chan_cls
    from nnstreamer_tpu.pipeline.executor import _EMPTY, _Chan

    class SanChan(_Chan):
        """_Chan + put/get counters and per-put spec conformance. The
        Dekker pairing and wake discipline are inherited untouched —
        the instrumentation wraps, never reorders."""

        __slots__ = ("san", "node_name", "pad", "expected_spec",
                     "n_put", "n_got")

        def __init__(self, maxsize: int, san: "Sanitizer",
                     node_name: str, pad: int) -> None:
            super().__init__(maxsize)
            self.san = san
            self.node_name = node_name
            self.pad = pad
            self.expected_spec: Optional[TensorsSpec] = None
            self.n_put = 0
            self.n_got = 0

        def put(self, item, stop_event) -> None:
            if item is not EOS_FRAME:
                self.n_put += 1
                spec = self.expected_spec
                if spec is not None:
                    detail = frame_conforms(item, spec)
                    if detail is not None:
                        self.san.spec_violation(
                            self.node_name, self.pad, detail
                        )
            super().put(item, stop_event)

        def get(self, stop_event):
            item = super().get(stop_event)
            if item is not EOS_FRAME:
                self.n_got += 1
            return item

        def get_nowait(self):
            item = super().get_nowait()
            if item is not EOS_FRAME and item is not _EMPTY:
                self.n_got += 1
            return item

        def get_until(self, deadline, stop_event):
            item = super().get_until(deadline, stop_event)
            if item is not None and item is not EOS_FRAME:
                self.n_got += 1
            return item

        def drain(self, limit: int) -> list:
            items = super().drain(limit)
            self.n_got += sum(1 for i in items if i is not EOS_FRAME)
            return items

    _san_chan_cls = SanChan
    return SanChan


# -- the sanitizer -----------------------------------------------------------

class Sanitizer:
    """One per Executor. Collects NNS-S findings (thread-safe), owns the
    lock-order graph, and counts node-level pushes for the EOS frame-
    accounting check."""

    def __init__(self) -> None:
        self.report = LintReport()
        self._mu = threading.Lock()
        self.lock_graph = LockOrderGraph(on_cycle=self._cycle)
        # (node name, out pad) -> frames pushed (producer-thread writes;
        # GIL-atomic int adds under the per-key single-writer contract)
        self._pushes: Dict[Tuple[str, int], int] = {}

    # -- recording ---------------------------------------------------------
    def record(self, code: str, where: Optional[str], message: str,
               hint: str = "") -> None:
        with self._mu:
            self.report.add(code, where, message, hint)
        _log.warning("sanitizer %s [%s]: %s", code, where, message)
        from nnstreamer_tpu import trace

        tracer = trace.get()
        if tracer is not None:
            tracer.san(where or "pipeline", code, message=message)

    @property
    def codes(self) -> List[str]:
        with self._mu:
            return self.report.codes

    def findings(self) -> List[Any]:
        with self._mu:
            return list(self.report.diagnostics)

    # -- spec conformance --------------------------------------------------
    def spec_violation(self, node: str, pad: int, detail: str) -> None:
        self.record(
            "NNS-S001", node, f"sink pad {pad}: {detail}",
            "an element emitted tensors that do not match what it "
            "negotiated",
        )
        raise SpecViolationError(node, pad, detail)

    # -- lock order --------------------------------------------------------
    def lock(self, name: str) -> TrackedLock:
        return TrackedLock(name, self.lock_graph)

    def _cycle(self, chain: str) -> None:
        self.record(
            "NNS-S003", None,
            f"lock acquisition order cycle: {chain}",
            "impose one global order on these locks",
        )

    # -- frame accounting --------------------------------------------------
    def register_pad(self, node_name: str, pad: int) -> None:
        """Pre-create the (node, pad) counter at build time: with every
        key present before streaming, the per-frame count_push fast path
        never resizes the dict (single-writer value updates are
        GIL-atomic and safe against concurrent snapshot reads)."""
        with self._mu:
            self._pushes.setdefault((node_name, pad), 0)

    def count_push(self, node_name: str, pad: int) -> None:
        key = (node_name, pad)
        cur = self._pushes.get(key)
        if cur is None:  # unregistered (hand-built plan): insert locked
            with self._mu:
                self._pushes.setdefault(key, 0)
            cur = self._pushes[key]
        self._pushes[key] = cur + 1

    def pushes(self, node_name: str, pad: int) -> int:
        return self._pushes.get((node_name, pad), 0)

    def node_snapshot(self, node) -> Dict[str, int]:
        offered = sum(
            q.n_got for q in node.in_queues if hasattr(q, "n_got")
        )
        err_pad = self._error_pad(node)
        delivered = routed = 0
        with self._mu:  # excludes key inserts, not value updates
            items = list(self._pushes.items())
        for (name, pad), n in items:
            if name != node.name:
                continue
            if err_pad is not None and pad == err_pad:
                routed += n
            else:
                delivered += n
        return {
            "san_offered": offered,
            "san_delivered": delivered,
            "san_routed": routed,
        }

    @staticmethod
    def _error_pad(node) -> Optional[int]:
        elem = getattr(node, "elem", None)
        if elem is None:
            elem = getattr(getattr(node, "seg", None), "first", None)
        return getattr(elem, "error_pad", None) if elem is not None else None

    def check_accounting(self, node) -> None:
        """Latch offered == delivered + dropped + routed for one node at
        clean EOS (the caller filters to eligible 1:1 nodes)."""
        snap = self.node_snapshot(node)
        # deadline sheds are counted drops: the frame was popped
        # (offered) and disposed of with a reason before processing
        dropped = getattr(node, "deadline_shed", 0)
        fs = getattr(node, "fault_stats", None)
        if fs is not None:
            dropped += fs.dropped
        balance = (
            snap["san_offered"]
            - snap["san_delivered"] - snap["san_routed"] - dropped
        )
        if balance != 0:
            what = "leaked" if balance > 0 else "duplicated"
            self.record(
                "NNS-S002", node.name,
                f"{abs(balance)} frame(s) {what} at EOS: offered="
                f"{snap['san_offered']}, delivered="
                f"{snap['san_delivered']}, dropped={dropped}, "
                f"routed={snap['san_routed']}",
                "the element consumed or emitted frames outside its "
                "declared 1:1 + error-policy accounting",
            )

    # -- thread leaks ------------------------------------------------------
    def thread_leak(self, names: List[str]) -> None:
        self.record(
            "NNS-S004", None,
            f"{len(names)} thread(s) survived executor shutdown: "
            f"{', '.join(sorted(names))}",
            "join service threads in stop() or mark them daemon with a "
            "bounded loop",
        )
