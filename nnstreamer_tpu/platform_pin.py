"""Honor an explicit JAX_PLATFORMS env pin.

A site hook may force-set the hardware platform via ``jax.config``
(which outranks the env var); a user who asked for ``JAX_PLATFORMS=cpu``
must never block on an unavailable accelerator attachment. One shared
implementation for the CLI and every example — call before the first
device operation (jax backend init is lazy, so import order is enough).
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
