"""Honor an explicit JAX_PLATFORMS env pin; avoid blocking on a dead relay.

A site hook may force-set the hardware platform via ``jax.config``
(which outranks the env var); a user who asked for ``JAX_PLATFORMS=cpu``
must never block on an unavailable accelerator attachment. One shared
implementation for the CLI and every example — call before the first
device operation (jax backend init is lazy, so import order is enough).

When a remote-accelerator platform is requested but its relay endpoint
is unreachable, attach would BLOCK INDEFINITELY (the client retries
connect in a sleep loop — the failure mode bench.py gates with
``_tunnel_alive``). In that case fall back to CPU with a warning rather
than hang whatever example or pipeline asked for a device.
"""

from __future__ import annotations

import os
import sys


def probe_relay(hosts=None, timeout: float = 2.0) -> bool:
    """ONE shared TCP probe of the accelerator relay pool (no jax
    import — a dead relay makes jax.devices() block forever in the
    axon client's connect-retry loop). ``hosts`` defaults to
    PALLAS_AXON_POOL_IPS, falling back to the local tunnel address.
    Callers own the policy of what an unreachable relay means."""
    import socket

    if hosts is None:
        ips = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1")
        hosts = [h.strip() for h in ips.split(",") if h.strip()]
    for host in hosts:
        try:
            socket.create_connection((host, 8082), timeout=timeout).close()
            return True
        except OSError:
            pass
    return False


def _relay_reachable() -> bool:
    """True unless a remote-accelerator relay is configured AND down."""
    ips = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    if not ips:
        return True  # topology unknown: don't second-guess
    return probe_relay([h.strip() for h in ips.split(",") if h.strip()])


def honor_jax_platforms_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and "cpu" != plat and not _relay_reachable():
        print(
            "[nnstreamer_tpu] accelerator relay unreachable; running on "
            "CPU instead of blocking on attach",
            file=sys.stderr,
        )
        plat = "cpu"
        os.environ["JAX_PLATFORMS"] = "cpu"
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
