"""Jittable detection post-processing primitives.

TPU-native redesign of the scalar C loops in the reference's bounding-box
decoder (ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c): prior-box
decode (:349-361 scales), score thresholding, and NMS run as vectorized jax
ops so they can be jitted — and fused into the same XLA program as the model
when a Filter and Decoder stage are fused by the pipeline compiler. The
reference iterates detections one-by-one on the CPU; here everything is a
fixed-shape masked tensor program (no data-dependent shapes, so XLA compiles
once and the MXU/VPU stay busy).

Detections are represented as a fixed-size ``(max_out, 6)`` float32 tensor
of ``[x1, y1, x2, y2, class, score]`` rows (normalized [0,1] coords), with
``score == 0`` marking empty slots — the static-shape analogue of the
reference's GArray of detectedObject.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Reference defaults (tensordec-boundingbox.c:343-361, :125-127)
SSD_THRESHOLD = 0.5
SSD_Y_SCALE = 10.0
SSD_X_SCALE = 10.0
SSD_H_SCALE = 5.0
SSD_W_SCALE = 5.0
SSD_IOU_THRESHOLD = 0.5
YOLOV5_CONF_THRESHOLD = 0.3
YOLOV5_IOU_THRESHOLD = 0.6
OV_CONF_THRESHOLD = 0.8


def ssd_decode_boxes(
    locations: jax.Array,
    priors: jax.Array,
    y_scale: float = SSD_Y_SCALE,
    x_scale: float = SSD_X_SCALE,
    h_scale: float = SSD_H_SCALE,
    w_scale: float = SSD_W_SCALE,
) -> jax.Array:
    """Decode SSD location offsets against prior boxes → [N,4] x1,y1,x2,y2.

    locations: [N, 4] (ycenter, xcenter, h, w offsets); priors: [4, N]
    rows (ycenter, xcenter, h, w) as loaded from the reference's
    box-priors.txt (4 lines × N values).
    """
    loc = locations.astype(jnp.float32)
    pr = priors.astype(jnp.float32)
    ycenter = loc[:, 0] / y_scale * pr[2] + pr[0]
    xcenter = loc[:, 1] / x_scale * pr[3] + pr[1]
    h = jnp.exp(loc[:, 2] / h_scale) * pr[2]
    w = jnp.exp(loc[:, 3] / w_scale) * pr[3]
    x1 = xcenter - w / 2.0
    y1 = ycenter - h / 2.0
    return jnp.stack([x1, y1, x1 + w, y1 + h], axis=-1)


def iou_matrix(boxes: jax.Array) -> jax.Array:
    """Pairwise IoU of [N,4] x1,y1,x2,y2 boxes → [N,N]. O(N²) but fully
    vectorized — the TPU-friendly trade against the reference's sequential
    compare loop."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0.0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0.0
    )
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(
    boxes: jax.Array,
    scores: jax.Array,
    iou_threshold: float,
    max_out: int,
    impl: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Greedy class-agnostic NMS with static shapes.

    Returns (keep_idx[max_out] int32, keep_score[max_out]); empty slots have
    score 0 and index -1. Implemented as a lax.fori_loop over ranked
    candidates with a masked IoU matrix — equivalent semantics to the
    reference's sort + suppress loop, but compiled. ``impl="auto"``
    swaps in the Pallas suppression kernel (ops/pallas/nms.py — no N×N
    IoU matrix in HBM) on a real TPU backend; both implementations are
    bit-identical (tests/test_ops_device.py).
    """
    if impl not in ("auto", "jnp", "pallas"):
        raise ValueError(f"nms impl {impl!r} not auto/jnp/pallas")
    from nnstreamer_tpu.ops.dispatch import record as _record_dispatch
    from nnstreamer_tpu.ops.pallas._compat import pallas_ok

    use_pallas = impl == "pallas" or (
        impl == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas:
        # registry dtype gate: an unsupported score dtype degrades to
        # the (bit-identical) jnp path with a logged reason
        use_pallas, _ = pallas_ok("nms", scores.dtype)
    _record_dispatch("nms", "pallas" if use_pallas else "jnp")
    if use_pallas:
        from nnstreamer_tpu.ops.pallas.nms import nms as pallas_nms

        # explicit impl=pallas off-TPU runs the interpreter (parity
        # tests); auto never picks it there
        return pallas_nms(
            boxes, scores, iou_threshold, max_out,
            interpret=jax.default_backend() != "tpu",
        )
    n = boxes.shape[0]
    k = min(max_out, n)
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    sscores = scores[order]
    ious = iou_matrix(sboxes)

    def body(i, alive):
        # i-th candidate survives iff still alive; then kill its overlaps.
        keep_i = alive[i]
        suppress = (ious[i] > iou_threshold) & (jnp.arange(n) > i) & keep_i
        return alive & ~suppress

    alive = jax.lax.fori_loop(0, n, body, sscores > 0)
    kept_scores = jnp.where(alive, sscores, 0.0)
    top = jnp.argsort(-kept_scores)[:k]
    sel_scores = kept_scores[top]
    sel_idx = jnp.where(sel_scores > 0, order[top], -1)
    if k < max_out:
        sel_idx = jnp.pad(sel_idx, (0, max_out - k), constant_values=-1)
        sel_scores = jnp.pad(sel_scores, (0, max_out - k))
    return sel_idx.astype(jnp.int32), sel_scores


def _pack_detections(
    boxes: jax.Array,
    classes: jax.Array,
    keep_idx: jax.Array,
    keep_scores: jax.Array,
) -> jax.Array:
    """Gather kept rows into the fixed [max_out, 6] detections tensor."""
    safe = jnp.maximum(keep_idx, 0)
    sel_boxes = boxes[safe]
    sel_cls = classes[safe].astype(jnp.float32)
    valid = (keep_idx >= 0)[:, None].astype(jnp.float32)
    rows = jnp.concatenate(
        [sel_boxes, sel_cls[:, None], keep_scores[:, None]], axis=-1
    )
    return rows * valid


@functools.partial(
    jax.jit, static_argnames=("threshold", "iou_threshold", "max_out")
)
def ssd_postprocess(
    locations: jax.Array,
    class_scores: jax.Array,
    priors: jax.Array,
    threshold: float = SSD_THRESHOLD,
    iou_threshold: float = SSD_IOU_THRESHOLD,
    max_out: int = 100,
    y_scale: float = SSD_Y_SCALE,
    x_scale: float = SSD_X_SCALE,
    h_scale: float = SSD_H_SCALE,
    w_scale: float = SSD_W_SCALE,
) -> jax.Array:
    """mobilenet-ssd mode: priors + raw logits → [max_out, 6] detections.

    class_scores: [N, num_classes] raw logits; class 0 is background
    (skipped, as in the reference's label loop starting at 1). The
    reference thresholds in logit space (sigmoid_threshold = logit(thr),
    tensordec-boundingbox.c:204,361) — same math, done as one masked
    sigmoid here.
    """
    boxes = ssd_decode_boxes(locations, priors, y_scale, x_scale, h_scale, w_scale)
    probs = jax.nn.sigmoid(class_scores.astype(jnp.float32))
    probs = probs.at[:, 0].set(0.0)  # background
    best = jnp.argmax(probs, axis=-1)
    best_score = jnp.max(probs, axis=-1)
    score = jnp.where(best_score >= threshold, best_score, 0.0)
    keep_idx, keep_scores = nms(boxes, score, iou_threshold, max_out)
    return _pack_detections(boxes, best, keep_idx, keep_scores)


@functools.partial(jax.jit, static_argnames=("threshold", "max_out"))
def ssd_pp_postprocess(
    locations: jax.Array,
    classes: jax.Array,
    scores: jax.Array,
    num: jax.Array,
    threshold: float = 0.5,
    max_out: int = 100,
) -> jax.Array:
    """mobilenet-ssd-postprocess mode: the model already ran NMS; just
    threshold + repack. locations [N,4] = (ymin, xmin, ymax, xmax)
    normalized (TFLite detection postprocess convention)."""
    loc = locations.astype(jnp.float32)
    boxes = jnp.stack([loc[:, 1], loc[:, 0], loc[:, 3], loc[:, 2]], axis=-1)
    n = loc.shape[0]
    valid = jnp.arange(n) < num.astype(jnp.int32).reshape(())
    s = jnp.where(valid & (scores.astype(jnp.float32) >= threshold),
                  scores.astype(jnp.float32), 0.0)
    top = jnp.argsort(-s)[:max_out]
    keep_idx = jnp.where(s[top] > 0, top, -1).astype(jnp.int32)
    return _pack_detections(boxes, classes.astype(jnp.float32), keep_idx, s[top])


@functools.partial(
    jax.jit, static_argnames=("conf_threshold", "iou_threshold", "max_out", "scaled")
)
def yolov5_postprocess(
    pred: jax.Array,
    conf_threshold: float = YOLOV5_CONF_THRESHOLD,
    iou_threshold: float = YOLOV5_IOU_THRESHOLD,
    max_out: int = 100,
    scaled: bool = True,
) -> jax.Array:
    """yolov5 mode: [N, 5+C] (cx,cy,w,h,objectness,C class scores) →
    [max_out, 6]. ``scaled=False`` applies sigmoid (raw head outputs);
    coords are expected normalized to [0,1] (the element divides by input
    size beforehand when the model emits pixels)."""
    p = pred.astype(jnp.float32)
    if not scaled:
        p = jax.nn.sigmoid(p)
    cx, cy, w, h = p[:, 0], p[:, 1], p[:, 2], p[:, 3]
    obj = p[:, 4]
    cls_scores = p[:, 5:] * obj[:, None]
    best = jnp.argmax(cls_scores, axis=-1)
    best_score = jnp.max(cls_scores, axis=-1)
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    score = jnp.where(best_score >= conf_threshold, best_score, 0.0)
    keep_idx, keep_scores = nms(boxes, score, iou_threshold, max_out)
    return _pack_detections(boxes, best, keep_idx, keep_scores)


@functools.partial(jax.jit, static_argnames=("conf_threshold", "max_out"))
def ov_detection_postprocess(
    pred: jax.Array,
    conf_threshold: float = OV_CONF_THRESHOLD,
    max_out: int = 100,
) -> jax.Array:
    """ov-person/face-detection: [N, 7] rows (image_id, label, conf,
    x_min, y_min, x_max, y_max), already normalized — threshold + repack
    (reference tensordec-boundingbox.c:121-124)."""
    p = pred.astype(jnp.float32).reshape(-1, 7)
    boxes = p[:, 3:7]
    score = jnp.where(p[:, 2] >= conf_threshold, p[:, 2], 0.0)
    n = p.shape[0]
    k = min(max_out, n)
    top = jnp.argsort(-score)[:k]
    keep_idx = jnp.where(score[top] > 0, top, -1).astype(jnp.int32)
    det = _pack_detections(boxes, p[:, 1], keep_idx, score[top])
    if k < max_out:
        det = jnp.pad(det, ((0, max_out - k), (0, 0)))
    return det


def generate_mp_palm_anchors(
    num_layers: int = 4,
    min_scale: float = 1.0,
    max_scale: float = 1.0,
    x_offset: float = 0.5,
    y_offset: float = 0.5,
    strides: Sequence[int] = (8, 16, 16, 16),
    input_size: int = 192,
) -> np.ndarray:
    """SSD-style anchor generation for mp-palm-detection (reference
    tensordec-boundingbox.c option3 scheme :68-80; same recipe as
    mediapipe's SsdAnchorsCalculator). Returns [N, 4] (ycenter, xcenter,
    h, w) — host-side, computed once at negotiate time."""
    if len(strides) < num_layers:
        raise ValueError(
            f"mp-palm anchors: {num_layers} layers need {num_layers} strides, "
            f"got {len(strides)}"
        )
    anchors = []
    layer = 0
    while layer < num_layers:
        # merge consecutive layers with identical strides
        scales = []
        last = layer
        while last < num_layers and strides[last] == strides[layer]:
            if num_layers == 1:
                scale = (min_scale + max_scale) * 0.5
            else:
                scale = min_scale + (max_scale - min_scale) * last / (num_layers - 1.0)
            scales.extend([scale, scale])  # 2 anchors per cell
            last += 1
        stride = strides[layer]
        fm = int(np.ceil(input_size / stride))
        for y in range(fm):
            for x in range(fm):
                for _ in scales:
                    anchors.append(
                        ((y + y_offset) / fm, (x + x_offset) / fm, 1.0, 1.0)
                    )
        layer = last
    return np.asarray(anchors, np.float32)


@functools.partial(
    jax.jit, static_argnames=("score_threshold", "iou_threshold", "max_out", "input_size")
)
def mp_palm_postprocess(
    raw_boxes: jax.Array,
    raw_scores: jax.Array,
    anchors: jax.Array,
    score_threshold: float = 0.5,
    iou_threshold: float = 0.3,
    max_out: int = 20,
    input_size: int = 192,
) -> jax.Array:
    """mp-palm-detection: raw_boxes [N, 18] (dx,dy,w,h + 7 keypoint pairs,
    pixel units), raw_scores [N] logits, anchors [N,4] → [max_out, 6]."""
    b = raw_boxes.astype(jnp.float32)
    a = anchors.astype(jnp.float32)
    cx = b[:, 0] / input_size + a[:, 1]
    cy = b[:, 1] / input_size + a[:, 0]
    w = b[:, 2] / input_size
    h = b[:, 3] / input_size
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    probs = jax.nn.sigmoid(raw_scores.astype(jnp.float32).reshape(-1))
    score = jnp.where(probs >= score_threshold, probs, 0.0)
    keep_idx, keep_scores = nms(boxes, score, iou_threshold, max_out)
    return _pack_detections(boxes, jnp.zeros_like(score), keep_idx, keep_scores)
