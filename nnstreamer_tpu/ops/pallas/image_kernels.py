"""Tiled bilinear crop/resize/normalize as a Pallas TPU kernel.

The pre-processing half of the "beyond matmul" direction (PAPERS.md:
Pushing Tensor Accelerators Beyond MatMul; GPTPU): bilinear resampling is
two small matrix contractions — ``out = Wy · img · Wxᵀ`` per channel,
where ``Wy [out_h, H]`` / ``Wx [out_w, W]`` are interpolation-weight
matrices with two non-zeros per row — so the crop runs on the MXU instead
of the gather/scatter path XLA lowers ``image[y0i][:, x0i]`` to. The grid
walks the N crop boxes; each step builds its weight matrices from the
box's corners (SMEM scalars) with ``broadcasted_iota`` and streams the
whole source image from VMEM through two ``dot_general`` calls, with an
optional fused ``*scale + offset`` normalization epilogue so a
uint8→float input transform costs zero extra HBM round trips.

Numerics match :func:`nnstreamer_tpu.ops.image.crop_and_resize` (the jnp
reference): sample centers at ``box_lo + extent·(i+0.5)/out - 0.5``,
edge clamping via clipping the sample coordinate — a clipped coordinate
puts weight 1 on the edge row, exactly what the reference's index
clamping computes. Parity is pinned by tests/test_ops_device.py in
interpret mode (the CPU fallback, ops/pallas/_compat.py discipline).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params


def _weight_matrix(lo, hi, out_n: int, in_n: int):
    """[out_n, in_n] bilinear interpolation weights for sampling the
    interval [lo, hi) (pixel coords) at out_n output-pixel centers.
    Built fully 2-D (TPU iota constraint)."""
    o = jax.lax.broadcasted_iota(jnp.float32, (out_n, in_n), 0)
    i = jax.lax.broadcasted_iota(jnp.float32, (out_n, in_n), 1)
    ys = lo + (hi - lo) * (o + 0.5) / float(out_n) - 0.5
    ys = jnp.clip(ys, 0.0, float(in_n - 1))
    return jnp.maximum(0.0, 1.0 - jnp.abs(ys - i))


def _crop_kernel(
    boxes_ref, img_ref, out_ref, *,
    h: int, w: int, c: int, out_h: int, out_w: int,
    scale: Optional[float], offset: Optional[float],
):
    x1 = boxes_ref[0, 0]
    y1 = boxes_ref[0, 1]
    x2 = boxes_ref[0, 2]
    y2 = boxes_ref[0, 3]
    wy = _weight_matrix(y1, y2, out_h, h)          # [out_h, h]
    wx = _weight_matrix(x1, x2, out_w, w)          # [out_w, w]
    # the image block is [h, w, c] (crop grid: whole image every step)
    # or [1, h, w, c] (resize grid: one batch element per step); the
    # reshape collapses either into the [h, w·c] contraction operand
    img = img_ref[:].astype(jnp.float32).reshape(h, w * c)
    # y-interpolation: one MXU contraction over the source rows
    tmp = jax.lax.dot_general(
        wy, img, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(out_h, w, c)
    # x-interpolation: contract the W axis → [out_h, c, out_w]
    out = jax.lax.dot_general(
        tmp, wx, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).transpose(0, 2, 1)
    if scale is not None:
        out = out * scale
    if offset is not None:
        out = out + offset
    if jnp.issubdtype(out_ref.dtype, jnp.integer):
        info = jnp.iinfo(out_ref.dtype)
        out = jnp.clip(jnp.round(out), info.min, info.max)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "out_h", "out_w", "scale", "offset", "out_dtype", "interpret"
    ),
)
def crop_and_resize(
    image,
    boxes,
    out_h: int,
    out_w: int,
    scale: Optional[float] = None,
    offset: Optional[float] = None,
    out_dtype=None,
    interpret: bool = False,
):
    """Pallas crop+resize: image [H, W, C], boxes [N, 4] pixel
    (x1, y1, x2, y2) → [N, out_h, out_w, C].

    ``scale``/``offset`` fuse a normalization epilogue (out·scale +
    offset) into the kernel — the uint8→float preprocessing transform at
    zero extra memory traffic. ``out_dtype`` defaults to the image dtype
    (float outputs when a normalize epilogue is active); integer outputs
    round-and-clip like the device-crop element."""
    h, w, c = image.shape
    if out_dtype is None:
        out_dtype = (
            jnp.float32 if (scale is not None or offset is not None)
            else image.dtype
        )
    return _launch_crop(
        image, boxes.astype(jnp.float32),
        # crop grid: every step reads the whole (shared) image
        pl.BlockSpec((h, w, c), lambda i: (0, 0, 0)),
        out_h, out_w, scale, offset, out_dtype, interpret,
    )


def _launch_crop(
    img, boxes, img_spec, out_h, out_w, scale, offset, out_dtype,
    interpret,
):
    """One home for the crop-kernel launch (grid over boxes, per-box
    SMEM-scalar spec, interpret-vs-Mosaic compiler params): the crop
    and resize entry points differ only in how the image block is
    indexed per grid step."""
    n = boxes.shape[0]
    h, w, c = img.shape[-3:]
    kernel = functools.partial(
        _crop_kernel,
        h=h, w=w, c=c, out_h=out_h, out_w=out_w,
        scale=scale, offset=offset,
    )
    if interpret:
        kw = {}
    else:  # pragma: no cover - real-TPU path (CPU tests interpret)
        from jax.experimental.pallas import tpu as pltpu

        kw = {
            "compiler_params": _compiler_params(
                pltpu, dimension_semantics=("parallel",)
            ),
        }
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, out_h, out_w, c), out_dtype),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, 4), lambda i: (i, 0)), img_spec],
        out_specs=pl.BlockSpec(
            (1, out_h, out_w, c), lambda i: (i, 0, 0, 0)
        ),
        interpret=interpret,
        **kw,
    )(boxes, img)


@functools.partial(
    jax.jit,
    static_argnames=("out_h", "out_w", "scale", "offset", "interpret"),
)
def resize_bilinear(
    image,
    out_h: int,
    out_w: int,
    scale: Optional[float] = None,
    offset: Optional[float] = None,
    interpret: bool = False,
):
    """Whole-image bilinear resize (+ optional normalize epilogue):
    [N, H, W, C] or [H, W, C] → same rank with H, W replaced. A resize
    IS a crop of the full image; the batch rides the grid axis (one
    full-image box per batch element, image block indexed per step)."""
    squeeze = image.ndim == 3
    img = image[None] if squeeze else image
    n, h, w, c = img.shape
    out_dtype = (
        jnp.float32 if (scale is not None or offset is not None)
        else img.dtype
    )
    boxes = jnp.broadcast_to(
        jnp.asarray([[0.0, 0.0, float(w), float(h)]], jnp.float32), (n, 4)
    )
    out = _launch_crop(
        img, boxes,
        # resize grid: one batch element per step
        pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
        out_h, out_w, scale, offset, out_dtype, interpret,
    )
    return out[0] if squeeze else out
