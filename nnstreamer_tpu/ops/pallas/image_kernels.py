"""Tiled bilinear crop/resize/normalize as a Pallas TPU kernel.

The pre-processing half of the "beyond matmul" direction (PAPERS.md:
Pushing Tensor Accelerators Beyond MatMul; GPTPU): bilinear resampling is
two small matrix contractions — ``out = Wy · img · Wxᵀ`` per channel,
where ``Wy [out_h, H]`` / ``Wx [out_w, W]`` are interpolation-weight
matrices with two non-zeros per row — so the crop runs on the MXU instead
of the gather/scatter path XLA lowers ``image[y0i][:, x0i]`` to. The grid
walks the N crop boxes; each step builds its weight matrices from the
box's corners (SMEM scalars) with ``broadcasted_iota`` and streams the
whole source image from VMEM through two ``dot_general`` calls, with an
optional fused ``*scale + offset`` normalization epilogue so a
uint8→float input transform costs zero extra HBM round trips.

Numerics match :func:`nnstreamer_tpu.ops.image.crop_and_resize` (the jnp
reference): sample centers at ``box_lo + extent·(i+0.5)/out - 0.5``,
edge clamping via clipping the sample coordinate — a clipped coordinate
puts weight 1 on the edge row, exactly what the reference's index
clamping computes. Parity is pinned by tests/test_ops_device.py in
interpret mode (the CPU fallback, ops/pallas/_compat.py discipline).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas import registry as _registry
from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params


# BlockSpec index maps — module-level so the registered LaunchPlans and
# the live pallas_call share the SAME callables (grid: one crop box —
# or one batch element, for resize — per step)
def _boxes_index_map(i):
    return (i, 0)


def _crop_img_index_map(i):
    # crop grid: every step reads the whole (shared) image
    return (0, 0, 0)


def _resize_img_index_map(i):
    # resize grid: one batch element per step
    return (i, 0, 0, 0)


def _out_index_map(i):
    return (i, 0, 0, 0)


def _weight_matrix(lo, hi, out_n: int, in_n: int):
    """[out_n, in_n] bilinear interpolation weights for sampling the
    interval [lo, hi) (pixel coords) at out_n output-pixel centers.
    Built fully 2-D (TPU iota constraint)."""
    o = jax.lax.broadcasted_iota(jnp.float32, (out_n, in_n), 0)
    i = jax.lax.broadcasted_iota(jnp.float32, (out_n, in_n), 1)
    ys = lo + (hi - lo) * (o + 0.5) / float(out_n) - 0.5
    ys = jnp.clip(ys, 0.0, float(in_n - 1))
    return jnp.maximum(0.0, 1.0 - jnp.abs(ys - i))


def _crop_kernel(
    boxes_ref, img_ref, out_ref, *,
    h: int, w: int, c: int, out_h: int, out_w: int,
    scale: Optional[float], offset: Optional[float],
):
    x1 = boxes_ref[0, 0]
    y1 = boxes_ref[0, 1]
    x2 = boxes_ref[0, 2]
    y2 = boxes_ref[0, 3]
    wy = _weight_matrix(y1, y2, out_h, h)          # [out_h, h]
    wx = _weight_matrix(x1, x2, out_w, w)          # [out_w, w]
    # the image block is [h, w, c] (crop grid: whole image every step)
    # or [1, h, w, c] (resize grid: one batch element per step); the
    # reshape collapses either into the [h, w·c] contraction operand
    img = img_ref[:].astype(jnp.float32).reshape(h, w * c)
    # y-interpolation: one MXU contraction over the source rows
    tmp = jax.lax.dot_general(
        wy, img, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(out_h, w, c)
    # x-interpolation: contract the W axis → [out_h, c, out_w]
    out = jax.lax.dot_general(
        tmp, wx, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).transpose(0, 2, 1)
    if scale is not None:
        out = out * scale
    if offset is not None:
        out = out + offset
    if jnp.issubdtype(out_ref.dtype, jnp.integer):
        info = jnp.iinfo(out_ref.dtype)
        out = jnp.clip(jnp.round(out), info.min, info.max)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "out_h", "out_w", "scale", "offset", "out_dtype", "interpret"
    ),
)
def crop_and_resize(
    image,
    boxes,
    out_h: int,
    out_w: int,
    scale: Optional[float] = None,
    offset: Optional[float] = None,
    out_dtype=None,
    interpret: bool = False,
):
    """Pallas crop+resize: image [H, W, C], boxes [N, 4] pixel
    (x1, y1, x2, y2) → [N, out_h, out_w, C].

    ``scale``/``offset`` fuse a normalization epilogue (out·scale +
    offset) into the kernel — the uint8→float preprocessing transform at
    zero extra memory traffic. ``out_dtype`` defaults to the image dtype
    (float outputs when a normalize epilogue is active); integer outputs
    round-and-clip like the device-crop element."""
    h, w, c = image.shape
    if out_dtype is None:
        out_dtype = (
            jnp.float32 if (scale is not None or offset is not None)
            else image.dtype
        )
    return _launch_crop(
        image, boxes.astype(jnp.float32),
        pl.BlockSpec((h, w, c), _crop_img_index_map),
        out_h, out_w, scale, offset, out_dtype, interpret,
    )


def _launch_crop(
    img, boxes, img_spec, out_h, out_w, scale, offset, out_dtype,
    interpret,
):
    """One home for the crop-kernel launch (grid over boxes, per-box
    SMEM-scalar spec, interpret-vs-Mosaic compiler params): the crop
    and resize entry points differ only in how the image block is
    indexed per grid step."""
    n = boxes.shape[0]
    h, w, c = img.shape[-3:]
    kernel = functools.partial(
        _crop_kernel,
        h=h, w=w, c=c, out_h=out_h, out_w=out_w,
        scale=scale, offset=offset,
    )
    if interpret:
        kw = {}
    else:  # pragma: no cover - real-TPU path (CPU tests interpret)
        from jax.experimental.pallas import tpu as pltpu

        kw = {
            "compiler_params": _compiler_params(
                pltpu, dimension_semantics=("parallel",)
            ),
        }
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, out_h, out_w, c), out_dtype),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, 4), _boxes_index_map), img_spec],
        out_specs=pl.BlockSpec(
            (1, out_h, out_w, c), _out_index_map
        ),
        interpret=interpret,
        **kw,
    )(boxes, img)


@functools.partial(
    jax.jit,
    static_argnames=("out_h", "out_w", "scale", "offset", "interpret"),
)
def resize_bilinear(
    image,
    out_h: int,
    out_w: int,
    scale: Optional[float] = None,
    offset: Optional[float] = None,
    interpret: bool = False,
):
    """Whole-image bilinear resize (+ optional normalize epilogue):
    [N, H, W, C] or [H, W, C] → same rank with H, W replaced. A resize
    IS a crop of the full image; the batch rides the grid axis (one
    full-image box per batch element, image block indexed per step)."""
    squeeze = image.ndim == 3
    img = image[None] if squeeze else image
    n, h, w, c = img.shape
    out_dtype = (
        jnp.float32 if (scale is not None or offset is not None)
        else img.dtype
    )
    boxes = jnp.broadcast_to(
        jnp.asarray([[0.0, 0.0, float(w), float(h)]], jnp.float32), (n, 4)
    )
    out = _launch_crop(
        img, boxes,
        pl.BlockSpec((1, h, w, c), _resize_img_index_map),
        out_h, out_w, scale, offset, out_dtype, interpret,
    )
    return out[0] if squeeze else out


# -- kernel registration (nns-kscope) ----------------------------------------


def _crop_flops(n, h, w, c, out_h, out_w):
    # two MXU contractions per box: Wy·img ([out_h,h]·[h,w·c]) then
    # ·Wxᵀ (contract the w axis), 2·m·n·k flops each
    return n * 2 * out_h * w * c * (h + out_w)


def _crop_plan(params):
    n = params.get("n", 4)
    h, w, c = params.get("h", 32), params.get("w", 48), params.get("c", 3)
    out_h, out_w = params.get("out_h", 8), params.get("out_w", 8)
    dtype = params.get("dtype", "float32")
    return _registry.LaunchPlan(
        grid=(n,),
        blocks=(
            _registry.BlockDesc(
                "boxes", "in", (n, 4), (1, 4), "float32", _boxes_index_map,
            ),
            _registry.BlockDesc(
                "image", "in", (h, w, c), (h, w, c), dtype,
                _crop_img_index_map,
            ),
            _registry.BlockDesc(
                "out", "out", (n, out_h, out_w, c), (1, out_h, out_w, c),
                dtype, _out_index_map,
            ),
        ),
        flops=_crop_flops(n, h, w, c, out_h, out_w),
        notes="whole image resident across the box grid (constant index map)",
    )


def _resize_plan(params):
    n = params.get("n", 2)
    h, w, c = params.get("h", 17), params.get("w", 23), params.get("c", 3)
    out_h, out_w = params.get("out_h", 8), params.get("out_w", 8)
    dtype = params.get("dtype", "float32")
    return _registry.LaunchPlan(
        grid=(n,),
        blocks=(
            _registry.BlockDesc(
                "boxes", "in", (n, 4), (1, 4), "float32", _boxes_index_map,
            ),
            _registry.BlockDesc(
                "image", "in", (n, h, w, c), (1, h, w, c), dtype,
                _resize_img_index_map,
            ),
            _registry.BlockDesc(
                "out", "out", (n, out_h, out_w, c), (1, out_h, out_w, c),
                dtype, _out_index_map,
            ),
        ),
        flops=_crop_flops(n, h, w, c, out_h, out_w),
    )


def _interp_atol(dtype, h, w):
    """Parity tolerance for bilinear sampling: the kernel and the jnp
    reference round the float32 source coordinates differently, and at
    magnitude max(h, w) one coordinate ulp (≈ max(h,w)·2⁻²³) moves an
    O(1) interpolation weight by that much — 720p-scale cases need a
    looser bar than thumbnails, not a sloppier kernel."""
    if jnp.issubdtype(dtype, jnp.integer):
        return 1.0
    return max(1e-4, 8 * max(h, w) * 2.0 ** -23)


def _rand_boxes(rng, n, h, w):
    import numpy as np

    x1 = rng.uniform(0, w - 1, n)
    y1 = rng.uniform(0, h - 1, n)
    x2 = x1 + rng.uniform(1.0, np.maximum(1.5, w - x1))
    y2 = y1 + rng.uniform(1.0, np.maximum(1.5, h - y1))
    return jnp.asarray(np.stack([x1, y1, x2, y2], -1), jnp.float32)


def _crop_run_case(params):
    import numpy as np

    from nnstreamer_tpu.ops import image as image_ops

    rng = np.random.default_rng(5)
    n = params.get("n", 4)
    h, w, c = params.get("h", 32), params.get("w", 48), params.get("c", 3)
    out_h, out_w = params.get("out_h", 8), params.get("out_w", 8)
    dtype = jnp.dtype(params.get("dtype", "float32"))
    scale, offset = params.get("scale"), params.get("offset")
    if jnp.issubdtype(dtype, jnp.integer):
        img = jnp.asarray(rng.integers(0, 256, (h, w, c)), dtype)
    else:
        img = jnp.asarray(rng.standard_normal((h, w, c)), dtype)
    boxes = _rand_boxes(rng, n, h, w)
    got = crop_and_resize(
        img, boxes, out_h, out_w, scale=scale, offset=offset, interpret=True,
    )
    want = image_ops.crop_and_resize(
        img.astype(jnp.float32), boxes, out_h, out_w, impl="jnp"
    )
    if scale is not None:
        want = want * scale
    if offset is not None:
        want = want + offset
    if scale is None and offset is None:
        want = image_ops._round_clip_cast(want, dtype)
    return got, want, _interp_atol(dtype, h, w)


def _resize_run_case(params):
    import numpy as np

    from nnstreamer_tpu.ops import image as image_ops

    rng = np.random.default_rng(6)
    n = params.get("n", 2)
    h, w, c = params.get("h", 17), params.get("w", 23), params.get("c", 3)
    out_h, out_w = params.get("out_h", 8), params.get("out_w", 8)
    dtype = jnp.dtype(params.get("dtype", "float32"))
    if jnp.issubdtype(dtype, jnp.integer):
        img = jnp.asarray(rng.integers(0, 256, (n, h, w, c)), dtype)
    else:
        img = jnp.asarray(rng.standard_normal((n, h, w, c)), dtype)
    got = resize_bilinear(img, out_h, out_w, interpret=True)
    want = image_ops.resize_bilinear(img, out_h, out_w, impl="jnp")
    return got, want, _interp_atol(dtype, h, w)


def _crop_probe():
    import numpy as np

    from nnstreamer_tpu.ops import image as image_ops

    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.standard_normal((8, 8, 3)), jnp.float32)
    boxes = jnp.asarray([[1.0, 1.0, 6.0, 6.0]], jnp.float32)
    np.asarray(image_ops.crop_and_resize(img, boxes, 4, 4, impl="pallas"))


def _resize_probe():
    import numpy as np

    from nnstreamer_tpu.ops import image as image_ops

    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.standard_normal((8, 8, 3)), jnp.float32)
    np.asarray(image_ops.resize_bilinear(img, 4, 4, impl="pallas"))


_registry.register(_registry.KernelSpec(
    name="crop_and_resize",
    module=__name__,
    ops=("crop_and_resize",),
    dtypes=("float32", "bfloat16", "uint8"),
    cases=(
        _registry.ShapeCase("f32", {}, tier1=True),
        _registry.ShapeCase("uint8", {"dtype": "uint8"}, tier1=True),
        _registry.ShapeCase(
            "normalize-epilogue",
            {"scale": 1.0 / 255.0, "offset": -0.5},
            tier1=True,
        ),
        _registry.ShapeCase(
            "cam-720p-face",
            {"n": 8, "h": 720, "w": 1280, "out_h": 112, "out_w": 112},
        ),
    ),
    plan=_crop_plan,
    run_case=_crop_run_case,
    probe=_crop_probe,
))

_registry.register(_registry.KernelSpec(
    name="resize_bilinear",
    module=__name__,
    ops=("resize_bilinear",),
    dtypes=("float32", "bfloat16", "uint8"),
    cases=(
        _registry.ShapeCase("down", {}, tier1=True),
        _registry.ShapeCase(
            "up",
            {"n": 1, "h": 8, "w": 8, "out_h": 16, "out_w": 16},
            tier1=True,
        ),
        _registry.ShapeCase("uint8", {"dtype": "uint8"}),
        _registry.ShapeCase(
            "cam-720p-to-300",
            {"n": 1, "h": 720, "w": 1280, "out_h": 300, "out_w": 300},
        ),
    ),
    plan=_resize_plan,
    run_case=_resize_run_case,
    probe=_resize_probe,
))
