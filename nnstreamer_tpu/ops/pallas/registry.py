"""Self-describing Pallas kernel registry (nns-kscope substrate).

Tensor Processing Primitives (PAPERS.md) argues accelerator kernels
should be compositions of a *described* primitive set — a description
an analyzer can consume. This module is that description for the
in-tree kernels: each kernel module registers a :class:`KernelSpec`
carrying its grid function, BlockSpec geometry (as plain-python
:class:`BlockDesc` rows sharing the REAL index-map callables the
``pl.pallas_call`` uses), scratch shapes, scalar-prefetch operands,
dtype support, jnp reference, and a representative shape grid.

Consumers:

- ``analysis/kernels.py`` (nns-kscope) derives per-grid-step VMEM
  residency, lane/sublane tile alignment, index-map hazards and a
  roofline cost row per registered kernel x shape — statically, no
  device, nothing allocated.
- ``ops/pallas/_compat.pallas_ok`` consults per-kernel dtype support so
  an unsupported-dtype ``impl="pallas"`` request degrades to the jnp
  path with a logged reason instead of a trace-time Mosaic error.
- ``nns-kscope --self-check`` runs every kernel against its jnp
  reference over the case grid in interpret mode (the differential
  sweep tests/test_pallas.py parametrizes from).
- ``nns-kscope --engage`` / ``bench.py --capture-tpu`` run each
  kernel's tiny probe and diff the dispatch tally (ops/dispatch.py) to
  prove the requested pallas path engaged.

Everything here is abstract: no jax import, no shapes allocated. The
kernel modules self-register at import; ``ensure_registered()`` pulls
them in for consumers that start from the registry side.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

# -- geometry descriptors ----------------------------------------------------


@dataclass(frozen=True)
class BlockDesc:
    """One pallas_call operand/result block: the BlockSpec geometry as
    data. ``index_map`` is the SAME callable the kernel's BlockSpec
    uses (grid indices first, then any scalar-prefetch arrays), so the
    analyzer enumerates exactly what the DMA engine would fetch."""

    name: str
    kind: str                       # "in" | "out"
    array_shape: Tuple[int, ...]    # full operand shape
    block_shape: Tuple[int, ...]    # BlockSpec block_shape
    dtype: str                      # numpy dtype name ("float32", ...)
    index_map: Callable[..., Tuple[int, ...]]


@dataclass(frozen=True)
class ScratchDesc:
    """One VMEM scratch allocation (``pltpu.VMEM(shape, dtype)``)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str = "float32"


@dataclass(frozen=True)
class PrefetchDesc:
    """One scalar-prefetch operand (SMEM): declared shape plus a
    ``make()`` producing representative values for index-map
    enumeration (e.g. a valid block table)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str = "int32"
    make: Optional[Callable[[], Any]] = None


@dataclass(frozen=True)
class LaunchPlan:
    """The abstract launch a kernel would issue for one shape case:
    what ``pl.pallas_call`` gets, minus the device."""

    grid: Tuple[int, ...]
    blocks: Tuple[BlockDesc, ...]
    scratch: Tuple[ScratchDesc, ...] = ()
    prefetch: Tuple[PrefetchDesc, ...] = ()
    flops: int = 0
    notes: str = ""


@dataclass(frozen=True)
class ShapeCase:
    """One representative shape: ``params`` feeds ``KernelSpec.plan``
    and ``run_case``. ``tier1`` cases ride the fast differential sweep
    (and the tier-1 parity tests); the full grid is the `slow` sweep."""

    name: str
    params: Dict[str, Any] = field(default_factory=dict)
    tier1: bool = False


@dataclass(frozen=True)
class KernelSpec:
    """One registered kernel. ``ops`` are the dispatch-tally op names
    (ops/dispatch.py) this kernel engages through — the first is the
    primary the ``--engage`` probe diffs. ``plan(params)`` derives the
    abstract launch; ``run_case(params)`` returns
    ``(pallas_out, reference_out, atol)`` in interpret mode;
    ``probe()`` is a tiny invocation through the public dispatching op
    with pallas explicitly requested."""

    name: str
    module: str
    ops: Tuple[str, ...]
    dtypes: Tuple[str, ...]
    cases: Tuple[ShapeCase, ...]
    plan: Callable[[Dict[str, Any]], LaunchPlan]
    run_case: Callable[[Dict[str, Any]], Tuple[Any, Any, float]]
    probe: Callable[[], None]

    @property
    def dispatch_op(self) -> str:
        return self.ops[0]

    def tier1_cases(self) -> Tuple[ShapeCase, ...]:
        return tuple(c for c in self.cases if c.tier1)


# -- the registry ------------------------------------------------------------

_REGISTRY: Dict[str, KernelSpec] = {}


def register(spec: KernelSpec) -> KernelSpec:
    """Idempotent by name (modules may be re-imported under test)."""
    _REGISTRY[spec.name] = spec
    return spec


def ensure_registered() -> None:
    """Import the kernel package so every in-tree kernel module has
    self-registered (consumers that start from the registry side)."""
    import nnstreamer_tpu.ops.pallas  # noqa: F401  (import side effect)


def names() -> Tuple[str, ...]:
    ensure_registered()
    return tuple(sorted(_REGISTRY))


def all_specs() -> Tuple[KernelSpec, ...]:
    ensure_registered()
    return tuple(_REGISTRY[n] for n in sorted(_REGISTRY))


def get(name: str) -> KernelSpec:
    ensure_registered()
    return _REGISTRY[name]


def find(name: str) -> Optional[KernelSpec]:
    ensure_registered()
    return _REGISTRY.get(name)


def supports_dtype(kernel: str, dtype: Any) -> bool:
    """Does the registered kernel support this input dtype? Unknown
    kernels have no opinion (True) — the registry must never veto a
    kernel it has not described."""
    spec = find(kernel)
    if spec is None:
        return True
    import numpy as np

    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = str(dtype)
    return name in spec.dtypes
