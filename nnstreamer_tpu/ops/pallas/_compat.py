"""jax-version compatibility shims and dispatch gating for the Pallas
kernels.

One home (the parallel layer's analogue is ``parallel/mesh.py
shard_map``): the next upstream rename gets fixed once, not once per
kernel module — and every dual-path dispatch site asks the same
:func:`pallas_ok` question before committing to a kernel.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple

_log = logging.getLogger("nnstreamer_tpu.ops.pallas")

#: env escape hatch: force every dual-path op onto its jnp/XLA fallback
#: (read directly, not through conf() — it must work before any config
#: is loaded, e.g. for an --engage fallback drill)
DISABLE_ENV = "NNS_TPU_PALLAS_DISABLE"


def pallas_ok(kernel: str, dtype: Optional[Any] = None) -> Tuple[bool, str]:
    """May ``kernel`` take the Pallas path for ``dtype`` inputs?

    Returns ``(ok, reason)``; a False verdict is logged once per call
    site decision so a degraded pipeline says WHY it fell back instead
    of silently running jnp (or worse, raising a trace-time Mosaic
    error on an unsupported dtype — the registry's per-kernel dtype
    list is the support contract, satellite fix of PR 19).
    """
    if os.environ.get(DISABLE_ENV, "").strip() not in ("", "0"):
        reason = f"{DISABLE_ENV} set: pallas disabled process-wide"
        _log.warning("%s: %s — using jnp fallback", kernel, reason)
        return False, reason
    if dtype is not None:
        from nnstreamer_tpu.ops.pallas import registry

        if not registry.supports_dtype(kernel, dtype):
            spec = registry.find(kernel)
            supported = ", ".join(spec.dtypes) if spec else "?"
            reason = (
                f"dtype {str(dtype)} outside registered support"
                f" ({supported})"
            )
            _log.warning("%s: %s — using jnp fallback", kernel, reason)
            return False, reason
    return True, ""


def compiler_params(pltpu, **kw):
    """Version-portable TPU compiler params: newer jax renames
    ``TPUCompilerParams`` -> ``CompilerParams`` (the fields used by the
    in-tree kernels exist in both spellings). ``pltpu`` is passed in
    because the kernels import it lazily (CPU runs interpret)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pragma: no cover - depends on the installed jax
        cls = pltpu.TPUCompilerParams
    return cls(**kw)
