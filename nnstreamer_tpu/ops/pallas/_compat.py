"""jax-version compatibility shims for the Pallas kernels.

One home (the parallel layer's analogue is ``parallel/mesh.py
shard_map``): the next upstream rename gets fixed once, not once per
kernel module.
"""

from __future__ import annotations


def compiler_params(pltpu, **kw):
    """Version-portable TPU compiler params: newer jax renames
    ``TPUCompilerParams`` -> ``CompilerParams`` (the fields used by the
    in-tree kernels exist in both spellings). ``pltpu`` is passed in
    because the kernels import it lazily (CPU runs interpret)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pragma: no cover - depends on the installed jax
        cls = pltpu.TPUCompilerParams
    return cls(**kw)
