"""Single-token decode attention as a Pallas TPU kernel.

The serving hot loop (models/serving.py batched_decode_step) attends one
query token per slot against that slot's KV cache. Decode attention is
memory-bound: the FLOPs are trivial, the cost is streaming the cache out
of HBM. An unfused formulation reads K for the scores and V for the
weighted sum as two separate passes with a [B,H,1,S] score tensor in
between; this kernel is the flash-style single pass — each cache block is
read once, scores never leave VMEM, and the per-slot fill level arrives
as a scalar-prefetch operand, so masking costs no extra HBM tensor.

The kernel indexes the serving cache layout [B, S, H, D] directly via
BlockSpecs (grid (B, H, k-blocks), block (1, bk, 1, d)) — no transpose,
no pad, no bias materialization on the host side; ``pos`` [B] rides in
SMEM. k innermost with "arbitrary" semantics (sequential on TPU), the
online-softmax scratch (m, l, acc) carried across k iterations — the same
recurrence as ops/pallas/flash_attention.py specialized to one query row.
Blocks entirely beyond a slot's fill level are predicated off with
@pl.when.

Int8 caches: pass ``k_scale``/``v_scale`` [B, S, KV] (per-token-per-head
symmetric scales, models/serving.quantize_kv layout) and int8 cache
arrays — the kernel dequantizes per block in VMEM, so HBM traffic stays
at the int8 byte count (the whole point of quantizing the cache: 4× less
cache streaming per decode step than f32).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params

NEG_INF = -1e30


def _kernel(pos_ref, q_ref, k_ref, v_ref, *rest,
            scale: float, block_k: int, n_k: int, s_len: int,
            quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_start = ki * block_k
    # positions 0..pos inclusive are attendable; a windowed ring passes
    # ABSOLUTE pos, so after a wrap pos+1 exceeds the cache length and
    # every row is live — clamp to the static cache length so the tail
    # block's pad columns (cols in [s_len, n_k*block_k)) stay masked
    # instead of streaming pad garbage into the softmax.
    live_len = jnp.minimum(pos_ref[b] + 1, s_len)

    @pl.when(k_start < live_len)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)       # [1, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            # per-row dequant in VMEM: int8 payload × f32 scale [bk]
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [1, bk]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < live_len, s, NEG_INF)
        # dead rows get softmax weight exp(NEG_INF - m) = 0, but a tail
        # block past the cache length reads pad garbage for v, and
        # 0 * NaN = NaN — zero those rows so the weighted sum stays clean
        v = jnp.where(cols.reshape(-1, 1) < live_len, v, 0.0)

        m_prev = m_ref[:]                          # [1]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
        p = jnp.where(
            m_new[:, None] <= NEG_INF, 0.0, jnp.exp(s - m_new[:, None])
        )
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _final():
        l2 = l_ref[:][:, None]
        o_ref[0, 0] = jnp.where(
            l2 > 0, acc_ref[:] / jnp.maximum(l2, 1e-30), 0.0
        ).astype(o_ref.dtype)


def _pick_block(s_len: int, block_k: int) -> Tuple[int, int]:
    """(block size, grid length) covering s_len with ceil-division.

    Blocks need not divide the cache length: Pallas pads the tail block,
    and the kernel's ``cols < live_len`` mask (live_len ≤ s_len) already
    neutralizes the pad columns — so a prime or odd cache length keeps
    full-width blocks instead of degenerating to 1-row blocks."""
    bk = min(block_k, s_len)
    return bk, -(-s_len // bk)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def decode_attention(
    q,
    cache_k,
    cache_v,
    pos,
    k_scale=None,
    v_scale=None,
    scale: Optional[float] = None,
    block_k: int = 128,
    interpret: bool = False,
):
    """q [B,1,H,D], cache_k/v [B,S,KV,D] (the serving layout, consumed
    in place; KV ≤ H under grouped-query attention — query head hi reads
    kv head hi//(H/KV) straight from the BlockSpec index map, no
    expansion pass), pos [B] → o [B,1,H,D] float32. Positions > pos[b]
    are masked per slot. With ``k_scale``/``v_scale`` [B,S,KV] the cache
    arrays are int8 and dequantized blockwise in VMEM."""
    b, _, h, d = q.shape
    s_len = cache_k.shape[1]
    n_kv = cache_k.shape[2]
    if h % n_kv:
        raise ValueError(f"query heads {h} not divisible by kv heads {n_kv}")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    group = h // n_kv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bk, n_k = _pick_block(s_len, block_k)
    kernel = functools.partial(
        _kernel, scale=scale, block_k=bk, n_k=n_k, s_len=s_len,
        quantized=quantized,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU interprets

    kv_spec = pl.BlockSpec(
        (1, bk, 1, d), lambda bi, hi, kk, pos_ref: (bi, kk, hi // group, 0)
    )
    in_specs = [
        pl.BlockSpec((1, 1, 1, d), lambda bi, hi, kk, pos_ref: (bi, 0, hi, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [pos.astype(jnp.int32), q, cache_k, cache_v]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, bk, 1), lambda bi, hi, kk, pos_ref: (bi, kk, hi // group)
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, 1, d), lambda bi, hi, kk, pos_ref: (bi, 0, hi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out


def make_decode_attention(interpret: Optional[bool] = None, **kwargs):
    """attn factory: real kernel on TPU, interpreter elsewhere.

    The returned ``attn(q, ck, cv, pos)`` accepts either float cache
    arrays or the serving int8 cache entries ``(ck8, k_scale)`` /
    ``(cv8, v_scale)`` (models/serving.py quantize_kv layout)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, cache_k, cache_v, pos):
        if isinstance(cache_k, tuple):
            (k8, ks), (v8, vs) = cache_k, cache_v
            return decode_attention(
                q, k8, v8, pos, k_scale=ks, v_scale=vs,
                interpret=interpret, **kwargs,
            )
        return decode_attention(q, cache_k, cache_v, pos,
                                interpret=interpret, **kwargs)

    return attn
