"""Single-token decode attention as a Pallas TPU kernel.

The serving hot loop (models/serving.py batched_decode_step) attends one
query token per slot against that slot's KV cache. Decode attention is
memory-bound: the FLOPs are trivial, the cost is streaming the cache out
of HBM. An unfused formulation reads K for the scores and V for the
weighted sum as two separate passes with a [B,H,1,S] score tensor in
between; this kernel is the flash-style single pass — each cache block is
read once, scores never leave VMEM, and the per-slot fill level arrives
as a scalar-prefetch operand, so masking costs no extra HBM tensor.

The kernel indexes the serving cache layout [B, S, H, D] directly via
BlockSpecs (grid (B, H, k-blocks), block (1, bk, 1, d)) — no transpose,
no pad, no bias materialization on the host side; ``pos`` [B] rides in
SMEM. k innermost with "arbitrary" semantics (sequential on TPU), the
online-softmax scratch (m, l, acc) carried across k iterations — the
shared recurrence of ops/pallas/_primitives.py specialized to one query
row. Blocks entirely beyond a slot's fill level are predicated off with
@pl.when.

Int8 caches: pass ``k_scale``/``v_scale`` [B, S, KV] (per-token-per-head
symmetric scales, models/serving.quantize_kv layout) and int8 cache
arrays — the kernel dequantizes per block in VMEM, so HBM traffic stays
at the int8 byte count (the whole point of quantizing the cache: 4× less
cache streaming per decode step than f32).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas import registry as _registry
from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params
from nnstreamer_tpu.ops.pallas._primitives import (
    NEG_INF,
    dequant_rows,
    mask_dead_columns,
    online_softmax_finalize,
    online_softmax_init,
    online_softmax_update,
    scaled_qk,
)


def _kernel(pos_ref, q_ref, k_ref, v_ref, *rest,
            scale: float, block_k: int, n_k: int, s_len: int,
            quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        online_softmax_init(m_ref, l_ref, acc_ref)

    k_start = ki * block_k
    # positions 0..pos inclusive are attendable; a windowed ring passes
    # ABSOLUTE pos, so after a wrap pos+1 exceeds the cache length and
    # every row is live — clamp to the static cache length so the tail
    # block's pad columns (cols in [s_len, n_k*block_k)) stay masked
    # instead of streaming pad garbage into the softmax.
    live_len = jnp.minimum(pos_ref[b] + 1, s_len)

    @pl.when(k_start < live_len)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)       # [1, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = dequant_rows(k, ks_ref[0, :, 0])
            v = dequant_rows(v, vs_ref[0, :, 0])
        s = scaled_qk(q, k, scale)                 # [1, bk]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s, v = mask_dead_columns(s, v, cols, live_len)
        m_ref[:], l_ref[:], acc_ref[:] = online_softmax_update(
            s, v, m_ref[:], l_ref[:], acc_ref[:]
        )

    @pl.when(ki == n_k - 1)
    def _final():
        o_ref[0, 0] = online_softmax_finalize(l_ref[:], acc_ref[:], o_ref.dtype)


def _pick_block(s_len: int, block_k: int) -> Tuple[int, int]:
    """(block size, grid length) covering s_len with ceil-division.

    Blocks need not divide the cache length: Pallas pads the tail block,
    and the kernel's ``cols < live_len`` mask (live_len ≤ s_len) already
    neutralizes the pad columns — so a prime or odd cache length keeps
    full-width blocks instead of degenerating to 1-row blocks."""
    bk = min(block_k, s_len)
    return bk, -(-s_len // bk)


# BlockSpec index maps — module-level so the registered LaunchPlan and
# the live pallas_call share the SAME callables (grid (b, h, k-blocks),
# pos prefetched). GQA: query head hi reads kv head hi//group.
def _q_index_map(bi, hi, kk, pos_ref):
    return (bi, 0, hi, 0)


def _kv_index_map(group):
    return lambda bi, hi, kk, pos_ref: (bi, kk, hi // group, 0)


def _scale_index_map(group):
    return lambda bi, hi, kk, pos_ref: (bi, kk, hi // group)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def decode_attention(
    q,
    cache_k,
    cache_v,
    pos,
    k_scale=None,
    v_scale=None,
    scale: Optional[float] = None,
    block_k: int = 128,
    interpret: bool = False,
):
    """q [B,1,H,D], cache_k/v [B,S,KV,D] (the serving layout, consumed
    in place; KV ≤ H under grouped-query attention — query head hi reads
    kv head hi//(H/KV) straight from the BlockSpec index map, no
    expansion pass), pos [B] → o [B,1,H,D] float32. Positions > pos[b]
    are masked per slot. With ``k_scale``/``v_scale`` [B,S,KV] the cache
    arrays are int8 and dequantized blockwise in VMEM."""
    b, _, h, d = q.shape
    s_len = cache_k.shape[1]
    n_kv = cache_k.shape[2]
    if h % n_kv:
        raise ValueError(f"query heads {h} not divisible by kv heads {n_kv}")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    group = h // n_kv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bk, n_k = _pick_block(s_len, block_k)
    kernel = functools.partial(
        _kernel, scale=scale, block_k=bk, n_k=n_k, s_len=s_len,
        quantized=quantized,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU interprets

    kv_spec = pl.BlockSpec((1, bk, 1, d), _kv_index_map(group))
    in_specs = [
        pl.BlockSpec((1, 1, 1, d), _q_index_map),
        kv_spec,
        kv_spec,
    ]
    operands = [pos.astype(jnp.int32), q, cache_k, cache_v]
    if quantized:
        scale_spec = pl.BlockSpec((1, bk, 1), _scale_index_map(group))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, 1, d), _q_index_map),
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out


def decode_attention_ref(q, cache_k, cache_v, pos, k_scale=None,
                         v_scale=None, scale: Optional[float] = None):
    """jnp masked-softmax reference of the decode kernel: q [B,1,H,D],
    cache [B,S,KV,D] (int8 with ``k_scale``/``v_scale`` [B,S,KV]), pos
    [B] → [B,1,H,D] float32. Same clamp as the kernel: positions
    0..min(pos, S-1) attendable (a wrapped ring passes absolute pos).
    GQA folds query heads over the compact KV heads, no expansion."""
    b, _, h, d = q.shape
    s_len = cache_k.shape[1]
    n_kv = cache_k.shape[2]
    g = h // n_kv
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    ck = cache_k.astype(jnp.float32)
    cv = cache_v.astype(jnp.float32)
    if k_scale is not None:
        ck = ck * k_scale[..., None]
        cv = cv * v_scale[..., None]
    q5 = q.astype(jnp.float32)[:, 0].reshape(b, n_kv, g, d)
    s = jnp.einsum("bkgd,bskd->bkgs", q5, ck) * sc
    live_len = jnp.minimum(pos + 1, s_len)
    live = jnp.arange(s_len)[None, :] < live_len[:, None]
    s = jnp.where(live[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bskd->bkgd", p, cv)
    return o.reshape(b, 1, h, d)


def make_decode_attention(interpret: Optional[bool] = None, **kwargs):
    """attn factory: real kernel on TPU, interpreter elsewhere.

    The returned ``attn(q, ck, cv, pos)`` accepts either float cache
    arrays or the serving int8 cache entries ``(ck8, k_scale)`` /
    ``(cv8, v_scale)`` (models/serving.py quantize_kv layout). Each
    trace consults the registry's dtype support (_compat.pallas_ok) and
    degrades to :func:`decode_attention_ref` with a logged reason
    instead of a trace-time Mosaic error; the resolved choice lands in
    the dispatch tally as op "decode_attention"."""
    from nnstreamer_tpu.ops.dispatch import record as _record_dispatch
    from nnstreamer_tpu.ops.pallas._compat import pallas_ok

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, cache_k, cache_v, pos):
        payload = cache_k[0] if isinstance(cache_k, tuple) else cache_k
        ok, _ = pallas_ok("decode_attention", payload.dtype)
        _record_dispatch("decode_attention", "pallas" if ok else "jnp")
        if isinstance(cache_k, tuple):
            (k8, ks), (v8, vs) = cache_k, cache_v
            fn = decode_attention if ok else decode_attention_ref
            kw = dict(kwargs) if ok else {
                k: v for k, v in kwargs.items() if k == "scale"
            }
            if ok:
                kw["interpret"] = interpret
            return fn(q, k8, v8, pos, k_scale=ks, v_scale=vs, **kw)
        if not ok:
            return decode_attention_ref(
                q, cache_k, cache_v, pos, scale=kwargs.get("scale")
            )
        return decode_attention(q, cache_k, cache_v, pos,
                                interpret=interpret, **kwargs)

    return attn


# -- kernel registration (nns-kscope) ----------------------------------------


def _plan(params):
    b, h, d = params.get("b", 2), params.get("h", 4), params.get("d", 16)
    n_kv = params.get("n_kv", h)
    s_len = params["s_len"]
    dtype = params.get("dtype", "float32")
    group = h // n_kv
    bk, n_k = _pick_block(s_len, params.get("block_k", 128))
    quantized = dtype == "int8"
    blocks = [
        _registry.BlockDesc(
            "q", "in", (b, 1, h, d), (1, 1, 1, d), dtype if not quantized
            else "float32", _q_index_map,
        ),
        _registry.BlockDesc(
            "cache_k", "in", (b, s_len, n_kv, d), (1, bk, 1, d), dtype,
            _kv_index_map(group),
        ),
        _registry.BlockDesc(
            "cache_v", "in", (b, s_len, n_kv, d), (1, bk, 1, d), dtype,
            _kv_index_map(group),
        ),
    ]
    if quantized:
        for nm in ("k_scale", "v_scale"):
            blocks.append(_registry.BlockDesc(
                nm, "in", (b, s_len, n_kv), (1, bk, 1), "float32",
                _scale_index_map(group),
            ))
    blocks.append(_registry.BlockDesc(
        "o", "out", (b, 1, h, d), (1, 1, 1, d), "float32", _q_index_map,
    ))
    import numpy as np

    return _registry.LaunchPlan(
        grid=(b, h, n_k),
        blocks=tuple(blocks),
        scratch=(
            _registry.ScratchDesc("m", (1,)),
            _registry.ScratchDesc("l", (1,)),
            _registry.ScratchDesc("acc", (1, d)),
        ),
        prefetch=(
            _registry.PrefetchDesc(
                "pos", (b,),
                make=lambda: np.full((b,), s_len - 1, np.int32),
            ),
        ),
        # q·Kᵀ + p·V: 2·s·d each per (slot, head)
        flops=4 * b * h * s_len * d,
        notes="memory-bound: cache streaming dominates",
    )


def _run_case(params):
    import numpy as np

    rng = np.random.default_rng(1)
    b, h, d = params.get("b", 3), params.get("h", 4), params.get("d", 16)
    n_kv = params.get("n_kv", h)
    s_len, block_k = params["s_len"], params.get("block_k", 128)
    dtype = params.get("dtype", "float32")
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    # default fills spread slot positions from empty to full
    default_pos = [(i * (s_len - 1)) // max(1, b - 1) for i in range(b)]
    pos = jnp.asarray(params.get("pos", default_pos), jnp.int32)
    if dtype == "int8":
        ck = jnp.asarray(rng.integers(-127, 128, (b, s_len, n_kv, d)), jnp.int8)
        cv = jnp.asarray(rng.integers(-127, 128, (b, s_len, n_kv, d)), jnp.int8)
        ks = jnp.asarray(rng.uniform(0.01, 0.1, (b, s_len, n_kv)), jnp.float32)
        vs = jnp.asarray(rng.uniform(0.01, 0.1, (b, s_len, n_kv)), jnp.float32)
        got = decode_attention(q, ck, cv, pos, k_scale=ks, v_scale=vs,
                               block_k=block_k, interpret=True)
        want = decode_attention_ref(q, ck, cv, pos, k_scale=ks, v_scale=vs)
        return got, want, 2e-5
    cast = jnp.dtype(dtype)
    qd = q.astype(cast)
    ck = jnp.asarray(rng.standard_normal((b, s_len, n_kv, d)), jnp.float32).astype(cast)
    cv = jnp.asarray(rng.standard_normal((b, s_len, n_kv, d)), jnp.float32).astype(cast)
    got = decode_attention(qd, ck, cv, pos, block_k=block_k, interpret=True)
    want = decode_attention_ref(qd, ck, cv, pos)
    return got, want, (2e-2 if cast == jnp.bfloat16 else 2e-5)


def _probe():
    import numpy as np

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((1, 16, 2, 8)), jnp.float32)
    cv = jnp.asarray(rng.standard_normal((1, 16, 2, 8)), jnp.float32)
    pos = jnp.asarray([7], jnp.int32)
    np.asarray(make_decode_attention(interpret=True)(q, ck, cv, pos))


_registry.register(_registry.KernelSpec(
    name="decode_attention",
    module=__name__,
    ops=("decode_attention", "serving_attention"),
    dtypes=("float32", "bfloat16", "int8"),
    cases=(
        # the parity grid tests/test_pallas.py parametrizes over; the
        # non-dividing lengths pin ceil-covered tail blocks (ADVICE r2)
        _registry.ShapeCase("s64-bk16", {"s_len": 64, "block_k": 16}, tier1=True),
        _registry.ShapeCase("s48-bk16", {"s_len": 48, "block_k": 16}),
        _registry.ShapeCase("s40-bk128", {"s_len": 40, "block_k": 128}, tier1=True),
        _registry.ShapeCase("s97-bk32", {"s_len": 97, "block_k": 32}, tier1=True),
        _registry.ShapeCase("s130-bk128", {"s_len": 130, "block_k": 128}),
        _registry.ShapeCase("s33-bk16", {"s_len": 33, "block_k": 16}),
        _registry.ShapeCase(
            "gqa-int8",
            {"b": 2, "h": 4, "n_kv": 2, "s_len": 48, "block_k": 16,
             "dtype": "int8", "pos": [11, 40]},
            tier1=True,
        ),
        _registry.ShapeCase(
            "bf16",
            {"b": 2, "h": 2, "s_len": 32, "block_k": 16, "dtype": "bfloat16",
             "pos": [5, 20]},
        ),
        _registry.ShapeCase(
            "serve-2048", {"b": 8, "h": 8, "d": 128, "s_len": 2048},
        ),
    ),
    plan=_plan,
    run_case=_run_case,
    probe=_probe,
))
