"""Single-token decode attention as a Pallas TPU kernel.

The serving hot loop (models/serving.py batched_decode_step) attends one
query token per slot against that slot's KV cache. Decode attention is
memory-bound: the FLOPs are trivial, the cost is streaming the cache out
of HBM. An unfused formulation reads K for the scores and V for the
weighted sum as two separate passes with a [B,H,1,S] score tensor in
between; this kernel is the flash-style single pass — each cache block is
read once, scores never leave VMEM, and the per-slot fill-level mask is
an additive bias fused into the same pass.

Grid: (B*H, k-blocks), k innermost with "arbitrary" semantics (sequential
on TPU), online-softmax scratch (m, l, acc) carried across k iterations —
the same recurrence as ops/pallas/flash_attention.py specialized to one
query row. Layout contract: q [BH, D], k/v [BH, S, D], bias [BH, S]
(0 for live positions, NEG_INF for masked); the wrapper builds these from
the serving shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, b_ref, o_ref, m_ref, l_ref, acc_ref,
            *, scale: float, n_k: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:].astype(jnp.float32)        # [1, d]
    k = k_ref[0].astype(jnp.float32)        # [bk, d]
    v = v_ref[0].astype(jnp.float32)        # [bk, d]
    bias = b_ref[:].astype(jnp.float32)     # [1, bk]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale + bias                        # [1, bk]

    m_prev = m_ref[:]                       # [1]
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
    p = jnp.where(m_new[:, None] <= NEG_INF, 0.0, jnp.exp(s - m_new[:, None]))
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)
    m_ref[:] = m_new
    acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ki == n_k - 1)
    def _final():
        l2 = l_ref[:][:, None]
        o_ref[:] = jnp.where(
            l2 > 0, acc_ref[:] / jnp.maximum(l2, 1e-30), 0.0
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def decode_attention(
    q,
    cache_k,
    cache_v,
    pos,
    scale: Optional[float] = None,
    block_k: int = 128,
    interpret: bool = False,
):
    """q [B,1,H,D], cache_k/v [B,S,H,D] (serving layout), pos [B] → o
    [B,1,H,D] float32. Positions > pos[b] are masked per slot."""
    b, _, h, d = q.shape
    s_len = cache_k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bk = min(block_k, s_len)
    s_pad = -(-s_len // bk) * bk

    qf = q.reshape(b, h, d).reshape(b * h, d)

    def fold(c):
        c = c.transpose(0, 2, 1, 3).reshape(b * h, s_len, d)
        if s_pad != s_len:
            c = jnp.pad(c, ((0, 0), (0, s_pad - s_len), (0, 0)))
        return c

    kf, vf = fold(cache_k), fold(cache_v)
    live = jnp.arange(s_pad)[None, :] <= pos[:, None]  # [B, s_pad]
    bias = jnp.where(live, 0.0, NEG_INF).astype(jnp.float32)
    bias = jnp.repeat(bias, h, axis=0)  # [BH, s_pad]

    n_k = s_pad // bk
    kernel = functools.partial(_kernel, scale=scale, n_k=n_k)

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU interprets

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, d), jnp.float32),
        grid=(b * h, n_k),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, kk: (i, 0)),
            pl.BlockSpec((1, bk, d), lambda i, kk: (i, kk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, kk: (i, kk, 0)),
            pl.BlockSpec((1, bk), lambda i, kk: (i, kk)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, kk: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf, bias)
    return out.reshape(b, h, d)[:, None]  # [B,1,H,D]


def make_decode_attention(interpret: Optional[bool] = None, **kwargs):
    """attn factory: real kernel on TPU, interpreter elsewhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, cache_k, cache_v, pos):
        return decode_attention(q, cache_k, cache_v, pos,
                                interpret=interpret, **kwargs)

    return attn
