"""Paged (block-table) decode attention as a Pallas TPU kernel.

The flash-style single-pass decode kernel of
:mod:`~nnstreamer_tpu.ops.pallas.decode_attention` generalized to the
nns-kv paged layout (docs/llm-serving.md): instead of one contiguous
``[B, S, KV, D]`` cache row per slot, the K/V live in a shared block
arena ``[N, bs, KV, D]`` behind per-slot block tables ``[B, nb]`` —
and the whole point of this kernel is that the arena is attended
**through the table**, one block per grid step, with NO gathered
contiguous view ever materialized in HBM (the gather → attend →
scatter round trip the jnp gather formulation pays).

Mechanics (grid ``(B, H, nb)``, k innermost with "arbitrary"
semantics):

- the block table and per-slot fill levels ride as SCALAR-PREFETCH
  operands, so each grid step's BlockSpec index map picks the physical
  arena block to DMA (``tables[b, kb]``) before the body runs — each
  live arena block is read from HBM exactly once per (slot, head);
- blocks at or beyond a slot's fill level — including the
  scratch-mapped unallocated table tail — are predicated off with
  ``@pl.when``; partially-filled blocks mask their dead columns to
  softmax weight exactly zero and zero the matching V rows, so
  arbitrary scratch content can never leak into the output;
- the online-softmax scratch (m, l, acc) carries across blocks, and
  the pending token's OWN K/V (``fresh_k``/``fresh_v``, not yet in the
  arena — the batcher lands it after the layer scan with one in-place
  block write) folds in the final grid step: it is position ``pos``,
  the highest live column, so the reduction order equals position
  order;
- int8 arenas pass ``k_scale``/``v_scale`` ``[N, bs, KV]`` (the
  per-token-per-head symmetric scales of models/serving.quantize_kv)
  and dequantize per block in VMEM — HBM traffic stays at the int8
  byte count.

Off-TPU the kernel runs in interpret mode (``_compat`` discipline);
``kv.block_attn.block_attention(impl="auto")`` dispatches between this
kernel (TPU) and the jnp online-softmax reference it is pinned against
in tests/test_kv_block_attn.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params

NEG_INF = -1e30


def _kernel(tab_ref, pos_ref, q_ref, k_ref, v_ref, fk_ref, fv_ref, *rest,
            scale: float, block_k: int, n_b: int, quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # history length: positions 0..pos-1 live in arena blocks (the
    # pending token's column is the separate fresh operand); clamped to
    # the table's reach so a stale lane can never walk past the arena
    hist = jnp.minimum(pos_ref[b], n_b * block_k)
    k_start = kb * block_k

    @pl.when(k_start < hist)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)        # [1, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bs, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            # per-row dequant in VMEM: int8 payload × f32 scale [bs]
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                   # [1, bs]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < hist, s, NEG_INF)
        # dead rows get weight exp(NEG_INF - m) = 0, but a scratch-mapped
        # or partially-filled block may hold arbitrary V bytes, and
        # 0 * NaN = NaN — zero those rows so the weighted sum stays clean
        v = jnp.where(cols.reshape(-1, 1) < hist, v, 0.0)

        m_prev = m_ref[:]                           # [1]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
        p = jnp.where(
            m_new[:, None] <= NEG_INF, 0.0, jnp.exp(s - m_new[:, None])
        )
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == n_b - 1)
    def _final():
        # fold the pending token's own column (position pos — the
        # highest live position, so folding it LAST keeps the reduction
        # in position order), then normalize
        q = q_ref[0, 0].astype(jnp.float32)         # [1, d]
        fk = fk_ref[0, 0, 0].astype(jnp.float32)    # [d]
        fv = fv_ref[0, 0, 0].astype(jnp.float32)
        s1 = jax.lax.dot_general(
            q, fk[None, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [1, 1]
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s1[:, 0])
        alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
        p1 = jnp.exp(s1 - m_new[:, None])           # always live
        l = l_ref[:] * alpha + jnp.sum(p1, axis=1)
        acc = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p1, fv[None, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l2 = l[:, None]
        o_ref[0, 0] = jnp.where(
            l2 > 0, acc / jnp.maximum(l2, 1e-30), 0.0
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q,
    arena_k,
    arena_v,
    tables,
    pos,
    fresh_k,
    fresh_v,
    k_scale=None,
    v_scale=None,
    scale: Optional[float] = None,
    interpret: bool = False,
):
    """q [B,1,H,D]; arena_k/v [N, bs, KV, D] (the kv.gather arena leaves
    of ONE layer, consumed in place; KV ≤ H under grouped-query
    attention — query head hi reads kv head hi//(H/KV) straight from
    the BlockSpec index map); tables [B, nb] int32 block tables; pos
    [B] int32 HISTORY lengths (positions 0..pos-1 attendable from
    blocks); fresh_k/v [B,1,KV,D] the pending token's K/V (column pos)
    → o [B,1,H,D] float32. With ``k_scale``/``v_scale`` [N, bs, KV]
    the arena payloads are int8 and dequantized blockwise in VMEM."""
    b, _, h, d = q.shape
    n_kv = arena_k.shape[2]
    bs = arena_k.shape[1]
    nb = tables.shape[1]
    if h % n_kv:
        raise ValueError(f"query heads {h} not divisible by kv heads {n_kv}")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    group = h // n_kv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    kernel = functools.partial(
        _kernel, scale=scale, block_k=bs, n_b=nb, quantized=quantized,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU interprets

    # the physical arena block each grid step streams is picked by the
    # PREFETCHED table — this index map is where the gather disappears
    kv_spec = pl.BlockSpec(
        (1, bs, 1, d),
        lambda bi, hi, kb, tab_ref, pos_ref: (tab_ref[bi, kb], 0,
                                              hi // group, 0),
    )
    fresh_spec = pl.BlockSpec(
        (1, 1, 1, d),
        lambda bi, hi, kb, tab_ref, pos_ref: (bi, 0, hi // group, 0),
    )
    in_specs = [
        pl.BlockSpec(
            (1, 1, 1, d),
            lambda bi, hi, kb, tab_ref, pos_ref: (bi, 0, hi, 0),
        ),
        kv_spec,
        kv_spec,
        fresh_spec,
        fresh_spec,
    ]
    operands = [
        tables.astype(jnp.int32), pos.astype(jnp.int32),
        q, arena_k, arena_v, fresh_k, fresh_v,
    ]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, bs, 1),
            lambda bi, hi, kb, tab_ref, pos_ref: (tab_ref[bi, kb], 0,
                                                  hi // group),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, 1, d),
            lambda bi, hi, kb, tab_ref, pos_ref: (bi, 0, hi, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out


def make_paged_attention(interpret: Optional[bool] = None, **kwargs):
    """attn factory for the block-native serving step: real kernel on
    TPU, interpreter elsewhere.

    The returned ``attn(q, k_entry, v_entry, tables, pos, (fk, fv))``
    accepts either float arena leaves or the int8 entries
    ``(payload, scales)`` exactly as kv.block_attn's step bodies hold
    them; ``fk``/``fv`` are the pending token's (already dequantized)
    K/V, folded as the final online-softmax column."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, cache_k, cache_v, tables, pos, fresh_kv):
        fk, fv = fresh_kv
        if isinstance(cache_k, tuple):
            (k8, ks), (v8, vs) = cache_k, cache_v
            return paged_decode_attention(
                q, k8, v8, tables, pos, fk, fv, k_scale=ks, v_scale=vs,
                interpret=interpret, **kwargs,
            )
        return paged_decode_attention(
            q, cache_k, cache_v, tables, pos, fk, fv,
            interpret=interpret, **kwargs,
        )

    return attn
