"""Paged (block-table) decode attention as a Pallas TPU kernel.

The flash-style single-pass decode kernel of
:mod:`~nnstreamer_tpu.ops.pallas.decode_attention` generalized to the
nns-kv paged layout (docs/llm-serving.md): instead of one contiguous
``[B, S, KV, D]`` cache row per slot, the K/V live in a shared block
arena ``[N, bs, KV, D]`` behind per-slot block tables ``[B, nb]`` —
and the whole point of this kernel is that the arena is attended
**through the table**, one block per grid step, with NO gathered
contiguous view ever materialized in HBM (the gather → attend →
scatter round trip the jnp gather formulation pays).

Mechanics (grid ``(B, H, nb)``, k innermost with "arbitrary"
semantics):

- the block table and per-slot fill levels ride as SCALAR-PREFETCH
  operands, so each grid step's BlockSpec index map picks the physical
  arena block to DMA (``tables[b, kb]``) before the body runs — each
  live arena block is read from HBM exactly once per (slot, head);
- blocks at or beyond a slot's fill level — including the
  scratch-mapped unallocated table tail — are predicated off with
  ``@pl.when``; partially-filled blocks mask their dead columns to
  softmax weight exactly zero and zero the matching V rows, so
  arbitrary scratch content can never leak into the output;
- the online-softmax scratch (m, l, acc) carries across blocks (the
  shared recurrence of ops/pallas/_primitives.py), and the pending
  token's OWN K/V (``fresh_k``/``fresh_v``, not yet in the arena — the
  batcher lands it after the layer scan with one in-place block write)
  folds in the final grid step: it is position ``pos``, the highest
  live column, so the reduction order equals position order;
- int8 arenas pass ``k_scale``/``v_scale`` ``[N, bs, KV]`` (the
  per-token-per-head symmetric scales of models/serving.quantize_kv)
  and dequantize per block in VMEM — HBM traffic stays at the int8
  byte count.

Off-TPU the kernel runs in interpret mode (``_compat`` discipline);
``kv.block_attn.block_attention(impl="auto")`` dispatches between this
kernel (TPU) and the jnp online-softmax reference it is pinned against
in tests/test_kv_block_attn.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas import registry as _registry
from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params
from nnstreamer_tpu.ops.pallas._primitives import (
    NEG_INF,
    dequant_rows,
    mask_dead_columns,
    online_softmax_finalize,
    online_softmax_init,
    online_softmax_update,
    scaled_qk,
)


def _kernel(tab_ref, pos_ref, q_ref, k_ref, v_ref, fk_ref, fv_ref, *rest,
            scale: float, block_k: int, n_b: int, quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        online_softmax_init(m_ref, l_ref, acc_ref)

    # history length: positions 0..pos-1 live in arena blocks (the
    # pending token's column is the separate fresh operand); clamped to
    # the table's reach so a stale lane can never walk past the arena
    hist = jnp.minimum(pos_ref[b], n_b * block_k)
    k_start = kb * block_k

    @pl.when(k_start < hist)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)        # [1, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [bs, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = dequant_rows(k, ks_ref[0, :, 0])
            v = dequant_rows(v, vs_ref[0, :, 0])
        s = scaled_qk(q, k, scale)                  # [1, bs]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s, v = mask_dead_columns(s, v, cols, hist)
        m_ref[:], l_ref[:], acc_ref[:] = online_softmax_update(
            s, v, m_ref[:], l_ref[:], acc_ref[:]
        )

    @pl.when(kb == n_b - 1)
    def _final():
        # fold the pending token's own column (position pos — the
        # highest live position, so folding it LAST keeps the reduction
        # in position order), then normalize
        q = q_ref[0, 0].astype(jnp.float32)         # [1, d]
        fk = fk_ref[0, 0, 0].astype(jnp.float32)    # [d]
        fv = fv_ref[0, 0, 0].astype(jnp.float32)
        s1 = scaled_qk(q, fk[None, :], scale)       # [1, 1] — always live
        _, l, acc = online_softmax_update(
            s1, fv[None, :], m_ref[:], l_ref[:], acc_ref[:]
        )
        o_ref[0, 0] = online_softmax_finalize(l, acc, o_ref.dtype)


# BlockSpec index maps — module-level so the registered LaunchPlan and
# the live pallas_call share the SAME callables (grid (b, h, nb),
# tables + pos prefetched). The kv map is where the gather disappears:
# the PREFETCHED table picks the physical arena block each step DMAs.
def _q_index_map(bi, hi, kb, tab_ref, pos_ref):
    return (bi, 0, hi, 0)


def _kv_index_map(group):
    return lambda bi, hi, kb, tab_ref, pos_ref: (tab_ref[bi, kb], 0,
                                                 hi // group, 0)


def _fresh_index_map(group):
    return lambda bi, hi, kb, tab_ref, pos_ref: (bi, 0, hi // group, 0)


def _scale_index_map(group):
    return lambda bi, hi, kb, tab_ref, pos_ref: (tab_ref[bi, kb], 0,
                                                 hi // group)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q,
    arena_k,
    arena_v,
    tables,
    pos,
    fresh_k,
    fresh_v,
    k_scale=None,
    v_scale=None,
    scale: Optional[float] = None,
    interpret: bool = False,
):
    """q [B,1,H,D]; arena_k/v [N, bs, KV, D] (the kv.gather arena leaves
    of ONE layer, consumed in place; KV ≤ H under grouped-query
    attention — query head hi reads kv head hi//(H/KV) straight from
    the BlockSpec index map); tables [B, nb] int32 block tables; pos
    [B] int32 HISTORY lengths (positions 0..pos-1 attendable from
    blocks); fresh_k/v [B,1,KV,D] the pending token's K/V (column pos)
    → o [B,1,H,D] float32. With ``k_scale``/``v_scale`` [N, bs, KV]
    the arena payloads are int8 and dequantized blockwise in VMEM."""
    b, _, h, d = q.shape
    n_kv = arena_k.shape[2]
    bs = arena_k.shape[1]
    nb = tables.shape[1]
    if h % n_kv:
        raise ValueError(f"query heads {h} not divisible by kv heads {n_kv}")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    group = h // n_kv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    kernel = functools.partial(
        _kernel, scale=scale, block_k=bs, n_b=nb, quantized=quantized,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU interprets

    kv_spec = pl.BlockSpec((1, bs, 1, d), _kv_index_map(group))
    fresh_spec = pl.BlockSpec((1, 1, 1, d), _fresh_index_map(group))
    in_specs = [
        pl.BlockSpec((1, 1, 1, d), _q_index_map),
        kv_spec,
        kv_spec,
        fresh_spec,
        fresh_spec,
    ]
    operands = [
        tables.astype(jnp.int32), pos.astype(jnp.int32),
        q, arena_k, arena_v, fresh_k, fresh_v,
    ]
    if quantized:
        scale_spec = pl.BlockSpec((1, bs, 1), _scale_index_map(group))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, 1, d), _q_index_map),
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out


def make_paged_attention(interpret: Optional[bool] = None, **kwargs):
    """attn factory for the block-native serving step: real kernel on
    TPU, interpreter elsewhere.

    The returned ``attn(q, k_entry, v_entry, tables, pos, (fk, fv))``
    accepts either float arena leaves or the int8 entries
    ``(payload, scales)`` exactly as kv.block_attn's step bodies hold
    them; ``fk``/``fv`` are the pending token's (already dequantized)
    K/V, folded as the final online-softmax column."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, cache_k, cache_v, tables, pos, fresh_kv):
        fk, fv = fresh_kv
        if isinstance(cache_k, tuple):
            (k8, ks), (v8, vs) = cache_k, cache_v
            return paged_decode_attention(
                q, k8, v8, tables, pos, fk, fv, k_scale=ks, v_scale=vs,
                interpret=interpret, **kwargs,
            )
        return paged_decode_attention(
            q, cache_k, cache_v, tables, pos, fk, fv,
            interpret=interpret, **kwargs,
        )

    return attn


# -- kernel registration (nns-kscope) ----------------------------------------


def _plan(params):
    b, h, d = params.get("b", 2), params.get("h", 4), params.get("d", 16)
    n_kv = params.get("n_kv", h)
    bs, nb = params["bs"], params["nb"]
    n_blocks = params.get("n_blocks", b * nb)
    dtype = params.get("dtype", "float32")
    group = h // n_kv
    quantized = dtype == "int8"
    float_dtype = "float32" if quantized else dtype
    blocks = [
        _registry.BlockDesc(
            "q", "in", (b, 1, h, d), (1, 1, 1, d), float_dtype, _q_index_map,
        ),
        _registry.BlockDesc(
            "arena_k", "in", (n_blocks, bs, n_kv, d), (1, bs, 1, d), dtype,
            _kv_index_map(group),
        ),
        _registry.BlockDesc(
            "arena_v", "in", (n_blocks, bs, n_kv, d), (1, bs, 1, d), dtype,
            _kv_index_map(group),
        ),
        _registry.BlockDesc(
            "fresh_k", "in", (b, 1, n_kv, d), (1, 1, 1, d), float_dtype,
            _fresh_index_map(group),
        ),
        _registry.BlockDesc(
            "fresh_v", "in", (b, 1, n_kv, d), (1, 1, 1, d), float_dtype,
            _fresh_index_map(group),
        ),
    ]
    if quantized:
        for nm in ("k_scale", "v_scale"):
            blocks.append(_registry.BlockDesc(
                nm, "in", (n_blocks, bs, n_kv), (1, bs, 1), "float32",
                _scale_index_map(group),
            ))
    blocks.append(_registry.BlockDesc(
        "o", "out", (b, 1, h, d), (1, 1, 1, d), "float32", _q_index_map,
    ))
    import numpy as np

    return _registry.LaunchPlan(
        grid=(b, h, nb),
        blocks=tuple(blocks),
        scratch=(
            _registry.ScratchDesc("m", (1,)),
            _registry.ScratchDesc("l", (1,)),
            _registry.ScratchDesc("acc", (1, d)),
        ),
        prefetch=(
            _registry.PrefetchDesc(
                "tables", (b, nb),
                make=lambda: np.arange(b * nb, dtype=np.int32).reshape(b, nb)
                % n_blocks,
            ),
            _registry.PrefetchDesc(
                "pos", (b,),
                make=lambda: np.full((b,), nb * bs, np.int32),
            ),
        ),
        # q·Kᵀ + p·V over nb·bs history columns plus the fresh column
        flops=4 * b * h * (nb * bs + 1) * d,
        notes="arena blocks picked through the prefetched table",
    )


def _case_arrays(params, rng):
    import numpy as np

    b, h, d = params.get("b", 2), params.get("h", 4), params.get("d", 16)
    n_kv = params.get("n_kv", h)
    bs, nb = params["bs"], params["nb"]
    n_blocks = params.get("n_blocks", b * nb)
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(n_blocks)[: b * nb].reshape(b, nb), jnp.int32
    )
    # default fills spread slot positions from empty to full
    default_pos = [(i * nb * bs) // max(1, b - 1) for i in range(b)]
    pos = jnp.asarray(params.get("pos", default_pos), jnp.int32)
    fk = jnp.asarray(rng.standard_normal((b, 1, n_kv, d)), jnp.float32)
    fv = jnp.asarray(rng.standard_normal((b, 1, n_kv, d)), jnp.float32)
    return b, h, d, n_kv, bs, nb, n_blocks, q, tables, pos, fk, fv


def _run_case(params):
    import numpy as np

    from nnstreamer_tpu.kv.block_attn import paged_attention_ref

    rng = np.random.default_rng(3)
    (b, h, d, n_kv, bs, nb, n_blocks,
     q, tables, pos, fk, fv) = _case_arrays(params, rng)
    if params.get("dtype") == "int8":
        ak = jnp.asarray(
            rng.integers(-127, 128, (n_blocks, bs, n_kv, d)), jnp.int8
        )
        av = jnp.asarray(
            rng.integers(-127, 128, (n_blocks, bs, n_kv, d)), jnp.int8
        )
        ks = jnp.asarray(rng.uniform(0.01, 0.1, (n_blocks, bs, n_kv)), jnp.float32)
        vs = jnp.asarray(rng.uniform(0.01, 0.1, (n_blocks, bs, n_kv)), jnp.float32)
        got = paged_decode_attention(
            q, ak, av, tables, pos, fk, fv, k_scale=ks, v_scale=vs,
            interpret=True,
        )
        want = paged_attention_ref(
            q, ak, av, tables, pos, (fk, fv), k_scale=ks, v_scale=vs
        )
        return got, want, 2e-5
    ak = jnp.asarray(rng.standard_normal((n_blocks, bs, n_kv, d)), jnp.float32)
    av = jnp.asarray(rng.standard_normal((n_blocks, bs, n_kv, d)), jnp.float32)
    got = paged_decode_attention(q, ak, av, tables, pos, fk, fv, interpret=True)
    want = paged_attention_ref(q, ak, av, tables, pos, (fk, fv))
    return got, want, 2e-5


def _probe():
    import numpy as np

    from nnstreamer_tpu.kv.block_attn import block_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 1, 2, 4)), jnp.float32)
    arena = jnp.asarray(rng.standard_normal((4, 2, 2, 4)), jnp.float32)
    tables = jnp.asarray([[0, 1]], jnp.int32)
    pos = jnp.asarray([3], jnp.int32)
    fk = jnp.asarray(rng.standard_normal((1, 1, 2, 4)), jnp.float32)
    fv = jnp.asarray(rng.standard_normal((1, 1, 2, 4)), jnp.float32)
    np.asarray(block_attention(
        q, arena, arena, tables, pos, (fk, fv), impl="pallas", interpret=True
    ))


_registry.register(_registry.KernelSpec(
    name="paged_decode_attention",
    module=__name__,
    ops=("block_attention", "serving_attention"),
    dtypes=("float32", "bfloat16", "int8"),
    cases=(
        _registry.ShapeCase(
            "b2-full-and-empty", {"bs": 8, "nb": 3, "n_blocks": 8},
            tier1=True,
        ),
        _registry.ShapeCase(
            "gqa-partial-fill",
            {"b": 2, "h": 4, "n_kv": 2, "bs": 8, "nb": 4, "n_blocks": 12,
             "pos": [5, 27]},
            tier1=True,
        ),
        _registry.ShapeCase(
            "int8-arena",
            {"b": 2, "h": 2, "bs": 8, "nb": 3, "n_blocks": 8,
             "dtype": "int8", "pos": [9, 24]},
            tier1=True,
        ),
        _registry.ShapeCase(
            "serve-paged-2048",
            {"b": 8, "h": 8, "d": 128, "bs": 128, "nb": 16, "n_blocks": 128},
        ),
    ),
    plan=_plan,
    run_case=_run_case,
    probe=_probe,
))
