"""Flash attention as a Pallas TPU kernel.

The single-chip hot path for the long-context family (models/transformer.py)
— the [T, T] score matrix never leaves VMEM: the grid walks (batch*heads,
q-blocks, k-blocks) with the k dimension innermost ("arbitrary" semantics —
sequential on TPU), carrying the online-softmax running max/denominator/
accumulator in VMEM scratch across k iterations. Q/K/V blocks stream
HBM→VMEM via BlockSpecs (double-buffered by the pallas pipeline); the
s = q·kᵀ and p·v contractions hit the MXU with float32 accumulation
(preferred_element_type), so bfloat16 inputs keep full softmax precision.

Causal masking compares global row/col indices built from program_id;
fully-masked k-blocks are predicated off with @pl.when, so the causal case
does ~half the work. Matches parallel/ring_attention.dense_attention to
float tolerance (tests/test_pallas.py); composes with ring attention by
serving as the per-shard block math (the same online recurrence
ring_attention_local runs per rotation).

Layout: [B, T, H, D] like the rest of the framework; internally [B*H, T, D].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params

NEG_INF = -1e30


def _kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    n_k: int,
    valid_len: Optional[int],
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # predicate off blocks with no live entries: strictly-above-diagonal
    # (causal) and fully-padded (valid_len) ones
    live = True
    if causal:
        live = q_start + block_q - 1 >= k_start
    if valid_len is not None:
        live = jnp.logical_and(live, k_start < valid_len)

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal or valid_len is not None:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = jnp.ones(s.shape, bool)
            if causal:
                mask = rows >= cols
            if valid_len is not None:
                mask = jnp.logical_and(mask, cols < valid_len)
            s = jnp.where(mask, s, NEG_INF)
        # mosaic note: bool vectors cannot gain a minor dim — expand the
        # f32 operands first, compare in 2D
        m_prev = m_ref[:]  # [bq]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
        m_new2 = m_new[:, None]
        p = jnp.where(m_new2 <= NEG_INF, 0.0, jnp.exp(s - m_new2))
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _final():
        l2 = l_ref[:][:, None]
        o_ref[0] = jnp.where(
            l2 > 0, acc_ref[:] / jnp.maximum(l2, 1e-30), 0.0
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """q, k, v: [B, T, H, D] → [B, T, H, D] float32.

    T pads up to a block multiple internally; padded key columns are
    masked to NEG_INF and padded query rows are sliced off on return."""
    b, t, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bq = min(block_q, max(t, 16))
    bk = min(block_k, max(t, 16))
    blk = max(bq, bk)
    t_pad = -(-t // blk) * blk

    def to_bh(x):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        if t_pad != t:
            x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
        return x

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_q, n_k = t_pad // bq, t_pad // bk
    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        block_q=bq,
        block_k=bk,
        n_k=n_k,
        valid_len=t if t_pad != t else None,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU tests interpret

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d), jnp.float32),
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb)
    return out[:, :t, :].reshape(b, h, t, d).transpose(0, 2, 1, 3)


def make_flash_attention(interpret: Optional[bool] = None, **kwargs):
    """attn_fn factory matching the transformer's pluggable signature.
    interpret=None auto-selects: real kernel on TPU, interpreter elsewhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, k, v, causal: bool = True):
        return flash_attention(q, k, v, causal=causal, interpret=interpret, **kwargs)

    return attn
