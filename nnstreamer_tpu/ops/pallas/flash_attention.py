"""Flash attention as a Pallas TPU kernel.

The single-chip hot path for the long-context family (models/transformer.py)
— the [T, T] score matrix never leaves VMEM: the grid walks (batch*heads,
q-blocks, k-blocks) with the k dimension innermost ("arbitrary" semantics —
sequential on TPU), carrying the online-softmax running max/denominator/
accumulator in VMEM scratch across k iterations. Q/K/V blocks stream
HBM→VMEM via BlockSpecs (double-buffered by the pallas pipeline); the
s = q·kᵀ and p·v contractions hit the MXU with float32 accumulation
(preferred_element_type), so bfloat16 inputs keep full softmax precision.

Causal masking compares global row/col indices built from program_id;
fully-masked k-blocks are predicated off with @pl.when, so the causal case
does ~half the work. Matches parallel/ring_attention.dense_attention to
float tolerance (tests/test_pallas.py); composes with ring attention by
serving as the per-shard block math (the same online recurrence
ring_attention_local runs per rotation).

The online-softmax recurrence itself lives in ops/pallas/_primitives.py
(shared with the decode and paged-decode kernels); this module owns the
causal/pad masking and the [B, T, H, D] blocking, and registers the
whole launch geometry with ops/pallas/registry.py for nns-kscope.

Layout: [B, T, H, D] like the rest of the framework; internally [B*H, T, D].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas import registry as _registry
from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params
from nnstreamer_tpu.ops.pallas._primitives import (
    NEG_INF,
    online_softmax_finalize,
    online_softmax_init,
    online_softmax_update,
    scaled_qk,
)


def _kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    n_k: int,
    valid_len: Optional[int],
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        online_softmax_init(m_ref, l_ref, acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # predicate off blocks with no live entries: strictly-above-diagonal
    # (causal) and fully-padded (valid_len) ones
    live = True
    if causal:
        live = q_start + block_q - 1 >= k_start
    if valid_len is not None:
        live = jnp.logical_and(live, k_start < valid_len)

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = scaled_qk(q, k, scale)  # [bq, bk]
        if causal or valid_len is not None:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = jnp.ones(s.shape, bool)
            if causal:
                mask = rows >= cols
            if valid_len is not None:
                mask = jnp.logical_and(mask, cols < valid_len)
            s = jnp.where(mask, s, NEG_INF)
        m_ref[:], l_ref[:], acc_ref[:] = online_softmax_update(
            s, v, m_ref[:], l_ref[:], acc_ref[:]
        )

    @pl.when(ki == n_k - 1)
    def _final():
        o_ref[0] = online_softmax_finalize(l_ref[:], acc_ref[:], o_ref.dtype)


# BlockSpec index maps — module-level so the registered LaunchPlan and
# the live pallas_call share the SAME callables (grid (b*h, q, k))
def _q_index_map(i, j, kk):
    return (i, j, 0)


def _kv_index_map(i, j, kk):
    return (i, kk, 0)


def _blocking(t: int, block_q: int, block_k: int):
    """(bq, bk, t_pad, n_q, n_k): T pads up to a block multiple; tiny
    sequences shrink the block (16 floor keeps a sublane-full tile)."""
    bq = min(block_q, max(t, 16))
    bk = min(block_k, max(t, 16))
    blk = max(bq, bk)
    t_pad = -(-t // blk) * blk
    return bq, bk, t_pad, t_pad // bq, t_pad // bk


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """q, k, v: [B, T, H, D] → [B, T, H, D] float32.

    T pads up to a block multiple internally; padded key columns are
    masked to NEG_INF and padded query rows are sliced off on return."""
    b, t, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bq, bk, t_pad, n_q, n_k = _blocking(t, block_q, block_k)

    def to_bh(x):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        if t_pad != t:
            x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
        return x

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        block_q=bq,
        block_k=bk,
        n_k=n_k,
        valid_len=t if t_pad != t else None,
    )

    from jax.experimental.pallas import tpu as pltpu  # lazy: CPU tests interpret

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d), jnp.float32),
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), _q_index_map),
            pl.BlockSpec((1, bk, d), _kv_index_map),
            pl.BlockSpec((1, bk, d), _kv_index_map),
        ],
        out_specs=pl.BlockSpec((1, bq, d), _q_index_map),
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qb, kb, vb)
    return out[:, :t, :].reshape(b, h, t, d).transpose(0, 2, 1, 3)


def make_flash_attention(interpret: Optional[bool] = None, **kwargs):
    """attn_fn factory matching the transformer's pluggable signature.
    interpret=None auto-selects: real kernel on TPU, interpreter
    elsewhere. Each trace consults the registry's dtype support
    (_compat.pallas_ok) and degrades to the dense jnp reference with a
    logged reason instead of a trace-time Mosaic error; the resolved
    choice lands in the dispatch tally as op "flash_attention"."""
    from nnstreamer_tpu.ops.dispatch import record as _record_dispatch
    from nnstreamer_tpu.ops.pallas._compat import pallas_ok

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def attn(q, k, v, causal: bool = True):
        ok, _ = pallas_ok("flash_attention", q.dtype)
        _record_dispatch("flash_attention", "pallas" if ok else "jnp")
        if not ok:
            from nnstreamer_tpu.parallel.ring_attention import dense_attention

            return dense_attention(q, k, v, causal=causal)
        return flash_attention(q, k, v, causal=causal, interpret=interpret, **kwargs)

    return attn


# -- kernel registration (nns-kscope) ----------------------------------------


def _plan(params):
    b = params.get("b", 1)
    t = params["t"]
    h = params.get("h", 2)
    d = params.get("d", 64)
    dtype = params.get("dtype", "float32")
    causal = params.get("causal", True)
    bq, bk, t_pad, n_q, n_k = _blocking(
        t, params.get("block_q", 128), params.get("block_k", 128)
    )
    arr = (b * h, t_pad, d)
    # two MXU contractions (q·kᵀ, p·v), 2·m·n·k flops each; causal
    # predication skips the strictly-above-diagonal half
    flops = 4 * b * h * t_pad * t_pad * d
    if causal:
        flops //= 2
    return _registry.LaunchPlan(
        grid=(b * h, n_q, n_k),
        blocks=(
            _registry.BlockDesc("q", "in", arr, (1, bq, d), dtype, _q_index_map),
            _registry.BlockDesc("k", "in", arr, (1, bk, d), dtype, _kv_index_map),
            _registry.BlockDesc("v", "in", arr, (1, bk, d), dtype, _kv_index_map),
            _registry.BlockDesc("o", "out", arr, (1, bq, d), "float32", _q_index_map),
        ),
        scratch=(
            _registry.ScratchDesc("m", (bq,)),
            _registry.ScratchDesc("l", (bq,)),
            _registry.ScratchDesc("acc", (bq, d)),
        ),
        flops=flops,
        notes="causal: ~half the k blocks predicated off" if causal else "",
    )


def _run_case(params):
    import numpy as np

    from nnstreamer_tpu.parallel.ring_attention import dense_attention

    rng = np.random.default_rng(0)
    b, t = params.get("b", 1), params["t"]
    h, d = params.get("h", 2), params.get("d", 64)
    dtype = jnp.dtype(params.get("dtype", "float32"))
    causal = params.get("causal", True)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32).astype(dtype)
        for _ in range(3)
    )
    got = flash_attention(
        q, k, v, causal=causal,
        block_q=params.get("block_q", 128),
        block_k=params.get("block_k", 128),
        interpret=True,
    )
    want = dense_attention(q, k, v, causal=causal)
    return got, want, (2e-2 if dtype == jnp.bfloat16 else 2e-5)


def _probe():
    import numpy as np

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 16, 1, 8)), jnp.float32)
        for _ in range(3)
    )
    np.asarray(make_flash_attention(interpret=True, block_q=16, block_k=16)(q, k, v))


_registry.register(_registry.KernelSpec(
    name="flash_attention",
    module=__name__,
    ops=("flash_attention",),
    dtypes=("float32", "bfloat16"),
    cases=(
        _registry.ShapeCase(
            "t64-causal",
            {"b": 2, "t": 64, "h": 4, "d": 16, "block_q": 16, "block_k": 16},
            tier1=True,
        ),
        _registry.ShapeCase(
            "t64-full",
            {"b": 2, "t": 64, "h": 4, "d": 16, "block_q": 16, "block_k": 16,
             "causal": False},
        ),
        _registry.ShapeCase(
            "t100-pad-causal",
            {"b": 2, "t": 100, "h": 2, "d": 32, "block_q": 32, "block_k": 32},
            tier1=True,
        ),
        _registry.ShapeCase(
            "t100-pad-full",
            {"b": 2, "t": 100, "h": 2, "d": 32, "block_q": 32, "block_k": 32,
             "causal": False},
        ),
        _registry.ShapeCase(
            "bf16",
            {"b": 2, "t": 64, "h": 4, "d": 16, "block_q": 16, "block_k": 16,
             "dtype": "bfloat16"},
            tier1=True,
        ),
        _registry.ShapeCase(
            "serve-512", {"b": 8, "t": 512, "h": 8, "d": 128},
        ),
    ),
    plan=_plan,
    run_case=_run_case,
    probe=_probe,
))
