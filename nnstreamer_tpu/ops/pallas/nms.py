"""Greedy NMS suppression as a Pallas TPU kernel.

The sequential-suppression half of detection post-processing is the part
XLA handles poorly: the jnp reference (ops/detection.nms) materializes
the full N×N IoU matrix in HBM and walks it with a ``fori_loop``, so the
O(N²) pairwise work is paid in memory traffic before the loop even
starts. Here the kernel keeps the candidate list resident in VMEM as
four coordinate *rows* ([1, N] each — the block-masked layout) and, per
greedy step, computes ONE masked IoU row on the VPU against the live
mask, suppressing in place: no N×N buffer, no HBM round trips between
steps. The argsort ranking and the final top-k packing stay outside in
plain jnp (they're single XLA ops); only the data-dependent suppression
recurrence lives in the kernel.

Interpret-mode CPU fallback per ops/pallas/_compat.py discipline; bit
parity with ops/detection.nms is pinned by tests/test_ops_device.py
(identical ranking, identical suppression predicate, identical packing).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nnstreamer_tpu.ops.pallas import registry as _registry
from nnstreamer_tpu.ops.pallas._compat import compiler_params as _compiler_params


# BlockSpec index map — module-level so the registered LaunchPlan and
# the live pallas_call share the SAME callable (grid (1,): the whole
# candidate list stays VMEM-resident across the greedy recurrence)
def _whole_index_map(i):
    return (0, 0)


def _nms_kernel(coords_ref, scores_ref, alive_ref, *, n: int, n_pad: int,
                thr: float):
    """coords [4, n_pad] rows (x1, y1, x2, y2) of score-ranked boxes,
    scores [1, n_pad] → alive [1, n_pad] float32 0/1 mask."""
    x1 = coords_ref[0:1, :]
    y1 = coords_ref[1:2, :]
    x2 = coords_ref[2:3, :]
    y2 = coords_ref[3:4, :]
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)  # [1, n_pad]
    col = jax.lax.broadcasted_iota(jnp.int32, (1, n_pad), 1)
    alive_ref[:] = (scores_ref[:] > 0.0).astype(jnp.float32)

    def step(i, _):
        # the i-th ranked candidate: scalar corners via a [1,1] slice
        bx1 = coords_ref[0:1, pl.ds(i, 1)]
        by1 = coords_ref[1:2, pl.ds(i, 1)]
        bx2 = coords_ref[2:3, pl.ds(i, 1)]
        by2 = coords_ref[3:4, pl.ds(i, 1)]
        barea = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
        iw = jnp.maximum(
            jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0
        )
        ih = jnp.maximum(
            jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0
        )
        inter = iw * ih
        union = area + barea - inter
        iou = jnp.where(union > 0.0, inter / union, 0.0)
        keep_i = alive_ref[0:1, pl.ds(i, 1)]  # [1,1]: still live?
        alive = alive_ref[:]
        suppress = (
            (iou > thr)
            & (col > i)
            & (keep_i > 0.0)
        )
        alive_ref[:] = jnp.where(suppress, 0.0, alive)
        return 0

    jax.lax.fori_loop(0, n, step, 0)


@functools.partial(
    jax.jit, static_argnames=("iou_threshold", "max_out", "interpret")
)
def nms(
    boxes: jax.Array,
    scores: jax.Array,
    iou_threshold: float,
    max_out: int,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Drop-in for ops/detection.nms: boxes [N,4] x1,y1,x2,y2 + scores
    [N] → (keep_idx [max_out] int32, keep_score [max_out]); empty slots
    score 0 / index -1. Ranking and packing are the reference's exact
    jnp expressions, so the two implementations are bit-comparable."""
    n = boxes.shape[0]
    k = min(max_out, n)
    order = jnp.argsort(-scores)
    sboxes = boxes.astype(jnp.float32)[order]
    sscores = scores.astype(jnp.float32)[order]
    # lane-pad the candidate list; padded columns carry score 0 (never
    # alive, never selected) and zero-area boxes (suppress nothing)
    n_pad = max(128, -(-n // 128) * 128)
    coords = jnp.zeros((4, n_pad), jnp.float32)
    coords = coords.at[:, :n].set(sboxes.T)
    srow = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(sscores)
    kernel = functools.partial(
        _nms_kernel, n=n, n_pad=n_pad, thr=float(iou_threshold)
    )
    if interpret:
        kw = {}
    else:  # pragma: no cover - real-TPU path (CPU tests interpret)
        from jax.experimental.pallas import tpu as pltpu

        kw = {
            "compiler_params": _compiler_params(
                pltpu, dimension_semantics=("arbitrary",)
            ),
        }
    alive_row = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        grid=(1,),
        in_specs=[
            pl.BlockSpec((4, n_pad), _whole_index_map),
            pl.BlockSpec((1, n_pad), _whole_index_map),
        ],
        out_specs=pl.BlockSpec((1, n_pad), _whole_index_map),
        interpret=interpret,
        **kw,
    )(coords, srow)
    alive = alive_row[0, :n] > 0.0
    # packing identical to the jnp reference (bit-comparable selection)
    kept_scores = jnp.where(alive, sscores, 0.0)
    top = jnp.argsort(-kept_scores)[:k]
    sel_scores = kept_scores[top]
    sel_idx = jnp.where(sel_scores > 0, order[top], -1)
    if k < max_out:
        sel_idx = jnp.pad(sel_idx, (0, max_out - k), constant_values=-1)
        sel_scores = jnp.pad(sel_scores, (0, max_out - k))
    # the jnp reference preserves the caller's score dtype (it never
    # casts); match it so impl="auto" traces the same output spec on
    # every backend
    return sel_idx.astype(jnp.int32), sel_scores.astype(scores.dtype)


# -- kernel registration (nns-kscope) ----------------------------------------


def _pad_n(n: int) -> int:
    return max(128, -(-n // 128) * 128)


def _plan(params):
    n = params.get("n", 32)
    n_pad = _pad_n(n)
    return _registry.LaunchPlan(
        grid=(1,),
        blocks=(
            _registry.BlockDesc(
                "coords", "in", (4, n_pad), (4, n_pad), "float32",
                _whole_index_map,
            ),
            _registry.BlockDesc(
                "scores", "in", (1, n_pad), (1, n_pad), "float32",
                _whole_index_map,
            ),
            _registry.BlockDesc(
                "alive", "out", (1, n_pad), (1, n_pad), "float32",
                _whole_index_map,
            ),
        ),
        # one masked IoU row (~12 VPU ops/column) per greedy step
        flops=12 * n * n_pad,
        notes="sequential greedy recurrence; VPU-only (no MXU work)",
    )


def _boxes_scores(params):
    import numpy as np

    rng = np.random.default_rng(9)
    n = params.get("n", 32)
    xy = rng.uniform(0, 60, (n, 2))
    wh = rng.uniform(2, 30, (n, 2))
    boxes = jnp.asarray(np.concatenate([xy, xy + wh], -1), jnp.float32)
    scores = jnp.asarray(rng.uniform(0.05, 1.0, n), jnp.float32)
    return boxes, scores


def _run_case(params):
    from nnstreamer_tpu.ops import detection

    boxes, scores = _boxes_scores(params)
    thr = params.get("thr", 0.5)
    max_out = params.get("max_out", 8)
    got = nms(boxes, scores, thr, max_out, interpret=True)
    want = detection.nms(boxes, scores, thr, max_out, impl="jnp")
    # the two implementations are pinned bit-comparable (same ranking,
    # same suppression predicate, same packing)
    return got, want, 0.0


def _probe():
    import numpy as np

    from nnstreamer_tpu.ops import detection

    boxes = jnp.asarray(
        [[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40], [2, 2, 9, 9]],
        jnp.float32,
    )
    scores = jnp.asarray([0.9, 0.8, 0.7, 0.6], jnp.float32)
    idx, sc = detection.nms(boxes, scores, 0.5, 2, impl="pallas")
    np.asarray(idx), np.asarray(sc)


_registry.register(_registry.KernelSpec(
    name="nms",
    module=__name__,
    ops=("nms",),
    dtypes=("float32", "bfloat16"),
    cases=(
        _registry.ShapeCase("n32", {"n": 32}, tier1=True),
        _registry.ShapeCase("n100-pad128", {"n": 100, "max_out": 16}, tier1=True),
        _registry.ShapeCase("n200-pad256", {"n": 200, "max_out": 32}),
        _registry.ShapeCase("ssd-1917", {"n": 1917, "max_out": 100}),
    ),
    plan=_plan,
    run_case=_run_case,
    probe=_probe,
))
