"""Shared tiling/DMA idioms of the in-tree Pallas kernels.

The three attention kernels (flash, decode, paged decode) are one
online-softmax recurrence specialized to different cache layouts; until
PR 19 each module carried its own copy of the init/update/finalize math.
This module is the single home (the first piece of ROADMAP item 5's
shared primitive layer): pure functions over values — the callers own
their scratch refs and write-back, so the kernels keep their exact
@pl.when predication structure.

Numerics are the originals', bit-for-bit where it matters: f32
accumulation via ``preferred_element_type``, the ``m <= NEG_INF``
guards that keep fully-masked prefixes at weight exactly zero, and the
``l > 0`` guard that zeroes rows nothing attended to.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def scaled_qk(q, k, scale):
    """Scores block ``(q · kᵀ) * scale`` with f32 MXU accumulation.
    q [m, d], k [n, d] → [m, n] float32."""
    return jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale


def dequant_rows(x, scales):
    """Blockwise per-row dequant in VMEM: payload [n, d] × scales [n].
    HBM traffic stays at the quantized byte count — the point of a
    quantized cache."""
    return x * scales[:, None]


def mask_dead_columns(s, v, cols, live_len):
    """Mask score columns at/past ``live_len`` to NEG_INF and zero the
    matching V rows. Dead columns get softmax weight exp(NEG_INF - m)
    = 0, but a pad/scratch block may hold arbitrary V bytes and
    0 * NaN = NaN — zeroing keeps the weighted sum clean."""
    s = jnp.where(cols < live_len, s, NEG_INF)
    v = jnp.where(cols.reshape(-1, 1) < live_len, v, 0.0)
    return s, v


def online_softmax_init(m_ref, l_ref, acc_ref):
    """First-k-step scratch init: running max at NEG_INF (identity of
    max), denominator and accumulator at zero."""
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)


def online_softmax_update(s, v, m_prev, l_prev, acc_prev):
    """One block of the online-softmax recurrence.

    s [m, n] f32 scores, v [n, d] f32 values; (m_prev [m], l_prev [m],
    acc_prev [m, d]) the running (max, denominator, accumulator) →
    the updated triple. The ``<= NEG_INF`` guards pin fully-masked
    prefixes to weight exactly zero (exp(NEG_INF - NEG_INF) would be 1)."""
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    alpha = jnp.where(m_prev <= NEG_INF, 0.0, jnp.exp(m_prev - m_new))
    m2 = m_new[:, None]
    p = jnp.where(m2 <= NEG_INF, 0.0, jnp.exp(s - m2))
    l_new = l_prev * alpha + jnp.sum(p, axis=1)
    acc_new = acc_prev * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def online_softmax_finalize(l, acc, dtype):
    """Normalize the accumulator by the denominator; rows nothing
    attended to (l == 0) come out exactly zero instead of 0/0."""
    l2 = l[:, None]
    return jnp.where(l2 > 0, acc / jnp.maximum(l2, 1e-30), 0.0).astype(dtype)
