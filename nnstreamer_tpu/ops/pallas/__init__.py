"""Hand-written Pallas TPU kernels for the hot ops.

XLA fuses most of the pipeline (SURVEY.md §7 design mapping); these kernels
cover the cases where explicit VMEM blocking beats the fusion XLA picks —
flash attention, the serving decode kernels (contiguous and paged cache
layouts), and the pre/post-processing set (docs/on-device-ops.md):
MXU bilinear crop/resize with a fused normalize epilogue, and the greedy
NMS suppression recurrence. Every kernel has an ``interpret=True`` path so
the CPU test mesh exercises the same code the TPU runs.

Importing this package registers every kernel's :class:`KernelSpec` with
:mod:`~nnstreamer_tpu.ops.pallas.registry` (the nns-kscope substrate:
grid/BlockSpec geometry, dtype support, jnp reference, shape grid).
"""

from nnstreamer_tpu.ops.pallas.decode_attention import (  # noqa: F401
    decode_attention,
)
from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
from nnstreamer_tpu.ops.pallas.image_kernels import (  # noqa: F401
    crop_and_resize,
    resize_bilinear,
)
from nnstreamer_tpu.ops.pallas.nms import nms  # noqa: F401
from nnstreamer_tpu.ops.pallas.paged_attention import (  # noqa: F401
    paged_decode_attention,
)
