"""Hand-written Pallas TPU kernels for the hot ops.

XLA fuses most of the pipeline (SURVEY.md §7 design mapping); these kernels
cover the cases where explicit VMEM blocking beats the fusion XLA picks —
flash attention first. Every kernel has an ``interpret=True`` path so the
CPU test mesh exercises the same code the TPU runs.
"""

from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
