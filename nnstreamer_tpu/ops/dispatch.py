"""Kernel dispatch accounting: which implementation each dual-path op
actually chose.

Every op with a Pallas TPU kernel and a jnp fallback (ops/image.py
crop/resize, ops/detection.py NMS, kv/block_attn.py block attention,
the serving model's attention constructors) resolves ``impl="auto"`` at
trace/build time. Until now that decision was invisible — a pipeline
could silently run the fallback on TPU (or vice versa) with nothing to
prove which kernel engaged. This module is the proof: each dispatch
site records its (op, impl) choice into a process-local tally that
``nns-xray --dispatch`` diffs around tiny probe invocations
(docs/chain-analysis.md "Kernel dispatch"), and tests pin.

Recording happens at TRACE time (inside the op wrapper, outside any
jit), so counts measure program builds, not per-frame calls — exactly
the "did the kernel engage" evidence wanted, at zero hot-path cost.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

# -- dispatch tally ---------------------------------------------------------

class DispatchTally:
    """Process-local (op, impl) counters; every mutation under the one
    lock (the nns-san shared-counter discipline — dispatch sites run on
    whichever thread traces first)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str], int] = {}

    def record(self, op: str, impl: str) -> None:
        with self._lock:
            key = (str(op), str(impl))
            self._counts[key] = self._counts.get(key, 0) + 1

    def snapshot(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


tally = DispatchTally()

#: every op name a dual-path dispatch site records — the closed set
#: nns-kscope's registry↔tally agreement check (analysis/selfcheck.py
#: kscope_self_check) and bench.py's --capture-tpu schema enumerate.
#: Adding a dispatch site means adding its op here AND covering it from
#: a registered KernelSpec's ``ops`` tuple (ops/pallas/registry.py).
KNOWN_OPS = (
    "block_attention",
    "crop_and_resize",
    "decode_attention",
    "flash_attention",
    "nms",
    "resize_bilinear",
    "serving_attention",
)


def record(op: str, impl: str) -> None:
    """One dispatch decision: ``op`` resolved to ``impl`` ("pallas" or
    "jnp"/"xla"). Call at the branch point, with the RESOLVED impl —
    never "auto"."""
    tally.record(op, impl)


def engaged_impls(op: str, since: Dict[Tuple[str, str], int]) -> list:
    """Impls ``op`` dispatched to since the ``since`` snapshot, sorted
    (the nns-xray --dispatch measurement primitive)."""
    now = tally.snapshot()
    return sorted(
        impl
        for (o, impl), n in now.items()
        if o == op and n > since.get((o, impl), 0)
    )
