"""Jittable image ops: fixed-shape crop+resize for on-device cascades.

The reference composes detector→crop→second-model cascades through
tensor_crop (gsttensor_crop.c), whose outputs are *variable-size* host
buffers — every frame crosses the host and each crop size retriggers
downstream negotiation. The TPU-first alternative: crop and resample to a
canonical size inside the same XLA program (fixed shapes, MXU-friendly),
so a whole detect→crop→landmark cascade is ONE program with zero host
hops (see models/face_pipeline.apply_composite).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def crop_and_resize(image, boxes, out_h: int, out_w: int):
    """Bilinear crop+resize (TF crop_and_resize semantics, pixel boxes).

    image: [H, W, C] float; boxes: [N, 4] (x1, y1, x2, y2) in pixel
    coordinates (any float dtype; degenerate boxes clamp to edge pixels)
    → [N, out_h, out_w, C], image dtype.
    """
    h, w, _ = image.shape
    boxes = boxes.astype(jnp.float32)

    def one(box):
        x1, y1, x2, y2 = box
        # sample at output-pixel centers mapped into the box
        ys = y1 + (y2 - y1) * (jnp.arange(out_h, dtype=jnp.float32) + 0.5) / out_h - 0.5
        xs = x1 + (x2 - x1) * (jnp.arange(out_w, dtype=jnp.float32) + 0.5) / out_w - 0.5
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        y0i = jnp.clip(y0, 0, h - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x0i = jnp.clip(x0, 0, w - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        top = image[y0i][:, x0i] * (1 - wx)[None, :, None] + \
            image[y0i][:, x1i] * wx[None, :, None]
        bot = image[y1i][:, x0i] * (1 - wx)[None, :, None] + \
            image[y1i][:, x1i] * wx[None, :, None]
        return top * (1 - wy)[:, None, None] + bot * wy[:, None, None]

    return jax.vmap(one)(boxes).astype(image.dtype)
