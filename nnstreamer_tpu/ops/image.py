"""Jittable image ops: fixed-shape crop+resize for on-device cascades.

The reference composes detector→crop→second-model cascades through
tensor_crop (gsttensor_crop.c), whose outputs are *variable-size* host
buffers — every frame crosses the host and each crop size retriggers
downstream negotiation. The TPU-first alternative: crop and resample to a
canonical size inside the same XLA program (fixed shapes, MXU-friendly),
so a whole detect→crop→landmark cascade is ONE program with zero host
hops (see models/face_pipeline.apply_composite).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nnstreamer_tpu.ops.dispatch import record as _record_dispatch


def _use_pallas(impl: str, op: str = "", dtype=None) -> bool:
    """Implementation pick for the image ops: ``auto`` takes the Pallas
    kernel on a real TPU backend (MXU-blocked resampling,
    ops/pallas/image_kernels.py) and the jnp expression elsewhere (the
    interpreter would be a pessimization on the CPU hot path; interpret
    mode stays a parity-test tool). A pallas pick is re-checked against
    the kernel registry's dtype support (_compat.pallas_ok) — an
    unsupported dtype degrades to jnp with a logged reason instead of a
    trace-time error. A non-empty ``op`` records the resolved choice in
    the dispatch tally (ops/dispatch.py) so ``nns-xray --dispatch`` can
    prove which kernel engaged."""
    if impl == "pallas":
        use = True
    elif impl == "jnp":
        use = False
    elif impl != "auto":
        raise ValueError(f"image op impl {impl!r} not auto/jnp/pallas")
    else:
        use = jax.default_backend() == "tpu"
    if use:
        from nnstreamer_tpu.ops.pallas._compat import pallas_ok

        use, _ = pallas_ok(op or "image", dtype)
    if op:
        _record_dispatch(op, "pallas" if use else "jnp")
    return use


def crop_and_resize(image, boxes, out_h: int, out_w: int, impl: str = "auto"):
    """Bilinear crop+resize (TF crop_and_resize semantics, pixel boxes).

    image: [H, W, C] float; boxes: [N, 4] (x1, y1, x2, y2) in pixel
    coordinates (any float dtype; degenerate boxes clamp to edge pixels)
    → [N, out_h, out_w, C], image dtype.
    """
    if _use_pallas(impl, op="crop_and_resize", dtype=image.dtype):
        from nnstreamer_tpu.ops.pallas.image_kernels import (
            crop_and_resize as pallas_crop,
        )

        # explicit impl=pallas off-TPU runs the interpreter (parity
        # tests); auto never picks it there
        return pallas_crop(
            image, boxes, out_h, out_w,
            interpret=jax.default_backend() != "tpu",
        )
    h, w, _ = image.shape
    boxes = boxes.astype(jnp.float32)

    def one(box):
        x1, y1, x2, y2 = box
        # sample at output-pixel centers mapped into the box
        ys = y1 + (y2 - y1) * (jnp.arange(out_h, dtype=jnp.float32) + 0.5) / out_h - 0.5
        xs = x1 + (x2 - x1) * (jnp.arange(out_w, dtype=jnp.float32) + 0.5) / out_w - 0.5
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        y0i = jnp.clip(y0, 0, h - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x0i = jnp.clip(x0, 0, w - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        top = image[y0i][:, x0i] * (1 - wx)[None, :, None] + \
            image[y0i][:, x1i] * wx[None, :, None]
        bot = image[y1i][:, x0i] * (1 - wx)[None, :, None] + \
            image[y1i][:, x1i] * wx[None, :, None]
        return top * (1 - wy)[:, None, None] + bot * wy[:, None, None]

    return _round_clip_cast(jax.vmap(one)(boxes), image.dtype)


def _round_clip_cast(x, dtype):
    """Cast crop/resize output to ``dtype`` with the tensor_crop
    convention for integers: round + clip to the dtype's own range (a
    truncating astype would make integer results backend-dependent,
    and 0..255 would wrap int8 / clamp valid uint16). The ONE home of
    this epilogue — the Pallas kernel mirrors it in-kernel."""
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        x = jnp.clip(jnp.round(x), info.min, info.max)
    return x.astype(dtype)


def crop_regions(image, xyxy, out_h: int, out_w: int, valid=None,
                 out_dtype=None, impl: str = "auto"):
    """Crop+resize with the tensor_crop output conventions shared by
    ``tensor_crop out-size=`` and ``tensor_transform mode=crop-resize``
    (docs/on-device-ops.md): compute in float32, zero the rows where
    ``valid`` is False (zero-size regions, below-threshold detections),
    and round+clip integer outputs. image [H, W, C]; xyxy [N, 4] pixel
    corners; out_dtype defaults to the image dtype."""
    crops = crop_and_resize(
        image.astype(jnp.float32), xyxy, out_h, out_w, impl=impl
    )
    if valid is not None:
        crops = jnp.where(valid[:, None, None, None], crops, 0.0)
    return _round_clip_cast(
        crops, image.dtype if out_dtype is None else out_dtype
    )


def resize_bilinear(image, out_h: int, out_w: int, impl: str = "auto"):
    """Whole-image bilinear resize: [N, H, W, C] or [H, W, C] → same
    rank with the spatial dims replaced. Same sampling grid as
    crop_and_resize over the full-image box, so the element-level
    resize (tensor_transform mode=resize) and the crop path can't
    drift apart numerically."""
    squeeze = image.ndim == 3
    img = image[None] if squeeze else image
    if _use_pallas(impl, op="resize_bilinear", dtype=img.dtype):
        from nnstreamer_tpu.ops.pallas.image_kernels import (
            resize_bilinear as pallas_resize,
        )

        out = pallas_resize(
            img, out_h, out_w,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        _, h, w, _ = img.shape
        box = jnp.asarray([[0.0, 0.0, float(w), float(h)]], jnp.float32)

        def one(im):
            return crop_and_resize(im, box, out_h, out_w, impl="jnp")[0]

        out = jax.vmap(one)(img)
    return out[0] if squeeze else out
