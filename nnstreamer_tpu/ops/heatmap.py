"""Jittable heatmap post-processing: pose keypoints + segmentation argmax.

TPU-native counterparts of the reference's pose decoder keypoint scan
(ext/nnstreamer/tensor_decoder/tensordec-pose.c, modes heatmap-only /
heatmap-offset) and the image-segment decoder's per-pixel argmax
(ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c, tflite-deeplab).
The reference walks the heatmap grid per keypoint in C; here the reductions
are single XLA ops that can fuse with the model's last layers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def pose_keypoints_from_heatmap(heatmap: jax.Array) -> jax.Array:
    """heatmap-only mode: [H, W, K] score maps → [K, 3] (x, y, score) in
    heatmap-grid units. Scores pass through sigmoid as in the reference
    (posenet emits logits)."""
    h, w, k = heatmap.shape
    hm = heatmap.astype(jnp.float32).reshape(h * w, k)
    idx = jnp.argmax(hm, axis=0)
    score = jax.nn.sigmoid(jnp.max(hm, axis=0))
    y = (idx // w).astype(jnp.float32)
    x = (idx % w).astype(jnp.float32)
    return jnp.stack([x, y, score], axis=-1)


@jax.jit
def pose_keypoints_with_offsets(
    heatmap: jax.Array, offsets: jax.Array
) -> jax.Array:
    """heatmap-offset mode: refine grid argmax with the offset tensor
    [H, W, 2K] (first K channels = y offsets, last K = x offsets, posenet
    convention). Returns [K, 5] rows (grid_x, grid_y, score, off_x, off_y):
    grid coords plus raw pixel offsets — the caller applies
    stride = (input-1)/(grid-1) and adds the offsets (see PoseDecoder)."""
    h, w, k = heatmap.shape
    base = pose_keypoints_from_heatmap(heatmap)
    ys = base[:, 1].astype(jnp.int32)
    xs = base[:, 0].astype(jnp.int32)
    koff = jnp.arange(k)
    off_y = offsets.astype(jnp.float32)[ys, xs, koff]
    off_x = offsets.astype(jnp.float32)[ys, xs, koff + k]
    return jnp.stack([base[:, 0], base[:, 1], base[:, 2], off_x, off_y], axis=-1)


@functools.partial(jax.jit, static_argnames=("num_labels",))
def segment_argmax(seg: jax.Array, num_labels: int = 21) -> jax.Array:
    """tflite-deeplab: [H, W, C] class scores → [H, W] uint8 label map.
    A [H, W] map (already argmaxed, snpe-deeplab mode) passes through."""
    s = seg
    if s.ndim == 3 and s.shape[-1] > 1:
        return jnp.argmax(s.astype(jnp.float32), axis=-1).astype(jnp.uint8)
    return s.reshape(s.shape[0], s.shape[1]).astype(jnp.uint8)


@jax.jit
def depth_normalize(depth: jax.Array) -> jax.Array:
    """snpe-depth: [H, W] float depth → uint8 grayscale via min-max
    normalization (reference MODE_SNPE_DEPTH rendering)."""
    d = depth.astype(jnp.float32).reshape(depth.shape[0], depth.shape[1])
    lo = jnp.min(d)
    hi = jnp.max(d)
    return ((d - lo) / jnp.maximum(hi - lo, 1e-9) * 255.0).astype(jnp.uint8)
