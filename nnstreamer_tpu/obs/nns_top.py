"""nns-top: live per-element console view of a running pipeline.

The in-tree answer to watching GstShark dashboards: a top(1)-style table
refreshed in place, one row per pipeline element —

    ELEMENT        FPS  FRAMES  P50ms  P99ms  Q  BATCH  PAD%  ERR  NOTES

Data sources (pick one):

- ``nns-top http://host:9464`` — poll a live ``/metrics.json`` endpoint
  (``[executor] metrics_port`` / ``NNS_TPU_METRICS_PORT``).
- ``nns-top out.json`` — render a one-shot snapshot file
  (``nns-launch --metrics out.json``), re-reading it each interval.
- in-process: ``nns_top.watch(executor)`` renders the same table from a
  live :class:`~nnstreamer_tpu.pipeline.executor.Executor` without any
  HTTP hop (notebooks, tests).

FPS is computed by differencing ``frames`` between polls when a
previous snapshot exists (the live rate), falling back to each row's
cumulative ``fps`` field (which includes compile/warmup).

``--clients`` switches to the per-client admission view (one row per
query-server client: queued/inflight/admitted/rejected, plus reject
reasons — docs/edge-serving.md).

``--fleet`` switches to the per-endpoint fleet view (one row per
fleet-client endpoint: state/score/inflight/failovers from the health
scorer, plus each query server's drain readiness flag —
docs/edge-serving.md "Running a fleet").

``--models`` switches to the per-plane serving view (one row per
serving plane: mode/devices, attached streams, cross-stream queue
depth, dispatches, batch occupancy — plus a per-stream admit/serve
footer; docs/serving-plane.md).

``--requests`` switches to the per-request LLM serving view (one row
per request of a continuous batcher: state, KV blocks held, queue/
TTFT/TPOT latencies, deadline headroom — docs/llm-serving.md).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, Optional

_COLUMNS = (
    ("ELEMENT", 22), ("FPS", 8), ("FRAMES", 9), ("P50ms", 8),
    ("P99ms", 8), ("WAITms", 8), ("Q", 5), ("BATCH", 7), ("PAD%", 6),
    ("ERR", 5), ("NOTES", 0),
)


def _num(row: dict, key: str, nd: int = 1) -> str:
    v = row.get(key)
    if v is None:
        return "-"
    return f"{v:.{nd}f}" if isinstance(v, float) else str(v)


def _notes(row: dict) -> str:
    """Compressed per-row flags: retry/circuit-breaker state from
    FaultStats/cb_* counters, admission/shedding counters, sanitizer
    findings, serving counters."""
    notes = []
    if row.get("error_retries"):
        notes.append(f"retry={row['error_retries']}")
    if row.get("error_routed"):
        notes.append(f"routed={row['error_routed']}")
    if row.get("adm_rejected"):
        notes.append(f"rej={row['adm_rejected']}")
    if row.get("adm_inflight"):
        notes.append(f"infl={row['adm_inflight']}")
    if row.get("deadline_shed"):
        notes.append(f"shed={row['deadline_shed']}")
    if row.get("cb_opens"):
        state = "OPEN" if row.get("cb_open") else "closed"
        notes.append(f"cb={state}({row['cb_opens']})")
    if row.get("fused_postproc"):
        # pre/post-processing ops fused into this device segment
        # (docs/on-device-ops.md)
        notes.append("fused-post")
    if row.get("chain_segments"):
        # whole-chain resident program: segments collapsed into one
        # node, one launch per unrolled window; `!` marks a chain
        # serving from the per-node parity path after a fallback latch
        # (docs/chain-analysis.md "Compiled chains")
        mark = "!" if row.get("chain_fallback_windows") else ""
        notes.append(
            f"chain={row['chain_segments']}x{row.get('chain_unroll', 1)}"
            f"{mark}"
        )
    san = {k: v for k, v in row.items() if k.startswith("san_") and v}
    for k, v in sorted(san.items()):
        notes.append(f"{k}={v}")
    serving = {
        k: v for k, v in row.items() if k.startswith("serving_") and v
    }
    if serving:
        notes.append("serving")
    return " ".join(notes)


def render(
    snap: dict,
    prev: Optional[dict] = None,
    interval_s: Optional[float] = None,
) -> str:
    """One table frame from a ``/metrics.json``-shaped snapshot.
    ``prev`` + ``interval_s`` turn cumulative frame counts into live
    rates; without them the cumulative ``fps`` field is shown."""
    nodes: Dict[str, dict] = snap.get("nodes", {})
    prev_nodes = (prev or {}).get("nodes", {})
    lines = []
    head = "".join(
        name.ljust(w) if w else name for name, w in _COLUMNS
    )
    lines.append(head)
    lines.append("-" * max(len(head), 72))
    for name, row in nodes.items():
        if name.startswith("_"):
            continue  # the __pipeline__ totals row is footer material
        fps = row.get("fps")
        if interval_s and name in prev_nodes:
            df = row.get("frames", 0) - prev_nodes[name].get("frames", 0)
            fps = df / interval_s if interval_s > 0 else fps
        depth = row.get("queue_depth")
        cells = [
            name[:21],
            f"{fps:.1f}" if isinstance(fps, (int, float)) else "-",
            str(row.get("frames", "-")),
            _num(row, "latency_p50_ms", 2),
            _num(row, "latency_p99_ms", 2),
            _num(row, "queue_wait_p50_ms", 2),
            str(sum(depth)) if depth else "-",
            _num(row, "avg_batch_size"),
            _num(row, "pad_waste_pct"),
            str(row.get("errors", 0) or "-"),
            _notes(row),
        ]
        lines.append("".join(
            c.ljust(w) if w else c for c, (_, w) in zip(cells, _COLUMNS)
        ))
    totals = snap.get("totals") or {}
    if totals:
        lines.append("")
        lines.append(
            f"produced={totals.get('produced')} "
            f"rendered={totals.get('rendered')} "
            f"dropped={sum((totals.get('dropped') or {}).values())} "
            f"balance={totals.get('balance')}"
        )
    proc = snap.get("process")
    if proc:
        lines.append(f"[{proc}]")
    return "\n".join(lines)


_CLIENT_COLUMNS = (
    ("SERVER", 22), ("CLIENT", 14), ("QUEUED", 8), ("INFLIGHT", 10),
    ("ADMITTED", 10), ("REJECTED", 0),
)


def render_clients(snap: dict) -> str:
    """The ``--clients`` view: one row per (query server, client) from
    the admission controller's per-client counters (docs/
    edge-serving.md), plus a per-server footer with the reject reasons.
    Empty when no node in the snapshot serves an admission-controlled
    fleet."""
    nodes: Dict[str, dict] = snap.get("nodes", {})
    lines = []
    head = "".join(
        name.ljust(w) if w else name for name, w in _CLIENT_COLUMNS
    )
    for name, row in nodes.items():
        clients = row.get("adm_clients")
        if not isinstance(clients, dict):
            continue
        if not lines:
            lines.append(head)
            lines.append("-" * max(len(head), 64))
        for cid, c in sorted(clients.items()):
            cells = [
                name[:21], str(cid)[:13], str(c.get("queued", 0)),
                str(c.get("inflight", 0)), str(c.get("admitted", 0)),
                str(c.get("rejected", 0)),
            ]
            lines.append("".join(
                v.ljust(w) if w else v
                for v, (_, w) in zip(cells, _CLIENT_COLUMNS)
            ))
        footer = []
        reasons = row.get("adm_rejected_by_reason") or {}
        for reason, count in sorted(reasons.items()):
            footer.append(f"{reason}={count}")
        if row.get("adm_rejected_conns"):
            footer.append(f"conn-rejects={row['adm_rejected_conns']}")
        if footer:
            lines.append(f"  {name}: " + " ".join(footer))
    if not lines:
        return "(no admission-controlled query server in this snapshot)"
    return "\n".join(lines)


_FLEET_COLUMNS = (
    ("CLIENT", 20), ("ENDPOINT", 22), ("STATE", 10), ("SCORE", 7),
    ("INFL", 6), ("SERVED", 8), ("FAILS", 7), ("FAILOVER", 0),
)


def render_fleet(snap: dict) -> str:
    """The ``--fleet`` view: one row per (fleet client, endpoint) from
    the client's health scorer (``fleet_endpoints`` in its stats row —
    docs/edge-serving.md "Running a fleet"), plus a per-client footer
    with the failover/hedge/duplicate totals (plus prefix-route hit/
    index counts when the client routes by prompt prefix) — and a row
    per query SERVER advertising its drain readiness flag or its
    disaggregated-serving role with handoff-outcome counts. Empty when
    nothing in the snapshot serves a fleet."""
    nodes: Dict[str, dict] = snap.get("nodes", {})
    lines = []
    head = "".join(
        name.ljust(w) if w else name for name, w in _FLEET_COLUMNS
    )
    for name, row in nodes.items():
        eps = row.get("fleet_endpoints")
        if not isinstance(eps, dict):
            continue
        if not lines:
            lines.append(head)
            lines.append("-" * max(len(head), 72))
        for addr, e in sorted(eps.items()):
            cells = [
                name[:19], str(addr)[:21],
                str(e.get("state", "-"))[:9],
                _num(e, "score", 2),
                str(e.get("inflight", 0)),
                str(e.get("served", 0)),
                str(e.get("fails", 0)),
                str(e.get("failovers", 0))
                + (" unresolvable" if e.get("unresolvable") else ""),
            ]
            lines.append("".join(
                c.ljust(w) if w else c
                for c, (_, w) in zip(cells, _FLEET_COLUMNS)
            ))
        footer = [
            f"healthy={row.get('fleet_healthy', '-')}",
            f"failovers={row.get('fleet_failovers', 0)}",
            f"hedges={row.get('fleet_hedges', 0)}",
            f"dup-replies={row.get('fleet_duplicate_replies', 0)}",
        ]
        if row.get("fleet_stale_replies"):
            footer.append(f"stale={row['fleet_stale_replies']}")
        if row.get("fleet_prefix_hits") is not None:
            # prefix-route=true clients: cache-affinity routing wins
            # and how many prompt prefixes the router currently maps
            footer.append(f"prefix-hits={row['fleet_prefix_hits']}")
            footer.append(f"prefix-index={row.get('fleet_prefix_index', 0)}")
        lines.append(f"  {name}: " + " ".join(footer))
    # server half: the drain/rolling-restart readiness flags
    for name, row in nodes.items():
        readiness = row.get("adm_readiness")
        if readiness is None:
            continue
        extra = (
            f" drain-nacked={row['adm_drain_nacked']}"
            if row.get("adm_drain_nacked") else ""
        )
        lines.append(f"  server {name}: {readiness}{extra}")
    # disaggregated-serving roles (docs/llm-serving.md "Disaggregated
    # serving"): a prefill server's handoff outcomes / a decode
    # server's parked finished handoffs
    for name, row in nodes.items():
        role = row.get("serving_disagg_role")
        if not role:
            continue
        parts = [f"role={role}"]
        counts = (row.get("serving_disagg") or {}).get("counts") or {}
        parts.extend(f"{k}={v}" for k, v in sorted(counts.items()))
        if (row.get("serving_disagg") or {}).get("outstanding"):
            parts.append(
                f"outstanding={row['serving_disagg']['outstanding']}"
            )
        if row.get("serving_disagg_done_waiting"):
            parts.append(
                f"done-waiting={row['serving_disagg_done_waiting']}"
            )
        lines.append(f"  server {name}: " + " ".join(parts))
    if not lines:
        return "(no fleet client in this snapshot)"
    return "\n".join(lines)


_MODEL_COLUMNS = (
    ("PLANE", 16), ("MODE", 10), ("DEV", 5), ("STREAMS", 9),
    ("Q", 5), ("INFL", 6), ("DISP", 8), ("BATCH", 7), ("OCC%", 7),
    ("FRAMES", 0),
)


def render_models(snap: dict) -> str:
    """The ``--models`` view: one row per serving plane from the
    ``plane_*`` stats the attached filters surface (multiple sharers
    report the same plane — deduped by name), plus a per-stream
    admit/serve footer. Empty when nothing in the snapshot serves
    through a plane."""
    nodes: Dict[str, dict] = snap.get("nodes", {})
    lines = []
    head = "".join(
        name.ljust(w) if w else name for name, w in _MODEL_COLUMNS
    )
    seen = set()
    for _name, row in nodes.items():
        pname = row.get("plane_name")
        if not pname or pname in seen:
            continue
        seen.add(pname)
        if not lines:
            lines.append(head)
            lines.append("-" * max(len(head), 64))
        cells = [
            str(pname)[:15],
            str(row.get("plane_mode", "-")),
            str(row.get("plane_devices", "-")),
            str(row.get("plane_streams", "-")),
            str(row.get("plane_queue_depth", "-")),
            # async in-flight windows parked across the plane's stream
            # rings (docs/serving-plane.md); 0/- under blocking submits
            str(row.get("plane_inflight", "-")),
            str(row.get("plane_dispatches", "-")),
            _num(row, "plane_avg_batch"),
            _num(row, "plane_occupancy_pct"),
            str(row.get("plane_frames", "-")),
        ]
        lines.append("".join(
            c.ljust(w) if w else c
            for c, (_, w) in zip(cells, _MODEL_COLUMNS)
        ))
        per_stream = row.get("plane_per_stream")
        if isinstance(per_stream, dict):
            for sid, s in sorted(per_stream.items()):
                lines.append(
                    f"  {str(sid)[:20]}: admitted={s.get('admitted', 0)} "
                    f"served={s.get('served', 0)} "
                    f"queued={s.get('queued', 0)} "
                    f"inflight={s.get('inflight', 0)} "
                    f"errors={s.get('errors', 0)} "
                    f"weight={s.get('weight', 1.0)}"
                )
        reps = row.get("plane_replicas")
        if isinstance(reps, dict):
            lines.append(
                f"  replicas: healthy={reps.get('healthy')}/"
                f"{reps.get('replicas')} "
                f"failovers={reps.get('failovers', 0)} "
                f"exhaustions={reps.get('exhaustions', 0)}"
            )
    if not lines:
        return "(no serving plane in this snapshot)"
    return "\n".join(lines)


_REQUEST_COLUMNS = (
    ("ELEMENT", 20), ("RID", 6), ("STATE", 12), ("BLOCKS", 8),
    ("QUEUEms", 9), ("TTFTms", 9), ("TPOTms", 9), ("TOKENS", 8),
    ("DEADLINE", 0),
)


def render_requests(snap: dict) -> str:
    """The ``--requests`` view: one row per live/recent request of an
    LLM serving element, from the batcher's SLO ledger
    (``serving_requests`` in the element's stats row —
    docs/llm-serving.md). Empty when nothing in the snapshot serves an
    LLM batch."""
    nodes: Dict[str, dict] = snap.get("nodes", {})
    lines = []
    head = "".join(
        name.ljust(w) if w else name for name, w in _REQUEST_COLUMNS
    )

    def _ms(row, key):
        v = row.get(key)
        return f"{v:.1f}" if isinstance(v, (int, float)) else "-"

    for name, row in nodes.items():
        reqs = row.get("serving_requests")
        if not isinstance(reqs, dict) or not reqs:
            continue
        if not lines:
            lines.append(head)
            lines.append("-" * max(len(head), 72))
        for rid in sorted(reqs, key=int):
            r = reqs[rid]
            dl = r.get("deadline_s")
            cells = [
                name[:19], str(rid), str(r.get("state", "-"))[:11],
                str(r.get("blocks", "-")),
                _ms(r, "queue_ms"), _ms(r, "ttft_ms"), _ms(r, "tpot_ms"),
                str(r.get("tokens", "-")),
                (f"{dl:+.1f}s" if isinstance(dl, (int, float)) else "-"),
            ]
            lines.append("".join(
                c.ljust(w) if w else c
                for c, (_, w) in zip(cells, _REQUEST_COLUMNS)
            ))
        pre = row.get("serving_kv_preemptions")
        blocks = row.get("serving_kv_blocks_in_use")
        footer = []
        if blocks is not None:
            footer.append(
                f"blocks={blocks}/{row.get('serving_kv_blocks', '?')}"
            )
        if row.get("serving_kv_attn"):
            # which paged decode path is live: block (arena attended
            # through the tables) or gather (the materialized-view
            # oracle — the dispatch count shows what it is costing)
            footer.append(f"kv-attn={row['serving_kv_attn']}")
            if row.get("serving_kv_gather_dispatches"):
                footer.append(
                    "gather-dispatches="
                    f"{row['serving_kv_gather_dispatches']}"
                )
        if row.get("serving_kv_prefix_hits"):
            footer.append(f"prefix-hits={row['serving_kv_prefix_hits']}")
        if pre:
            footer.append(f"preemptions={pre}")
        # live migration + crash recovery (docs/llm-serving.md
        # "Migration & recovery"): spans shipped out / adopted in, and
        # requests resumed (re-prefill fallback or checkpoint restart);
        # migrated requests also show as state=migrated in the rows
        if row.get("serving_kv_migrations_out") or row.get(
            "serving_kv_migrations_in"
        ):
            footer.append(
                "migrations="
                f"{row.get('serving_kv_migrations_out', 0)}out/"
                f"{row.get('serving_kv_migrations_in', 0)}in"
            )
        if row.get("serving_request_resumes"):
            footer.append(f"resumes={row['serving_request_resumes']}")
        if footer:
            lines.append(f"  {name}: " + " ".join(footer))
    if not lines:
        return "(no LLM serving element in this snapshot)"
    return "\n".join(lines)


def _fetch(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        url = source.rstrip("/")
        if not url.endswith(".json"):
            if url.endswith("/metrics"):
                # the executor logs the /metrics (Prometheus) URL;
                # pasting it here must land on the JSON sibling
                url = url[: -len("/metrics")]
            url += "/metrics.json"
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        return json.load(f)


def snapshot_executor(ex) -> dict:
    """In-process snapshot from a live Executor (no HTTP hop)."""
    from nnstreamer_tpu.obs import expo, metrics

    return expo.snapshot(metrics.get(), ex.stats(), ex.totals())


def watch(ex, interval_s: float = 1.0, iterations: Optional[int] = None,
          out=None) -> None:
    """Render an in-process executor's table every ``interval_s`` until
    the pipeline finishes (or ``iterations`` frames of output)."""
    out = out or sys.stdout
    prev = None
    n = 0
    while iterations is None or n < iterations:
        snap = snapshot_executor(ex)
        out.write("\x1b[2J\x1b[H" if out.isatty() else "")
        out.write(render(snap, prev, interval_s if prev else None) + "\n")
        out.flush()
        if ex.finished or (ex.stop_event.is_set() and ex.errors):
            break
        prev = snap
        n += 1
        time.sleep(interval_s)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-top", description=__doc__)
    ap.add_argument(
        "source",
        help="metrics endpoint URL (http://host:port) or snapshot file",
    )
    ap.add_argument("--interval", "-n", type=float, default=1.0,
                    help="refresh period, seconds (default 1)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripting)")
    ap.add_argument("--clients", action="store_true",
                    help="per-client admission view (query servers)")
    ap.add_argument("--fleet", action="store_true",
                    help="per-endpoint fleet view (query clients + "
                    "server readiness)")
    ap.add_argument("--models", action="store_true",
                    help="per-plane serving view (shared model planes)")
    ap.add_argument("--requests", action="store_true",
                    help="per-request LLM serving view (SLO ledger)")
    args = ap.parse_args(argv)

    prev = None
    prev_t = None
    while True:
        try:
            snap = _fetch(args.source)
        except (OSError, ValueError) as exc:
            print(f"nns-top: {args.source}: {exc}", file=sys.stderr)
            return 1
        now = time.monotonic()
        dt = (now - prev_t) if prev_t is not None else None
        if not args.once and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        if args.clients:
            print(render_clients(snap))
        elif args.fleet:
            print(render_fleet(snap))
        elif args.models:
            print(render_models(snap))
        elif args.requests:
            print(render_requests(snap))
        else:
            print(render(snap, prev, dt))
        if args.once:
            return 0
        prev, prev_t = snap, now
        try:
            time.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
