"""Metric exposition: Prometheus text format, JSON snapshots, HTTP server.

Three consumers, one data model (:class:`~.metrics.MetricsRegistry`):

- ``/metrics`` — Prometheus text exposition format 0.0.4 (the scrape
  target). Histograms render cumulative ``_bucket{le=...}`` series over
  the log ladder's upper edges; empty buckets are elided (``le`` labels
  are arbitrary as long as counts stay cumulative), so a 112-rung
  ladder costs lines only where data landed.
- ``/metrics.json`` — the full JSON snapshot: registry dump + the
  executor's per-node stats/totals when wired. ``nns-top`` polls this.
- ``nns-launch --metrics out.json`` — the same snapshot written once at
  EOS (:func:`dump_json`, atomic tmp + rename).

:class:`MetricsServer` is a stdlib ``ThreadingHTTPServer`` on a daemon
background thread, started by the executor when
``[executor] metrics_port`` / ``NNS_TPU_METRICS_PORT`` is set (default
off) and joined on ``Executor.stop()`` — it must never outlive the
pipeline as a leaked thread.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs.metrics import (
    METRIC_CATALOG,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

_log = get_logger("obs")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    if v != v or v in (float("inf"), float("-inf")):
        return "NaN" if v != v else ("+Inf" if v > 0 else "-Inf")
    if isinstance(v, int) or float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def to_prometheus(registry: MetricsRegistry) -> str:
    """Render the registry in Prometheus text exposition format."""
    by_name: Dict[str, list] = {}
    for m in registry.metrics():
        by_name.setdefault(m.name, []).append(m)
    out = []
    for name in sorted(by_name):
        group = by_name[name]
        out.append(f"# HELP {name} {METRIC_CATALOG.get(name, '')}")
        out.append(f"# TYPE {name} {group[0].kind}")
        for m in sorted(group, key=lambda m: sorted(m.labels.items())):
            if isinstance(m, (Counter, Gauge)):
                out.append(f"{name}{_label_str(m.labels)} {_fmt(m.value)}")
                continue
            assert isinstance(m, Histogram)
            cum = 0
            for i, c in enumerate(m.counts):
                if not c:
                    continue
                cum += c
                le = _label_str({**m.labels, "le": _fmt(m.edge(i + 1))})
                out.append(f"{name}_bucket{le} {cum}")
            inf = _label_str({**m.labels, "le": "+Inf"})
            out.append(f"{name}_bucket{inf} {m.count}")
            out.append(f"{name}_sum{_label_str(m.labels)} {_fmt(m.sum)}")
            out.append(f"{name}_count{_label_str(m.labels)} {m.count}")
    return "\n".join(out) + "\n"


def snapshot(
    registry: Optional[MetricsRegistry],
    stats: Optional[dict] = None,
    totals: Optional[dict] = None,
    process: Optional[str] = None,
) -> dict:
    """The JSON document ``/metrics.json`` serves and ``--metrics``
    dumps: per-node stats rows (what ``nns-top`` renders) plus the raw
    registry dump (what cross-process aggregation merges)."""
    return {
        "schema": "nns-obs/1",
        "process": process or f"pid{os.getpid()}",
        "time_unix_s": time.time(),
        "nodes": stats or {},
        "totals": totals or {},
        "metrics": registry.to_dict()["metrics"] if registry else [],
    }


def dump_json(path: str, doc: dict) -> None:
    """Atomic snapshot write (tmp + rename): a reader polling the file
    never sees a torn document."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)


class _Handler(BaseHTTPRequestHandler):
    # the server object carries the registry/stats refs (stdlib pattern)
    def do_GET(self) -> None:  # noqa: N802 - stdlib API name
        srv = self.server
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = to_prometheus(srv.registry).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path in ("/metrics.json", "/json"):
            body = json.dumps(srv.snapshot()).encode()
            ctype = "application/json"
        elif path == "/":
            body = (
                b"nns-obs metrics endpoint\n"
                b"  /metrics       Prometheus text format\n"
                b"  /metrics.json  JSON snapshot (nns-top polls this)\n"
            )
            ctype = "text/plain; charset=utf-8"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:
        _log.debug("http: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    registry: MetricsRegistry
    stats_fn: Optional[Callable[[], dict]]
    totals_fn: Optional[Callable[[], dict]]
    process: Optional[str]

    def snapshot(self) -> dict:
        stats = totals = None
        try:
            if self.stats_fn is not None:
                stats = self.stats_fn()
            if self.totals_fn is not None:
                totals = self.totals_fn()
        except Exception as exc:  # noqa: BLE001 — a dying pipeline must
            # not take the exposition endpoint down with it
            _log.warning("stats snapshot failed: %s", exc)
        return snapshot(self.registry, stats, totals, self.process)


class MetricsServer:
    """Background exposition server. ``port=0`` binds an ephemeral port
    (read it back from ``.port`` — tests and same-host scrapers)."""

    def __init__(
        self,
        registry: MetricsRegistry,
        stats_fn: Optional[Callable[[], dict]] = None,
        totals_fn: Optional[Callable[[], dict]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        process: Optional[str] = None,
    ) -> None:
        self._httpd = _Server((host, port), _Handler)
        self._httpd.registry = registry
        self._httpd.stats_fn = stats_fn
        self._httpd.totals_fn = totals_fn
        self._httpd.process = process
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="nns-obs-http",
            daemon=True,
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsServer":
        self._thread.start()
        _log.info("metrics endpoint serving on %s/metrics", self.url)
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
