"""Metric primitives: Counter / Gauge / Histogram + the MetricsRegistry.

Design constraints (why this is not a prometheus_client dependency):

- **Cheap under the executor's per-frame lock discipline.** The hot-path
  writers are the node service threads, one writer per metric instance
  (the BatchStats/FaultStats single-writer contract): ``observe()`` /
  ``inc()`` are a handful of GIL-atomic attribute ops, no lock taken.
  Readers (the exposition thread, ``Executor.stats()``) get a
  consistent-enough snapshot from GIL-atomic reads, exactly like the
  executor's existing counters.
- **Fixed log-scaled buckets.** A histogram is an integer array over a
  geometric ladder ``lo · growth^i``: ``observe()`` is one ``log`` and
  one list increment, quantiles interpolate log-linearly inside the
  landing bucket, and the worst-case quantile error is bounded by one
  bucket's width (``growth`` − 1, ~19% at the default quarter-octave
  ladder — tails, not means, so that is plenty for p50/p95/p99).
- **Mergeable across nodes/processes.** Two histograms over the same
  ladder merge by summing counts; ``to_dict``/``from_dict`` round-trip
  through JSON so per-process snapshots (the edge/query topology)
  aggregate into one fleet view.

The module-level :func:`enable` / :func:`get` mirror ``trace.py``: one
global registry, resolved by the executor at construction, opt-in via
``NNS_TPU_METRICS`` / ``NNS_TPU_METRICS_PORT`` / ``[executor] metrics``.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Tuple

# Every metric the package emits, name → help text. The obs self-check
# (analysis/selfcheck.py obs_self_check, mirroring san_self_check) keeps
# this catalog, the emitting code, and docs/observability.md in sync —
# a metric emitted but not cataloged/documented fails the style gate.
METRIC_CATALOG: Dict[str, str] = {
    "nns_element_latency_us": (
        "per-element processing latency per invoke, microseconds "
        "(histogram; one observation per frame, or per batch on "
        "batched service loops)"
    ),
    "nns_element_frames_total": "frames processed per element (counter)",
    "nns_queue_wait_us": (
        "time a frame spent queued in an element's input channel before "
        "the service thread popped it, microseconds (histogram)"
    ),
    "nns_queue_depth": (
        "input-channel depth sampled every 16th frame, frames (histogram)"
    ),
    "nns_batch_size": (
        "frames per batched device invoke (histogram; micro-batching "
        "segments and batchable host filters only)"
    ),
    "nns_fault_events_total": (
        "fault-layer events by action label: retry / drop / route / "
        "route-unlinked (counter)"
    ),
    "nns_edge_requests_total": (
        "tensor_query_client round trips completed (counter)"
    ),
    "nns_edge_rtt_us": (
        "tensor_query_client request round-trip time, microseconds "
        "(histogram; includes serialization and the remote pipeline)"
    ),
    "nns_admission_rejects_total": (
        "query-server admission rejections by reason label: max-clients "
        "/ overload / client-backpressure / rate / malformed (counter)"
    ),
    "nns_deadline_shed_total": (
        "frames dropped at executor dequeue because their client SLO "
        "(deadline_ms meta) already expired, per node (counter)"
    ),
    "nns_client_queue_depth": (
        "admitted-but-unserved requests queued per client at a query "
        "server, by client label (gauge)"
    ),
    "nns_edge_nacks_total": (
        "structured NACKs a tensor_query_client received, by reason "
        "label (counter)"
    ),
    "nns_fleet_failovers_total": (
        "fleet-client requests re-sent to another endpoint after their "
        "first endpoint failed, NACKed draining, or rejected them "
        "(counter; docs/edge-serving.md)"
    ),
    "nns_fleet_hedges_total": (
        "hedged sends a fleet client fired at a second endpoint for "
        "straggling requests (hedge-after-ms; first reply wins, the "
        "loser is deduped by frame_id) (counter; docs/edge-serving.md)"
    ),
    "nns_endpoint_healthy": (
        "1 while a fleet endpoint is in the dispatch rotation, 0 while "
        "ejected (consecutive failures) or draining (rolling restart), "
        "by endpoint label (gauge; docs/edge-serving.md)"
    ),
    "nns_device_faults_total": (
        "device-plane faults classified per element, by kind label: "
        "oom / compile / device_lost / transient (counter; "
        "docs/resilience.md)"
    ),
    "nns_degraded_segments": (
        "1 while a segment serves degraded — device circuit open "
        "(host/eager path) or OOM batch ceiling below the full ladder — "
        "else 0, per element (gauge; docs/resilience.md)"
    ),
    "nns_plane_batch_occupancy": (
        "frames per cross-stream serving-plane dispatch, by plane "
        "label (histogram; occupancy vs plane-max-batch is the "
        "multiplexing win — docs/serving-plane.md)"
    ),
    "nns_plane_queue_depth": (
        "queued-but-undispatched requests across all client streams of "
        "a serving plane, sampled at each dispatch, by plane label "
        "(gauge; docs/serving-plane.md)"
    ),
    "nns_plane_stream_admitted_total": (
        "requests a client stream submitted into its serving plane, by "
        "plane and stream label (counter; docs/serving-plane.md)"
    ),
    "nns_plane_stream_served_total": (
        "requests a serving plane completed back to a client stream, "
        "by plane and stream label (counter; admitted minus served is "
        "the stream's in-flight/errored tail — docs/serving-plane.md)"
    ),
    "nns_plane_inflight_windows": (
        "windows submitted to a serving plane but not yet collected by "
        "their stream's async ticket wait, by plane label (gauge; ~0 "
        "under blocking submits, up to streams × ring-depth when the "
        "async in-flight rings are full — docs/serving-plane.md)"
    ),
    "nns_plane_submit_wait_ms": (
        "time a stream spent BLOCKED per plane window — the full round "
        "trip for blocking submits, the residual ticket wait for async "
        "ones (overlap eats the rest), milliseconds, by plane label "
        "(histogram; docs/serving-plane.md)"
    ),
    "nns_kv_blocks_in_use": (
        "KV-cache blocks currently referenced by live requests in a "
        "paged continuous batcher (gauge; capacity vs kv_blocks is the "
        "paging headroom — docs/llm-serving.md)"
    ),
    "nns_kv_prefix_hits_total": (
        "prompt blocks adopted from the paged KV prefix index instead "
        "of re-prefilled — shared system prompts count once, not per "
        "request (counter; docs/llm-serving.md)"
    ),
    "nns_kv_gather_dispatch_total": (
        "paged step/pump/spec launches that ran the gather→contiguous-"
        "view→scatter oracle (kv_attn=gather) instead of the "
        "block-native arena read — a nonzero rate means the decode "
        "plane is paying the materialized-view round trip (counter; "
        "docs/llm-serving.md)"
    ),
    "nns_kv_migrations_total": (
        "live request migrations through kv/migrate.py spans, by "
        "direction label: out (extracted and shipped to a peer) / in "
        "(adopted from a peer's span) (counter; docs/llm-serving.md "
        "Migration & recovery)"
    ),
    "nns_kv_span_bytes_total": (
        "encoded KV-span bytes, by direction label: out (spans "
        "encoded) / in (spans decoded) — warm migrations strip "
        "prefix-shared block payloads, so out bytes under-count the "
        "resident KV the receiver reconstructs (counter; "
        "docs/llm-serving.md)"
    ),
    "nns_disagg_handoffs_total": (
        "disaggregated prefill→decode request handoffs, by outcome "
        "label: handoff (span shipped to a decode peer) / local "
        "(every peer refused or was unreachable — decoded locally, "
        "tokens never lost) / relayed (finished tokens fetched back "
        "from the peer and delivered) / recovered (peer lost the "
        "handoff — prompt resubmitted locally) (counter; "
        "docs/llm-serving.md Disaggregated serving)"
    ),
    "nns_route_prefix_hits_total": (
        "fleet-client requests routed to the endpoint holding the "
        "longest matching prompt prefix (prefix-route=true) — the "
        "cache-affinity win over plain least-loaded rotation "
        "(counter; docs/edge-serving.md Prefix-aware routing)"
    ),
    "nns_request_resumes_total": (
        "in-flight requests resumed after a disruption, by kind "
        "label: reprefill (no peer accepted the span — deadline-aware "
        "re-prefill from the surviving prefix) / checkpoint (adopted "
        "from an on-disk span checkpoint after a restart) (counter; "
        "docs/llm-serving.md)"
    ),
    "nns_request_ttft_ms": (
        "per-request time to first token, submit → first token "
        "materialized, milliseconds (histogram; the admission SLO — "
        "docs/llm-serving.md)"
    ),
    "nns_request_tpot_ms": (
        "per-request mean time per output token after the first, "
        "milliseconds (histogram; the decode SLO — docs/llm-serving.md)"
    ),
    "nns_transfer_bytes_total": (
        "bytes crossing the host<->device boundary through the "
        "transfer engine, by direction label: h2d (staged uploads) / "
        "d2h (coalesced fetches) — zero d2h between adjacent fused "
        "segments is the resident-handoff invariant (counter; "
        "docs/streaming.md)"
    ),
    "nns_fused_postproc_total": (
        "frames whose pre/post-processing (decode, resize/crop, "
        "normalize) ran fused inside a device segment instead of as a "
        "host node, per element (counter; docs/on-device-ops.md)"
    ),
    "nns_chain_launches_total": (
        "window dispatches of a compiled whole-chain resident program "
        "— one per unrolled window, NOT one per node per frame, per "
        "chain element (counter; docs/chain-analysis.md)"
    ),
    "nns_chain_fallback_total": (
        "windows a compiled chain served through the per-node parity "
        "path after its fallback latched (device fault, unshrinkable "
        "OOM, or compile failure), per chain element (counter; "
        "docs/chain-analysis.md)"
    ),
}

# default ladder: quarter-octave buckets from 1 µs up past 100 s —
# one ladder for every time-valued histogram so they merge freely
DEFAULT_LO = 1.0
DEFAULT_GROWTH = 2.0 ** 0.25
DEFAULT_NBUCKETS = 112


class Counter:
    """Monotonic counter (single-writer increments, GIL-atomic reads)."""

    __slots__ = ("name", "labels", "value")

    kind = "counter"

    def __init__(self, name: str, labels: Dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def to_dict(self) -> dict:
        return {"type": "counter", "name": self.name,
                "labels": self.labels, "value": self.value}

    def merge(self, other: "Counter") -> None:
        self.value += other.value


class Gauge:
    """Point-in-time value (queue depth now, workers alive, ...)."""

    __slots__ = ("name", "labels", "value")

    kind = "gauge"

    def __init__(self, name: str, labels: Dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def to_dict(self) -> dict:
        return {"type": "gauge", "name": self.name,
                "labels": self.labels, "value": self.value}

    def merge(self, other: "Gauge") -> None:
        # merging point-in-time gauges across processes: sum (the fleet
        # total is the only aggregate that needs no extra metadata)
        self.value += other.value


class Histogram:
    """Fixed log-scaled-bucket histogram with quantile estimates.

    Bucket ``i`` covers ``[lo·growth^i, lo·growth^(i+1))``; bucket 0
    additionally absorbs values below ``lo`` and the last bucket values
    past the top. ``observe()`` is one ``math.log`` + one list
    increment — single-writer cheap. Quantiles walk the cumulative
    counts and interpolate log-linearly inside the landing bucket,
    clamped to the observed min/max so a one-sample histogram reports
    the sample, not a bucket edge.
    """

    __slots__ = ("name", "labels", "lo", "growth", "counts", "count",
                 "sum", "min", "max", "_inv_log_growth")

    kind = "histogram"

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        lo: float = DEFAULT_LO,
        growth: float = DEFAULT_GROWTH,
        nbuckets: int = DEFAULT_NBUCKETS,
    ) -> None:
        if lo <= 0 or growth <= 1.0 or nbuckets < 1:
            raise ValueError(
                f"bad histogram ladder lo={lo} growth={growth} n={nbuckets}"
            )
        self.name = name
        self.labels = labels
        self.lo = float(lo)
        self.growth = float(growth)
        self.counts: List[int] = [0] * int(nbuckets)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._inv_log_growth = 1.0 / math.log(self.growth)

    def _idx(self, v: float) -> int:
        if v < self.lo:
            return 0
        i = int(math.log(v / self.lo) * self._inv_log_growth)
        n = len(self.counts)
        return i if i < n else n - 1

    def observe(self, v: float) -> None:
        self.counts[self._idx(v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    # -- reading -----------------------------------------------------------
    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def edge(self, i: int) -> float:
        """Lower edge of bucket ``i`` (upper edge of ``i - 1``)."""
        return self.lo * (self.growth ** i)

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile (0..1) by cumulative walk +
        log-linear interpolation inside the landing bucket."""
        if not self.count:
            return 0.0
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= target:
                frac = (target - cum) / c
                est = self.edge(i) * (self.growth ** frac)
                return min(max(est, self.min), self.max)
            cum += c
        return self.max

    def percentiles(self) -> Tuple[float, float, float]:
        """(p50, p95, p99) — the live-telemetry tail view."""
        return self.quantile(0.50), self.quantile(0.95), self.quantile(0.99)

    # -- merge / serialization ---------------------------------------------
    def merge(self, other: "Histogram") -> None:
        if (other.lo, other.growth, len(other.counts)) != (
            self.lo, self.growth, len(self.counts)
        ):
            raise ValueError(
                f"cannot merge histograms over different ladders: "
                f"{self.name} ({self.lo},{self.growth},{len(self.counts)}) "
                f"vs ({other.lo},{other.growth},{len(other.counts)})"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def to_dict(self) -> dict:
        p50, p95, p99 = self.percentiles()
        return {
            "type": "histogram", "name": self.name, "labels": self.labels,
            "lo": self.lo, "growth": self.growth,
            "nbuckets": len(self.counts),
            # sparse: index → count (most of a 112-rung ladder is empty)
            "counts": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            "count": self.count, "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "p50": p50, "p95": p95, "p99": p99,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls(d["name"], dict(d.get("labels", {})), lo=d["lo"],
                growth=d["growth"], nbuckets=d["nbuckets"])
        for i, c in d.get("counts", {}).items():
            h.counts[int(i)] = int(c)
        h.count = int(d["count"])
        h.sum = float(d["sum"])
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        return h


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name+labels → metric instance, with get-or-create semantics.

    Creation takes the registry lock; the steady-state lookup is one
    dict read (GIL-atomic), so per-frame emitters can re-resolve their
    metric without a lock — though hot paths cache the instance.
    Metric names must be cataloged in :data:`METRIC_CATALOG`: the obs
    self-check keeps code, catalog, and docs in sync.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, object] = {}

    @staticmethod
    def _key(name: str, labels: Dict[str, str]) -> Tuple:
        return (name,) + tuple(sorted(labels.items()))

    def _get_or_create(self, cls, name: str, labels: Dict[str, str],
                       **kw):
        if name not in METRIC_CATALOG:
            raise KeyError(
                f"unknown metric {name!r}: add it to "
                "obs.metrics.METRIC_CATALOG (and docs/observability.md)"
            )
        key = self._key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, labels, **kw)
                    self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}"
            )
        return m

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(
        self, name: str, lo: float = DEFAULT_LO,
        growth: float = DEFAULT_GROWTH, nbuckets: int = DEFAULT_NBUCKETS,
        **labels: str,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, labels, lo=lo, growth=growth, nbuckets=nbuckets
        )

    # -- reading -----------------------------------------------------------
    def metrics(self) -> List[object]:
        with self._lock:
            return list(self._metrics.values())

    def find(self, name: str, **labels: str):
        """The metric registered under (name, labels), or None."""
        return self._metrics.get(self._key(name, labels))

    def to_dict(self) -> dict:
        return {"metrics": [m.to_dict() for m in self.metrics()]}

    def merge_dict(self, snap: dict) -> None:
        """Fold another process's :meth:`to_dict` snapshot into this
        registry (cross-node aggregation for the edge/query topology)."""
        for d in snap.get("metrics", []):
            cls = _KINDS[d["type"]]
            labels = dict(d.get("labels", {}))
            if cls is Histogram:
                mine = self._get_or_create(
                    cls, d["name"], labels, lo=d["lo"], growth=d["growth"],
                    nbuckets=d["nbuckets"],
                )
                mine.merge(Histogram.from_dict(d))
            else:
                mine = self._get_or_create(cls, d["name"], labels)
                mine.value += d["value"]


# -- global opt-in (the trace.py enable/disable/get pattern) ----------------

_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None


def enable() -> MetricsRegistry:
    """Install (or return) the global registry; executors built after
    this exists record per-element metrics."""
    global _registry
    with _lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def disable() -> None:
    global _registry
    with _lock:
        _registry = None


def _configured_on() -> bool:
    """Env/config opt-in: ``NNS_TPU_METRICS`` truthy, a metrics port set
    (either env spelling), or ``[executor] metrics`` in the ini."""
    if os.environ.get("NNS_TPU_METRICS", "").strip().lower() in (
        "1", "true", "yes", "on"
    ):
        return True
    if resolve_port() is not None:
        return True
    from nnstreamer_tpu.config import conf

    return conf().get_bool("executor", "metrics", False)


def resolve_port() -> Optional[int]:
    """Exposition port, or None when off: ``NNS_TPU_METRICS_PORT``
    (the documented direct env knob) outranks the layered
    ``[executor] metrics_port`` (itself env-overridable as
    ``NNS_TPU_EXECUTOR_METRICS_PORT``); 0/unset = off. Malformed values
    read as off with a warning — a typo'd env var must not keep a
    pipeline from starting (the [executor]-defaults discipline)."""
    raw = os.environ.get("NNS_TPU_METRICS_PORT")
    if raw is not None and raw.strip():
        try:
            port = int(raw)
        except ValueError:
            from nnstreamer_tpu.log import get_logger

            get_logger("obs").warning(
                "NNS_TPU_METRICS_PORT=%r is not an int; metrics "
                "endpoint stays off", raw,
            )
            return None
        return port if port > 0 else None
    from nnstreamer_tpu.config import conf

    port = conf().get_int("executor", "metrics_port", 0)
    return port if port > 0 else None


def get() -> Optional[MetricsRegistry]:
    """Active registry or None. Mirrors ``trace.get()``: resolved by the
    executor ONCE at construction (not per frame), so the env/config
    probe on the None path stays off the hot path."""
    r = _registry
    if r is None and _configured_on():
        r = enable()
    return r
