"""nns-obs: metrics & live-telemetry subsystem.

The reference ecosystem leans on out-of-tree GstShark/NNShark for
per-element telemetry (SURVEY.md §5.1); in-tree ``trace.py`` gives
post-hoc chrome-trace spans, and ``Executor.stats()`` reported only
means. This package is the live half of observability:

- :mod:`nnstreamer_tpu.obs.metrics` — a :class:`MetricsRegistry` of
  Counter/Gauge/Histogram primitives (fixed log-scaled buckets, cheap
  under the executor's per-frame single-writer discipline, mergeable
  across nodes/processes) with p50/p95/p99 quantile estimates.
- :mod:`nnstreamer_tpu.obs.expo` — Prometheus text format and a JSON
  snapshot from a stdlib-http background thread
  (``[executor] metrics_port`` / ``NNS_TPU_METRICS_PORT``, default off)
  plus the one-shot ``nns-launch --metrics out.json`` dump.
- :mod:`nnstreamer_tpu.obs.nns_top` — the ``nns-top`` console script: a
  live per-element table (fps, p50/p99, queue depth, batch avg /
  pad-waste, retry/circuit-breaker state, san_* counters) against the
  JSON endpoint or an in-process executor.

Enable via :func:`enable` / ``NNS_TPU_METRICS=1`` /
``[executor] metrics`` — disabled (the default) the hot path pays one
``None`` attribute check per frame, mirroring ``trace.get()``.
"""

from nnstreamer_tpu.obs.metrics import (  # noqa: F401  (re-export)
    METRIC_CATALOG,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    disable,
    enable,
    get,
)
