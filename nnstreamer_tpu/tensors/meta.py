"""Flexible-tensor binary header: per-frame self-describing tensor metadata.

TPU-native equivalent of the reference's GstTensorMetaInfo
(tensor_typedef.h:279-294): for ``format=flexible`` streams each tensor is
prefixed with a compact binary header carrying dtype/shape, parsed and
stripped at element boundaries (tensor_filter.c:617-625). The same header is
the wire format of the distributed edge/query layer (SURVEY.md §5.8), so a
tensor serialized on one host is self-describing on another.

Layout (little-endian, 96 bytes fixed):

    uint32 magic      'NNST' (0x5453_4E4E)
    uint32 version    1
    uint32 dtype      DType code (index into _DTYPE_CODES)
    uint32 format     TensorFormat (0 static, 1 flexible, 2 sparse)
    uint32 media_type reserved media-type tag (0 = tensors)
    uint32 rank
    uint32 dims[16]   innermost-first like the reference; unused = 0
    uint64 payload    payload byte size that follows the header
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from nnstreamer_tpu.tensors.spec import DType, TensorFormat, TensorSpec

MAGIC = 0x5453_4E4E  # b'NNST' little-endian
VERSION = 1
_MAX_DIMS = 16
_STRUCT = struct.Struct("<6I16IQ")
HEADER_SIZE = _STRUCT.size  # 96

_DTYPE_CODES = [
    DType.INT8,
    DType.UINT8,
    DType.INT16,
    DType.UINT16,
    DType.INT32,
    DType.UINT32,
    DType.INT64,
    DType.UINT64,
    DType.FLOAT16,
    DType.FLOAT32,
    DType.FLOAT64,
    DType.BFLOAT16,
    DType.BOOL,
]
_DTYPE_TO_CODE = {d: i for i, d in enumerate(_DTYPE_CODES)}

_FORMAT_CODES = [TensorFormat.STATIC, TensorFormat.FLEXIBLE, TensorFormat.SPARSE]
_FORMAT_TO_CODE = {f: i for i, f in enumerate(_FORMAT_CODES)}


@dataclass(frozen=True)
class FlexTensorMeta:
    """Parsed flexible-tensor header."""

    dtype: DType
    shape: Tuple[int, ...]  # canonical row-major (outermost first)
    format: TensorFormat = TensorFormat.FLEXIBLE
    media_type: int = 0
    payload_size: int = 0

    @property
    def spec(self) -> TensorSpec:
        return TensorSpec(self.shape, self.dtype)

    def pack(self) -> bytes:
        if len(self.shape) > _MAX_DIMS:
            raise ValueError(f"rank {len(self.shape)} > {_MAX_DIMS}")
        dims = [0] * _MAX_DIMS
        # innermost-first on the wire, like the reference's uint32[16]
        for i, d in enumerate(reversed(self.shape)):
            dims[i] = int(d)
        return _STRUCT.pack(
            MAGIC,
            VERSION,
            _DTYPE_TO_CODE[self.dtype],
            _FORMAT_TO_CODE[self.format],
            self.media_type,
            len(self.shape),
            *dims,
            self.payload_size,
        )

    @classmethod
    def unpack(cls, buf: bytes, offset: int = 0) -> "FlexTensorMeta":
        if len(buf) - offset < HEADER_SIZE:
            raise ValueError(
                f"buffer too small for flex header: {len(buf) - offset} < {HEADER_SIZE}"
            )
        fields = _STRUCT.unpack_from(buf, offset)
        magic, version, dtype_c, fmt_c, media_type, rank = fields[:6]
        dims = fields[6 : 6 + _MAX_DIMS]
        payload = fields[-1]
        if magic != MAGIC:
            raise ValueError(f"bad flex-tensor magic: {magic:#x}")
        if version != VERSION:
            raise ValueError(f"unsupported flex-tensor version: {version}")
        if rank > _MAX_DIMS:
            raise ValueError(f"bad rank {rank}")
        if dtype_c >= len(_DTYPE_CODES):
            raise ValueError(f"bad dtype code {dtype_c}")
        if fmt_c >= len(_FORMAT_CODES):
            raise ValueError(f"bad format code {fmt_c}")
        shape = tuple(reversed(dims[:rank]))
        return cls(
            dtype=_DTYPE_CODES[dtype_c],
            shape=shape,
            format=_FORMAT_CODES[fmt_c],
            media_type=media_type,
            payload_size=payload,
        )

    # -- array <-> bytes helpers (the serialize path of the edge layer) ----
    @classmethod
    def encode_array(cls, array) -> bytes:
        """array → header + raw bytes (C-contiguous)."""
        a = np.ascontiguousarray(np.asarray(array))
        meta = cls(
            dtype=DType.from_any(a.dtype),
            shape=tuple(int(d) for d in a.shape),
            payload_size=a.nbytes,
        )
        return meta.pack() + a.tobytes()

    @classmethod
    def decode_array(cls, buf: bytes, offset: int = 0) -> Tuple[np.ndarray, int]:
        """header + raw bytes → (array, bytes consumed)."""
        meta = cls.unpack(buf, offset)
        start = offset + HEADER_SIZE
        end = start + meta.payload_size
        if len(buf) < end:
            raise ValueError(
                f"truncated flex tensor: need {meta.payload_size} payload bytes"
            )
        a = np.frombuffer(buf[start:end], dtype=meta.dtype.np_dtype)
        return a.reshape(meta.shape), end - offset


def encode_frame_tensors(tensors) -> bytes:
    """Serialize a frame's tensors as concatenated flex-header chunks."""
    return b"".join(FlexTensorMeta.encode_array(t) for t in tensors)


def decode_frame_tensors(buf: bytes) -> Tuple[np.ndarray, ...]:
    """Inverse of encode_frame_tensors."""
    out = []
    offset = 0
    while offset < len(buf):
        a, used = FlexTensorMeta.decode_array(buf, offset)
        out.append(a)
        offset += used
    return tuple(out)
