"""Sparse tensor codec (COO): values + flat uint32 indices.

Reference: gst/nnstreamer/elements/gsttensor_sparseutil.c —
``gst_tensor_sparse_from_dense`` (:116) emits meta header + nnz values + nnz
uint32 flat indices; ``gst_tensor_sparse_to_dense`` (:27) inverts it.

This is a *wire/stream compression* format: encode/decode run on host at
stream boundaries (numpy), exactly like the reference. On-device sparsity is
a different concern (XLA wants dense static shapes); sparse frames are
densified before entering a fused compute segment.
"""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

from nnstreamer_tpu.tensors.meta import FlexTensorMeta, HEADER_SIZE
from nnstreamer_tpu.tensors.spec import DType, TensorFormat

_NNZ_STRUCT = struct.Struct("<Q")


def sparse_encode(dense: np.ndarray) -> bytes:
    """dense array → flex header (format=sparse) + nnz + values + indices."""
    a = np.ascontiguousarray(np.asarray(dense))
    flat = a.reshape(-1)
    (idx,) = np.nonzero(flat)
    if flat.size > np.iinfo(np.uint32).max:
        raise ValueError("tensor too large for uint32 flat indexing")
    values = flat[idx]
    indices = idx.astype(np.uint32)
    payload = _NNZ_STRUCT.pack(idx.size) + values.tobytes() + indices.tobytes()
    meta = FlexTensorMeta(
        dtype=DType.from_any(a.dtype),
        shape=tuple(int(d) for d in a.shape),
        format=TensorFormat.SPARSE,
        payload_size=len(payload),
    )
    return meta.pack() + payload


def sparse_decode(buf: bytes, offset: int = 0) -> Tuple[np.ndarray, int]:
    """Inverse of sparse_encode → (dense array, bytes consumed)."""
    meta = FlexTensorMeta.unpack(buf, offset)
    if meta.format is not TensorFormat.SPARSE:
        raise ValueError(f"not a sparse chunk: format={meta.format}")
    pos = offset + HEADER_SIZE
    (nnz,) = _NNZ_STRUCT.unpack_from(buf, pos)
    pos += _NNZ_STRUCT.size
    dt = meta.dtype.np_dtype
    values = np.frombuffer(buf[pos : pos + nnz * dt.itemsize], dtype=dt)
    pos += nnz * dt.itemsize
    indices = np.frombuffer(buf[pos : pos + nnz * 4], dtype=np.uint32)
    pos += nnz * 4
    dense = np.zeros(int(np.prod(meta.shape)) if meta.shape else 1, dtype=dt)
    dense[indices] = values
    return dense.reshape(meta.shape), pos - offset


def sparse_density(dense: np.ndarray) -> float:
    """Fraction of nonzero elements (used by tests and the enc element)."""
    a = np.asarray(dense)
    return float(np.count_nonzero(a)) / max(a.size, 1)
