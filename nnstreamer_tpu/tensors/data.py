"""Typed scalar operations for control-plane decisions.

Reference: gst/nnstreamer/tensor_data.{c,h} — a tagged-union scalar
(tensor_element, tensor_typedef.h:198-212) with typecast / compare / average
used by tensor_if compared-values, tensor_transform 'stand' mode, and
tensor_rate. Here scalars are 0-d numpy values; the same helpers are reused
in jnp form inside fused programs where possible.
"""

from __future__ import annotations

from typing import Any, Union

import numpy as np

from nnstreamer_tpu.tensors.spec import DType

Scalar = Union[int, float, np.number]


def typecast(value: Scalar, dtype: Union[DType, str]) -> np.number:
    """Cast a scalar with C-like saturation-free semantics
    (gst_tensor_data_typecast)."""
    dt = DType.from_any(dtype)
    return np.asarray(value).astype(dt.np_dtype)[()]


def tensor_average(array) -> float:
    """Mean over all elements (gst_tensor_data_raw_average) — used by
    tensor_if TENSOR_AVERAGE_VALUE compared-value mode."""
    return float(np.mean(np.asarray(array, dtype=np.float64)))


def tensor_average_per_channel(array, axis: int = -1) -> np.ndarray:
    """Per-channel mean (gst_tensor_data_raw_average_per_channel) — used by
    tensor_transform stand mode with per-channel option."""
    a = np.asarray(array, dtype=np.float64)
    axes = tuple(i for i in range(a.ndim) if i != (axis % a.ndim))
    return np.mean(a, axis=axes)


def tensor_std(array) -> float:
    """Population standard deviation (gst_tensor_data_raw_std)."""
    return float(np.std(np.asarray(array, dtype=np.float64)))


_COMPARE_OPS = {
    "EQ": np.equal,
    "NE": np.not_equal,
    "GT": np.greater,
    "GE": np.greater_equal,
    "LT": np.less,
    "LE": np.less_equal,
}


def compare(a: Scalar, op: str, b: Scalar) -> bool:
    """Scalar comparison by operator name (tensor_if operators,
    gsttensor_if.h; RANGE ops are composed from these in elements/flow.py)."""
    try:
        fn = _COMPARE_OPS[op.upper()]
    except KeyError as exc:
        raise ValueError(f"unknown compare op {op!r}") from exc
    return bool(fn(a, b))
