"""Tensor specs: the typed contract that flows through a pipeline.

TPU-native redesign of the reference's tensor type system
(gst/nnstreamer/include/tensor_typedef.h:131-296 — GstTensorInfo,
GstTensorsInfo, GstTensorsConfig — and the caps/dim-string utilities in
gst/nnstreamer/nnstreamer_plugin_api_util_impl.c).

Differences from the reference, by design:

- Shapes are canonical row-major tuples (outermost first), matching
  jax/numpy. The reference stores dims innermost-first in ``uint32[4]``
  (tensor_typedef.h:34, Documentation/data-type-and-flow-control.md); we keep
  that colon-string syntax (``d1:d2:d3:d4``, innermost first) at the string
  boundary for user parity and reverse it on parse.
- ``bfloat16`` is a first-class dtype (the TPU-native compute type); the
  reference stops at float16 (tensor_typedef.h:131-146).
- A dim of ``None`` is a negotiation wildcard (the reference's 0 /
  unspecified dim); specs are fully static after pipeline negotiation so XLA
  compiles once.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Iterable, Optional, Sequence, Tuple, Union

import numpy as np

# Reference limits: NNS_TENSOR_RANK_LIMIT=4 / 16 (flexible),
# NNS_TENSOR_SIZE_LIMIT=16 (tensor_typedef.h:34-44). We allow rank 8
# everywhere (superset) and keep the 16-tensors-per-frame limit.
NNS_TENSOR_RANK_LIMIT = 8
NNS_TENSOR_SIZE_LIMIT = 16


class DType(enum.Enum):
    """Tensor element types (reference: tensor_type, tensor_typedef.h:131-146)."""

    INT8 = "int8"
    UINT8 = "uint8"
    INT16 = "int16"
    UINT16 = "uint16"
    INT32 = "int32"
    UINT32 = "uint32"
    INT64 = "int64"
    UINT64 = "uint64"
    FLOAT16 = "float16"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BFLOAT16 = "bfloat16"  # TPU-native extension
    BOOL = "bool"  # convenience for predicate streams (tensor_if)

    @property
    def np_dtype(self) -> np.dtype:
        if self is DType.BFLOAT16:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(self.value)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize

    @property
    def is_float(self) -> bool:
        return self in (DType.FLOAT16, DType.FLOAT32, DType.FLOAT64, DType.BFLOAT16)

    @property
    def is_integer(self) -> bool:
        return not self.is_float and self is not DType.BOOL

    @classmethod
    def from_any(cls, value: Union["DType", str, np.dtype, type]) -> "DType":
        if isinstance(value, DType):
            return value
        if isinstance(value, str):
            try:
                return cls(value.strip().lower())
            except ValueError:
                pass
        name = np.dtype(value).name if not isinstance(value, str) else value
        try:
            return cls(name)
        except ValueError as exc:
            raise ValueError(f"unknown tensor dtype: {value!r}") from exc


class TensorFormat(enum.Enum):
    """Stream data format (reference: tensor_format, tensor_typedef.h:67,91-126).

    - STATIC: shapes/dtypes fixed by the negotiated spec; frames carry raw
      tensors only.
    - FLEXIBLE: each frame is self-describing via a per-tensor binary header
      (see tensors/meta.py, reference GstTensorMetaInfo).
    - SPARSE: COO encoding (header + values + flat uint32 indices; reference
      gst/nnstreamer/elements/gsttensor_sparseutil.c).
    """

    STATIC = "static"
    FLEXIBLE = "flexible"
    SPARSE = "sparse"

    @classmethod
    def from_any(cls, value: Union["TensorFormat", str]) -> "TensorFormat":
        if isinstance(value, TensorFormat):
            return value
        return cls(value.strip().lower())


DimValue = Optional[int]  # None = wildcard (reference: dim 0 / unspecified)
Shape = Tuple[DimValue, ...]


def parse_dimension(dim_str: str) -> Shape:
    """Parse a reference-style dim string into a canonical row-major shape.

    The reference's colon syntax is innermost-first: ``3:224:224:1`` is a
    batch-1 NHWC image with 3 channels (gst_tensor_parse_dimension,
    nnstreamer_plugin_api_util_impl.c; Documentation/
    data-type-and-flow-control.md). We reverse on parse so the canonical
    shape is ``(1, 224, 224, 3)``. ``0`` or ``?`` means wildcard.
    """
    parts = [p.strip() for p in dim_str.strip().split(":") if p.strip() != ""]
    if not parts:
        raise ValueError(f"empty dimension string: {dim_str!r}")
    if len(parts) > NNS_TENSOR_RANK_LIMIT:
        raise ValueError(
            f"rank {len(parts)} exceeds limit {NNS_TENSOR_RANK_LIMIT}: {dim_str!r}"
        )
    dims: list = []
    for p in parts:
        if p in ("?", "0"):
            dims.append(None)
        else:
            v = int(p)
            if v < 0:
                raise ValueError(f"negative dim in {dim_str!r}")
            dims.append(v)
    return tuple(reversed(dims))


def format_dimension(shape: Sequence[DimValue]) -> str:
    """Canonical shape → reference-style innermost-first colon string."""
    return ":".join("0" if d is None else str(d) for d in reversed(tuple(shape)))


@dataclass(frozen=True)
class TensorSpec:
    """Shape/dtype/name of one tensor in a frame (reference: GstTensorInfo,
    tensor_typedef.h:238-247)."""

    shape: Shape
    dtype: DType = DType.FLOAT32
    name: Optional[str] = None

    def __post_init__(self):
        shape = tuple(self.shape)
        if len(shape) > NNS_TENSOR_RANK_LIMIT:
            raise ValueError(f"rank {len(shape)} exceeds {NNS_TENSOR_RANK_LIMIT}")
        for d in shape:
            if d is not None and (not isinstance(d, int) or d < 0):
                raise ValueError(f"bad dim {d!r} in shape {shape}")
        object.__setattr__(self, "shape", shape)
        object.__setattr__(self, "dtype", DType.from_any(self.dtype))

    # -- queries ----------------------------------------------------------
    @property
    def rank(self) -> int:
        return len(self.shape)

    @property
    def is_static(self) -> bool:
        """Fully specified (no wildcard dims) — required post-negotiation."""
        return all(d is not None for d in self.shape)

    @property
    def element_count(self) -> int:
        if not self.is_static:
            raise ValueError(f"spec not static: {self}")
        return math.prod(self.shape) if self.shape else 1

    @property
    def byte_size(self) -> int:
        """Reference: gst_tensor_info_get_size."""
        return self.element_count * self.dtype.itemsize

    def is_compatible(self, other: "TensorSpec") -> bool:
        """Structural compatibility with wildcard dims (either side).

        Mirrors gst_tensor_info_is_equal plus caps-intersection semantics:
        wildcards unify with anything.
        """
        if self.dtype != other.dtype:
            return False
        a, b = self.shape, other.shape
        if len(a) != len(b):
            # Ranks differ: allow trailing-1 padding like the reference's
            # fixed uint32[4] dims padded with 1s.
            la, lb = list(a), list(b)
            while len(la) < len(lb):
                la.insert(0, 1)
            while len(lb) < len(la):
                lb.insert(0, 1)
            a, b = tuple(la), tuple(lb)
        return all(x is None or y is None or x == y for x, y in zip(a, b))

    def merge(self, other: "TensorSpec") -> "TensorSpec":
        """Intersection of two compatible specs (resolve wildcards)."""
        if not self.is_compatible(other):
            raise ValueError(f"incompatible specs: {self} vs {other}")
        a, b = list(self.shape), list(other.shape)
        while len(a) < len(b):
            a.insert(0, 1)
        while len(b) < len(a):
            b.insert(0, 1)
        merged = tuple(x if x is not None else y for x, y in zip(a, b))
        return TensorSpec(merged, self.dtype, self.name or other.name)

    # -- string / construction -------------------------------------------
    @classmethod
    def from_dim_string(
        cls, dim_str: str, dtype: Union[DType, str] = DType.FLOAT32, name: str = None
    ) -> "TensorSpec":
        return cls(parse_dimension(dim_str), DType.from_any(dtype), name)

    @property
    def dim_string(self) -> str:
        return format_dimension(self.shape)

    def with_shape(self, shape: Sequence[DimValue]) -> "TensorSpec":
        return replace(self, shape=tuple(shape))

    def with_dtype(self, dtype) -> "TensorSpec":
        return replace(self, dtype=DType.from_any(dtype))

    def __str__(self) -> str:
        n = f" name={self.name}" if self.name else ""
        return f"Tensor[{self.dim_string}:{self.dtype.value}{n}]"


@dataclass(frozen=True)
class TensorsSpec:
    """Spec of a whole frame: ordered tensors + format + frame rate.

    Reference: GstTensorsConfig = GstTensorsInfo + format + rate_n/rate_d
    (tensor_typedef.h:259-274). The rate is stream metadata used by
    rate-conversion and sync policies, not a tensor property.
    """

    tensors: Tuple[TensorSpec, ...] = ()
    format: TensorFormat = TensorFormat.STATIC
    rate: Optional[Fraction] = None  # frames per second; None = unknown

    def __post_init__(self):
        tensors = tuple(self.tensors)
        if len(tensors) > NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(
                f"{len(tensors)} tensors exceeds limit {NNS_TENSOR_SIZE_LIMIT}"
            )
        object.__setattr__(self, "tensors", tensors)
        object.__setattr__(self, "format", TensorFormat.from_any(self.format))
        if self.rate is not None:
            object.__setattr__(self, "rate", Fraction(self.rate))

    # -- queries ----------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    @property
    def is_static(self) -> bool:
        return self.format is TensorFormat.STATIC and all(
            t.is_static for t in self.tensors
        )

    def is_compatible(self, other: "TensorsSpec") -> bool:
        if self.format != other.format:
            return False
        if self.format is not TensorFormat.STATIC:
            return True  # flexible/sparse negotiate per-frame
        if self.num_tensors != other.num_tensors:
            return False
        return all(a.is_compatible(b) for a, b in zip(self.tensors, other.tensors))

    def merge(self, other: "TensorsSpec") -> "TensorsSpec":
        if not self.is_compatible(other):
            raise ValueError(f"incompatible: {self} vs {other}")
        if self.format is not TensorFormat.STATIC:
            return self
        merged = tuple(a.merge(b) for a, b in zip(self.tensors, other.tensors))
        return TensorsSpec(merged, self.format, self.rate or other.rate)

    @property
    def byte_size(self) -> int:
        return sum(t.byte_size for t in self.tensors)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_strings(
        cls,
        dimensions: str,
        types: str = "",
        names: str = "",
        format: Union[TensorFormat, str] = TensorFormat.STATIC,
        rate: Optional[Union[str, Fraction, float, int]] = None,
    ) -> "TensorsSpec":
        """Build from reference-style property strings.

        ``dimensions="3:224:224:1,1001:1"``, ``types="uint8,float32"``,
        ``names="image,logits"`` — the syntax of the reference's
        input/output element properties (tensor_filter_common.c:103-128).
        """
        dim_parts = [d for d in dimensions.split(",") if d.strip()]
        type_parts = [t.strip() for t in types.split(",") if t.strip()]
        name_parts = [n.strip() for n in names.split(",")] if names else []
        specs = []
        for i, d in enumerate(dim_parts):
            dt = type_parts[i] if i < len(type_parts) else (
                type_parts[-1] if type_parts else DType.FLOAT32
            )
            nm = name_parts[i] if i < len(name_parts) and name_parts[i] else None
            specs.append(TensorSpec.from_dim_string(d, dt, nm))
        r = None if rate is None else Fraction(rate)
        return cls(tuple(specs), TensorFormat.from_any(format), r)

    @classmethod
    def of(cls, *specs: TensorSpec, **kw) -> "TensorsSpec":
        return cls(tuple(specs), **kw)

    @classmethod
    def from_arrays(cls, arrays: Iterable, **kw) -> "TensorsSpec":
        specs = tuple(
            TensorSpec(tuple(int(d) for d in a.shape), DType.from_any(a.dtype))
            for a in arrays
        )
        return cls(specs, **kw)

    # -- string ------------------------------------------------------------
    @property
    def dimensions_string(self) -> str:
        return ",".join(t.dim_string for t in self.tensors)

    @property
    def types_string(self) -> str:
        return ",".join(t.dtype.value for t in self.tensors)

    def to_caps_string(self) -> str:
        """Reference-style caps string (other/tensors,...) for logging/wire."""
        s = f"other/tensors,format={self.format.value}"
        if self.format is TensorFormat.STATIC:
            s += (
                f",num_tensors={self.num_tensors}"
                f",dimensions=(string){self.dimensions_string}"
                f",types=(string){self.types_string}"
            )
        if self.rate is not None:
            s += f",framerate={self.rate.numerator}/{self.rate.denominator}"
        return s

    def with_rate(self, rate) -> "TensorsSpec":
        return replace(self, rate=None if rate is None else Fraction(rate))

    def __str__(self) -> str:
        inner = ", ".join(str(t) for t in self.tensors)
        r = f" @{self.rate}fps" if self.rate is not None else ""
        return f"Tensors[{self.format.value}: {inner}{r}]"

    def __iter__(self):
        return iter(self.tensors)

    def __len__(self):
        return len(self.tensors)

    def __getitem__(self, i) -> TensorSpec:
        return self.tensors[i]


# Media ingress specs (what tensor_converter negotiates from;
# reference gsttensor_converter.c:1046-1270 media-type dispatch) are defined
# in elements/converter.py in terms of TensorsSpec.
