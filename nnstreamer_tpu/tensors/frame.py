"""Frame: the unit of data flowing through a pipeline.

TPU-native replacement for the reference's GstBuffer of 1..16 GstMemory
chunks (tensor_typedef.h:50-56, 220-224). Where the reference's
GstTensorMemory is a host pointer + size that every element maps/unmaps per
frame (tensor_filter.c:608-714), a Frame holds *device-resident*
``jax.Array``s directly — host copies happen only at converter/decoder
edges, and consecutive tensor-pure elements pass arrays without any copy.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.tensors.spec import DType, TensorsSpec

_frame_seq = itertools.count()

# Timestamps are integer nanoseconds (GStreamer GstClockTime convention).
NS = 1
US = 1_000
MS = 1_000_000
SECOND = 1_000_000_000
CLOCK_NONE: Optional[int] = None


@dataclass
class Frame:
    """One multi-tensor frame with stream timing and per-frame metadata.

    - ``tensors``: tuple of arrays (jax.Array on device in the hot path;
      numpy at host boundaries). Max 16, mirroring NNS_TENSOR_SIZE_LIMIT.
    - ``pts``/``duration``: presentation time in ns (None = unknown), used
      by mux/merge sync policies, aggregator, and rate elements.
    - ``meta``: free-form per-frame metadata. Key ``client_id`` mirrors the
      reference's GstMetaQuery (tensor_meta.h:26-31) for query-server
      demultiplexing; decoders/converters may add others.
    """

    tensors: Tuple[Any, ...]
    pts: Optional[int] = None
    duration: Optional[int] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    seq: int = field(default_factory=lambda: next(_frame_seq))
    # Sync fence state is per-Frame-object, NOT in meta: replace()-derived
    # frames share the meta dict, and a shared flag would mark sibling
    # frames (holding different, possibly still-executing tensors) synced.
    # init=False ⇒ every replace()-derived frame starts unsynced.
    _synced: bool = field(default=False, init=False, repr=False, compare=False)

    def __post_init__(self):
        self.tensors = tuple(self.tensors)

    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def spec(self, **kw) -> TensorsSpec:
        return TensorsSpec.from_arrays(self.tensors, **kw)

    def with_tensors(self, tensors: Sequence[Any]) -> "Frame":
        """New frame with same timing/meta but different payload (the common
        element output path — timing metadata rides along unchanged).

        Hand-rolled rather than dataclasses.replace(): this runs once per
        element per frame, and replace() pays __init__ + __post_init__
        dispatch (~7 µs) where direct attribute writes pay ~1 µs — at
        multi-kfps pipeline rates that difference is a measurable slice
        of the per-frame host budget. Semantics match replace(): meta is
        SHARED (same dict object), seq is fresh, _synced resets."""
        f = Frame.__new__(Frame)
        f.tensors = tuple(tensors)
        f.pts = self.pts
        f.duration = self.duration
        f.meta = self.meta
        f.seq = next(_frame_seq)
        f._synced = False
        return f

    def with_meta(self, **kw) -> "Frame":
        m = dict(self.meta)
        m.update(kw)
        return replace(self, meta=m)

    def with_pts(self, pts: Optional[int], duration: Optional[int] = None) -> "Frame":
        return replace(self, pts=pts, duration=duration if duration is not None else self.duration)

    def to_host(self) -> "Frame":
        """Materialize all tensors as numpy (egress boundary only)."""
        return self.with_tensors([np.asarray(t) for t in self.tensors])

    def to_device(self, device=None, sharding=None) -> "Frame":
        """Place all tensors on a device/sharding (ingress boundary)."""
        import jax

        target = sharding if sharding is not None else device
        if target is None:
            return self.with_tensors([jax.numpy.asarray(t) for t in self.tensors])
        return self.with_tensors([jax.device_put(t, target) for t in self.tensors])

    def block_until_ready(self) -> "Frame":
        # each block_until_ready costs a device round-trip even on finished
        # arrays (pronounced on remote-attached devices) — once a frame is
        # fenced, later calls are free
        if self._synced:
            return self
        for t in self.tensors:
            if hasattr(t, "block_until_ready"):
                t.block_until_ready()
        self._synced = True
        return self

    def mark_synced(self) -> "Frame":
        """Record that a later dispatch on the same device was fenced —
        in-order execution means this frame's compute is done too."""
        self._synced = True
        return self

    def prefetch_host(self) -> "Frame":
        """Start async device→host copies without blocking — lets a sink
        trail the device stream by a bounded window instead of paying a
        full sync round-trip per frame (Sink sync-window)."""
        for t in self.tensors:
            if hasattr(t, "copy_to_host_async"):
                t.copy_to_host_async()
        return self

    def __getitem__(self, i):
        return self.tensors[i]

    def __len__(self):
        return len(self.tensors)

    def __repr__(self):
        shapes = ",".join(
            f"{tuple(t.shape)}:{np.dtype(t.dtype).name}" for t in self.tensors
        )
        return f"Frame(seq={self.seq}, pts={self.pts}, [{shapes}])"


class EOS:
    """End-of-stream sentinel pushed through queues (GStreamer EOS event)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "EOS"


EOS_FRAME = EOS()
