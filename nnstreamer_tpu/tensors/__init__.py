"""Tensor type system: specs, dim strings, flexible meta headers, sparse codec.

TPU-native analogue of the reference's L1 layer
(gst/nnstreamer/include/tensor_typedef.h and
nnstreamer_plugin_api_util_impl.c).
"""

from nnstreamer_tpu.tensors.spec import (  # noqa: F401
    DType,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    NNS_TENSOR_SIZE_LIMIT,
    NNS_TENSOR_RANK_LIMIT,
)
from nnstreamer_tpu.tensors.frame import Frame  # noqa: F401
from nnstreamer_tpu.tensors.meta import FlexTensorMeta  # noqa: F401
